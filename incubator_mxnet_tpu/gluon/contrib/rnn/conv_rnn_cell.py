"""Convolutional recurrent cells (ref:
python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py — ConvRNN/ConvLSTM/ConvGRU
in 1/2/3 spatial dims, Shi et al. 2015).

One base implements all nine public cells: the gate pre-activations are
input and state convolutions (`nd.Convolution`, which lowers to a single
XLA conv HLO — the MXU path), and the mode picks the recurrence math.
Spatial dims are preserved: the i2h padding is caller-chosen and the h2h
kernel must be odd (implied same-padding), as in the reference.
"""
from __future__ import annotations

from .... import initializer as init_mod
from .... import ndarray as nd
from ...rnn.rnn_cell import RecurrentCell

__all__ = [
    "Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
    "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
    "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell",
]

_GATES = {"rnn": 1, "lstm": 4, "gru": 3}


def _tup(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _ConvRNNCellBase(RecurrentCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 mode, dims, i2h_pad=0, activation="tanh", prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_shape = tuple(input_shape)  # (C, *spatial)
        self._hc = hidden_channels
        self._dims = dims
        self._mode = mode
        self._act = activation
        self._i2h_kernel = _tup(i2h_kernel, dims)
        self._h2h_kernel = _tup(h2h_kernel, dims)
        if any(k % 2 == 0 for k in self._h2h_kernel):
            raise ValueError(
                f"h2h_kernel {self._h2h_kernel} must be odd so the hidden "
                f"state keeps its spatial shape (same as the reference)")
        self._i2h_pad = _tup(i2h_pad, dims)
        self._h2h_pad = tuple(k // 2 for k in self._h2h_kernel)
        # spatial shape after the input conv (stride 1, dilation 1)
        self._spatial = tuple(
            s + 2 * p - k + 1
            for s, k, p in zip(self._input_shape[1:], self._i2h_kernel,
                               self._i2h_pad))
        g = _GATES[mode]
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight",
                shape=(g * hidden_channels, self._input_shape[0])
                + self._i2h_kernel)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(g * hidden_channels, hidden_channels)
                + self._h2h_kernel)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(g * hidden_channels,),
                init=init_mod.Zero())
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(g * hidden_channels,),
                init=init_mod.Zero())

    def state_info(self, batch_size=0):
        shape = (batch_size, self._hc) + self._spatial
        n_states = 2 if self._mode == "lstm" else 1
        return [{"shape": shape} for _ in range(n_states)]

    def _convs(self, inputs, h):
        g = _GATES[self._mode] * self._hc
        pre_i = nd.Convolution(
            inputs, self.i2h_weight.data(), self.i2h_bias.data(),
            kernel=self._i2h_kernel, pad=self._i2h_pad, num_filter=g)
        pre_h = nd.Convolution(
            h, self.h2h_weight.data(), self.h2h_bias.data(),
            kernel=self._h2h_kernel, pad=self._h2h_pad, num_filter=g)
        return pre_i, pre_h

    def _activate(self, x):
        return getattr(nd, self._act)(x)

    def hybrid_forward(self, F, inputs, states, **kwargs):
        if self._mode == "rnn":
            pre_i, pre_h = self._convs(inputs, states[0])
            h_new = self._activate(pre_i + pre_h)
            return h_new, [h_new]
        if self._mode == "lstm":
            h, c = states
            pre_i, pre_h = self._convs(inputs, h)
            gates = pre_i + pre_h
            i, f, g, o = nd.split(gates, num_outputs=4, axis=1)
            c_new = nd.sigmoid(f) * c + nd.sigmoid(i) * self._activate(g)
            h_new = nd.sigmoid(o) * self._activate(c_new)
            return h_new, [h_new, c_new]
        # gru
        h = states[0]
        pre_i, pre_h = self._convs(inputs, h)
        ir, iz, inew = nd.split(pre_i, num_outputs=3, axis=1)
        hr, hz, hnew = nd.split(pre_h, num_outputs=3, axis=1)
        r = nd.sigmoid(ir + hr)
        z = nd.sigmoid(iz + hz)
        n = self._activate(inew + r * hnew)
        h_new = (1 - z) * n + z * h
        return h_new, [h_new]


def _make(mode, dims):
    gate_doc = {"rnn": "ConvRNN", "lstm": "ConvLSTM", "gru": "ConvGRU"}

    class Cell(_ConvRNNCellBase):
        def __init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, i2h_pad=0, activation="tanh", prefix=None,
                     params=None):
            super().__init__(input_shape, hidden_channels, i2h_kernel,
                             h2h_kernel, mode, dims, i2h_pad=i2h_pad,
                             activation=activation, prefix=prefix,
                             params=params)

    Cell.__name__ = f"Conv{dims}D{gate_doc[mode][4:]}Cell"
    Cell.__qualname__ = Cell.__name__
    Cell.__doc__ = (f"{dims}-D {gate_doc[mode]} cell "
                    f"(ref: conv_rnn_cell.py {Cell.__name__}).")
    return Cell


Conv1DRNNCell = _make("rnn", 1)
Conv2DRNNCell = _make("rnn", 2)
Conv3DRNNCell = _make("rnn", 3)
Conv1DLSTMCell = _make("lstm", 1)
Conv2DLSTMCell = _make("lstm", 2)
Conv3DLSTMCell = _make("lstm", 3)
Conv1DGRUCell = _make("gru", 1)
Conv2DGRUCell = _make("gru", 2)
Conv3DGRUCell = _make("gru", 3)
