"""Contrib recurrent cells
(ref: python/mxnet/gluon/contrib/rnn/rnn_cell.py)."""
from __future__ import annotations

from ...rnn.rnn_cell import LSTMCell, ModifierCell, RecurrentCell
from ... import parameter as _param  # noqa: F401  (init path parity)

__all__ = ["VariationalDropoutCell", "LSTMPCell"]


class VariationalDropoutCell(ModifierCell):
    """Variational (per-sequence, shared-across-time) dropout wrapper
    (ref: gluon/contrib/rnn/rnn_cell.py VariationalDropoutCell). The same
    dropout masks are sampled once per unroll and reused at every step —
    exactly the property that makes it XLA-friendly (masks are loop
    invariants the compiler keeps in registers/VMEM).
    """

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_masks = None
        self._output_mask = None

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_masks = None
        self._output_mask = None

    def _mask_like(self, p, arr):
        from .... import ndarray as nd

        keep = 1.0 - p
        mask = nd.random.uniform(shape=arr.shape) < keep
        return mask.astype("float32") / keep

    def hybrid_forward(self, F, inputs, states, **kwargs):
        from .... import autograd

        if not autograd.is_training():  # dropout is identity at inference,
            return self.base_cell(inputs, states)  # like the Dropout op
        if self.drop_inputs:
            if self._input_mask is None:
                self._input_mask = self._mask_like(self.drop_inputs, inputs)
            inputs = inputs * self._input_mask
        if self.drop_states:
            if self._state_masks is None:
                self._state_masks = [self._mask_like(self.drop_states, s)
                                     for s in states]
            states = [s * m for s, m in zip(states, self._state_masks)]
        out, nstates = self.base_cell(inputs, states)
        if self.drop_outputs:
            if self._output_mask is None:
                self._output_mask = self._mask_like(self.drop_outputs, out)
            out = out * self._output_mask
        return out, nstates


class LSTMPCell(RecurrentCell):
    """LSTM with a projection layer on the hidden state
    (ref: gluon/contrib/rnn/rnn_cell.py LSTMPCell — LSTMP from
    Sak et al. 2014). The projection matmul fuses into the recurrent
    matmuls on the MXU.
    """

    def __init__(self, hidden_size, projection_size, input_size=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        from .... import initializer as init_mod

        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, projection_size))
            self.h2r_weight = self.params.get(
                "h2r_weight", shape=(projection_size, hidden_size))
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,), init=init_mod.Zero())
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,), init=init_mod.Zero())

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size)},
                {"shape": (batch_size, self._hidden_size)}]

    def _pre_forward(self, x, states):
        if not self.i2h_weight._shape_known():
            self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, **kwargs):
        from .... import ndarray as nd

        r, c = states  # projected hidden, cell
        gates = (
            nd.FullyConnected(inputs, self.i2h_weight.data(),
                              self.i2h_bias.data(),
                              num_hidden=4 * self._hidden_size)
            + nd.FullyConnected(r, self.h2h_weight.data(),
                                self.h2h_bias.data(),
                                num_hidden=4 * self._hidden_size)
        )
        i, f, g, o = nd.SliceChannel(gates, num_outputs=4, axis=1)
        i = nd.Activation(i, act_type="sigmoid")
        f = nd.Activation(f, act_type="sigmoid")
        g = nd.Activation(g, act_type="tanh")
        o = nd.Activation(o, act_type="sigmoid")
        c_next = f * c + i * g
        h = o * nd.Activation(c_next, act_type="tanh")
        r_next = nd.FullyConnected(h, self.h2r_weight.data(), None,
                                   num_hidden=self._projection_size,
                                   no_bias=True)
        return r_next, [r_next, c_next]
