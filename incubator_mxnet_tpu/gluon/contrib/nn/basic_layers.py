"""Contrib basic layers
(ref: python/mxnet/gluon/contrib/nn/basic_layers.py)."""
from __future__ import annotations

import numpy as np

from ...block import HybridBlock
from ...nn.basic_layers import BatchNorm, Embedding, HybridSequential, Sequential

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle1D", "PixelShuffle2D", "PixelShuffle3D"]


class Concurrent(Sequential):
    """Run children on the same input, concat outputs along `axis`
    (ref: contrib/nn/basic_layers.py Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray as nd

        return nd.concat(*[block(x) for block in self._children.values()],
                         dim=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent (ref: contrib HybridConcurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        return F.Concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)


class Identity(HybridBlock):
    """(ref: contrib Identity)"""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Embedding):
    """Embedding with row-sparse gradient intent. On TPU the dense gather's
    VJP is already a scatter-add XLA fuses well, so this is Embedding with
    the reference's API (ref: contrib SparseEmbedding, gluon/nn Embedding
    sparse_grad=True)."""


class SyncBatchNorm(BatchNorm):
    """Cross-device batch normalization
    (ref: python/mxnet/gluon/contrib/nn/basic_layers.py SyncBatchNorm over
    src/operator/contrib/sync_batch_norm.cc).

    TPU-native semantics: under pjit with the batch axis sharded over the
    mesh, statistics are computed over the GLOBAL batch by construction (XLA
    inserts the cross-chip reductions), so this layer equals BatchNorm there.
    For shard_map per-replica programs pass `axis_name` to pmean the
    statistics across that mesh axis (the reference's num_devices group).
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True, use_global_stats=False,
                 axis_name=None, **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         in_channels=in_channels, **kwargs)
        self._axis_name = axis_name
        self._num_devices = num_devices

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F._contrib_SyncBatchNorm(
            x, gamma, beta, running_mean, running_var,
            eps=self._epsilon, momentum=self._momentum,
            fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats,
            ndev=self._num_devices or 1, axis_name=self._axis_name,
        )


class _PixelShuffle(HybridBlock):
    def __init__(self, factor, ndim):
        super().__init__()
        self._factor = ((factor,) * ndim if np.isscalar(factor)
                        else tuple(factor))
        self._ndim = ndim

    def hybrid_forward(self, F, x):
        # NDArray-level implementation via reshape/transpose ops
        f = self._factor
        shape = x.shape
        n, c = shape[0], shape[1]
        spatial = shape[2:]
        import math

        cf = math.prod(f)
        c_out = c // cf
        # (N, C_out, f1..fk, d1..dk) -> interleave -> (N, C_out, d1*f1, ...)
        x = F.reshape(x, shape=(n, c_out) + f + spatial)
        ndim = self._ndim
        perm = [0, 1]
        for i in range(ndim):
            perm += [2 + ndim + i, 2 + i]
        x = F.transpose(x, axes=tuple(perm))
        out_spatial = tuple(d * fi for d, fi in zip(spatial, f))
        return F.reshape(x, shape=(n, c_out) + out_spatial)


class PixelShuffle1D(_PixelShuffle):
    """(ref: contrib PixelShuffle1D)"""

    def __init__(self, factor):
        super().__init__(factor, 1)


class PixelShuffle2D(_PixelShuffle):
    """(ref: contrib PixelShuffle2D)"""

    def __init__(self, factor):
        super().__init__(factor, 2)


class PixelShuffle3D(_PixelShuffle):
    """(ref: contrib PixelShuffle3D)"""

    def __init__(self, factor):
        super().__init__(factor, 3)
