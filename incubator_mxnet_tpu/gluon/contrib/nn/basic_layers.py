"""Contrib basic layers
(ref: python/mxnet/gluon/contrib/nn/basic_layers.py)."""
from __future__ import annotations

import numpy as np

from ...block import HybridBlock
from ...nn.basic_layers import BatchNorm, Embedding, HybridSequential, Sequential

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle1D", "PixelShuffle2D", "PixelShuffle3D"]


class Concurrent(Sequential):
    """Run children on the same input, concat outputs along `axis`
    (ref: contrib/nn/basic_layers.py Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray as nd

        return nd.concat(*[block(x) for block in self._children.values()],
                         dim=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent (ref: contrib HybridConcurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        return F.Concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)


class Identity(HybridBlock):
    """(ref: contrib Identity)"""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Embedding):
    """Embedding with row-sparse gradients — and, given a
    ``ShardedEmbeddingService``, a table that lives ONLY on the PS shard
    fleet (ref: contrib SparseEmbedding over kvstore_dist row-sparse
    pull/push; the reference trains terascale tables this way).

    Local mode (``service=None``): the reference's contrib block —
    Embedding with ``sparse_grad=True``, engaging the lazy row-sparse
    optimizer paths.

    Remote mode (``service=`` a :class:`~incubator_mxnet_tpu.embedding.
    ShardedEmbeddingService`): no weight Parameter exists on this worker.
    The table is registered on the fleet (rows hash-sharded, initialized
    server-side), and each eager forward pulls only the batch's deduped,
    bucket-padded unique rows, gathers through ``F.Embedding`` (so the
    autograd tape records it), and — under ``autograd.record()`` — marks
    the pulled block as a variable whose backward gradient the service
    pushes back row-sparse. Worker-resident state is O(batch uniques),
    never O(vocab). Eager-only: the row set is host data, so this mode
    cannot be traced into a jit program.

    ``per_key=True`` selects the naive blocking one-RPC-per-table wire
    (the recommender bench's baseline); math is identical.
    """

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=True, service=None,
                 table=None, scale=0.05, seed=0, per_key=False, **kwargs):
        if service is None:
            self._remote = None
            super().__init__(input_dim, output_dim, dtype=dtype,
                             weight_initializer=weight_initializer,
                             sparse_grad=sparse_grad, **kwargs)
            return
        HybridBlock.__init__(self, **kwargs)
        self._input_dim = int(input_dim)
        self._output_dim = int(output_dim)
        self._service = service
        self._per_key = bool(per_key)
        self._remote = service.table(table or self.name, input_dim,
                                     output_dim, dtype=dtype, scale=scale,
                                     seed=seed)

    def prefetch(self, x):
        """Enqueue the pull for ids `x` on the service's background
        worker; the matching forward then only blocks on the unfinished
        remainder. No-op in local/per-key mode."""
        if self._remote is None or self._per_key:
            return
        self._service.prefetch([(self._remote.name,
                                 _host_ids(x))])

    def forward(self, x, *args):
        if self._remote is None:
            return super().forward(x, *args)

        from .... import autograd as _ag
        from .... import ndarray as nd
        from ....embedding import LEDGER_ROLE
        from ....telemetry import ledger as _ledger

        raw = _host_ids(x)
        if self._per_key:
            block, inv, n_uniq = self._service.pull_per_key(
                self._remote.name, raw)
        else:
            block, inv, n_uniq = self._remote.pull(raw)
        rows_nd = nd.array(block)
        _ledger.track(rows_nd, LEDGER_ROLE)
        if _ag.is_recording():
            _ag.mark_variables([rows_nd],
                               [nd.zeros(block.shape, dtype=block.dtype)])
            self._service.stash_grad(self._remote.name, np.unique(raw),
                                     rows_nd, n_uniq)
        out = nd.Embedding(nd.array(inv.astype(np.int32)), rows_nd,
                           input_dim=int(block.shape[0]),
                           output_dim=self._output_dim)
        return out.reshape(tuple(x.shape) + (self._output_dim,))


def _host_ids(x):
    """Flatten an id batch (NDArray or array-like) to host int64."""
    x = x.asnumpy() if hasattr(x, "asnumpy") else x
    return np.asarray(x, np.int64).reshape(-1)


class SyncBatchNorm(BatchNorm):
    """Cross-device batch normalization
    (ref: python/mxnet/gluon/contrib/nn/basic_layers.py SyncBatchNorm over
    src/operator/contrib/sync_batch_norm.cc).

    TPU-native semantics: under pjit with the batch axis sharded over the
    mesh, statistics are computed over the GLOBAL batch by construction (XLA
    inserts the cross-chip reductions), so this layer equals BatchNorm there.
    For shard_map per-replica programs pass `axis_name` to pmean the
    statistics across that mesh axis (the reference's num_devices group).
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True, use_global_stats=False,
                 axis_name=None, **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         in_channels=in_channels, **kwargs)
        self._axis_name = axis_name
        self._num_devices = num_devices

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F._contrib_SyncBatchNorm(
            x, gamma, beta, running_mean, running_var,
            eps=self._epsilon, momentum=self._momentum,
            fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats,
            ndev=self._num_devices or 1, axis_name=self._axis_name,
        )


class _PixelShuffle(HybridBlock):
    def __init__(self, factor, ndim):
        super().__init__()
        self._factor = ((factor,) * ndim if np.isscalar(factor)
                        else tuple(factor))
        self._ndim = ndim

    def hybrid_forward(self, F, x):
        # NDArray-level implementation via reshape/transpose ops
        f = self._factor
        shape = x.shape
        n, c = shape[0], shape[1]
        spatial = shape[2:]
        import math

        cf = math.prod(f)
        c_out = c // cf
        # (N, C_out, f1..fk, d1..dk) -> interleave -> (N, C_out, d1*f1, ...)
        x = F.reshape(x, shape=(n, c_out) + f + spatial)
        ndim = self._ndim
        perm = [0, 1]
        for i in range(ndim):
            perm += [2 + ndim + i, 2 + i]
        x = F.transpose(x, axes=tuple(perm))
        out_spatial = tuple(d * fi for d, fi in zip(spatial, f))
        return F.reshape(x, shape=(n, c_out) + out_spatial)


class PixelShuffle1D(_PixelShuffle):
    """(ref: contrib PixelShuffle1D)"""

    def __init__(self, factor):
        super().__init__(factor, 1)


class PixelShuffle2D(_PixelShuffle):
    """(ref: contrib PixelShuffle2D)"""

    def __init__(self, factor):
        super().__init__(factor, 2)


class PixelShuffle3D(_PixelShuffle):
    """(ref: contrib PixelShuffle3D)"""

    def __init__(self, factor):
        super().__init__(factor, 3)
