"""Contrib samplers (ref: python/mxnet/gluon/contrib/data/sampler.py)."""
from __future__ import annotations

from ...data.sampler import Sampler

__all__ = ["IntervalSampler"]


class IntervalSampler(Sampler):
    """Visit [0, length) at a fixed stride, rolling through the offsets
    (ref: contrib/data/sampler.py:25 — IntervalSampler(13, 3) yields
    0,3,6,9,12,1,4,7,10,2,5,8,11)."""

    def __init__(self, length, interval, rollover=True):
        if not 0 < interval <= length:
            raise ValueError(
                f"interval {interval} must be in [1, length={length}]")
        self._length = int(length)
        self._interval = int(interval)
        self._rollover = bool(rollover)

    def __iter__(self):
        offsets = range(self._interval) if self._rollover else [0]
        for start in offsets:
            yield from range(start, self._length, self._interval)

    def __len__(self):
        if self._rollover:
            return self._length
        return len(range(0, self._length, self._interval))
