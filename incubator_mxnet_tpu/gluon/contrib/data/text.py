"""Language-model datasets
(ref: python/mxnet/gluon/contrib/data/text.py — WikiText2/WikiText103:
tokenized corpus -> (seq_len,) data/label pairs shifted by one).

Zero-egress adaptation: the reference downloads the corpora; here a local
`root` containing `wiki.{train,valid,test}.tokens` is used when present,
otherwise a deterministic synthetic token stream with a Zipfian unigram
distribution stands in (same tensor shapes/vocab machinery, so pipelines
exercise identically — swap in the real files to train on WikiText).
"""
from __future__ import annotations

import os
import zlib

import numpy as np

from ....contrib.text import Vocabulary
from ...data.dataset import Dataset

__all__ = ["WikiText2", "WikiText103"]


def _synthetic_tokens(n_tokens, vocab_size, seed):
    """Zipf-distributed pseudo-corpus: token ids as whitespace words."""
    rng = np.random.RandomState(seed)
    ranks = np.arange(1, vocab_size + 1)
    p = 1.0 / ranks
    p /= p.sum()
    ids = rng.choice(vocab_size, size=n_tokens, p=p)
    return [f"w{i}" for i in ids]


class _WikiText(Dataset):
    _namespace = None
    _synthetic_sizes = {"train": 60000, "val": 6000, "test": 6000}
    _synthetic_vocab = 800

    def __init__(self, root=None, segment="train", vocab=None, seq_len=35):
        self._seq_len = int(seq_len)
        tokens = self._load(root, segment)
        self._vocab = vocab or Vocabulary(
            self._count(tokens), reserved_tokens=["<eos>"])
        ids = np.asarray(self._vocab.to_indices(tokens), np.int32)
        n = (len(ids) - 1) // self._seq_len * self._seq_len
        self._data = ids[:n].reshape(-1, self._seq_len)
        self._label = ids[1:n + 1].reshape(-1, self._seq_len)

    @staticmethod
    def _count(tokens):
        from collections import Counter

        return Counter(tokens)

    def _load(self, root, segment):
        seg_file = {"train": "wiki.train.tokens", "val": "wiki.valid.tokens",
                    "validation": "wiki.valid.tokens",
                    "test": "wiki.test.tokens"}[segment]
        if root:
            path = os.path.join(root, seg_file)
            if not os.path.exists(path):
                # an explicit root must never silently train on fake data
                raise FileNotFoundError(
                    f"{path} not found; pass root=None for the synthetic "
                    "stand-in corpus")
            with open(path, encoding="utf-8") as f:
                out = []
                for line in f:
                    out.extend(line.split())
                    out.append("<eos>")
                return out
        key = "val" if segment in ("val", "validation") else segment
        # crc32, not hash(): the synthetic corpus must be identical across
        # processes (hash() is salted per interpreter)
        seed = zlib.crc32(f"{self._namespace}/{key}".encode()) % (2 ** 31)
        return _synthetic_tokens(
            self._synthetic_sizes[key], self._synthetic_vocab, seed)

    @property
    def vocab(self):
        return self._vocab

    def __getitem__(self, idx):
        return self._data[idx], self._label[idx]

    def __len__(self):
        return self._data.shape[0]


class WikiText2(_WikiText):
    """(ref: contrib/data/text.py:105)."""

    _namespace = "wikitext-2"


class WikiText103(_WikiText):
    """(ref: contrib/data/text.py:143)."""

    _namespace = "wikitext-103"
    _synthetic_sizes = {"train": 200000, "val": 8000, "test": 8000}
    _synthetic_vocab = 2000
