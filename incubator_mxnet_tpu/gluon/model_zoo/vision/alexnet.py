"""AlexNet, spec-driven (Krizhevsky et al. 2012; capability parity with
python/mxnet/gluon/model_zoo/vision/alexnet.py, expressed as a flat layer
table like the rest of this zoo)."""
from ...block import HybridBlock
from ... import nn

__all__ = ["AlexNet", "alexnet"]

# (channels, kernel, stride, padding, pool-after?)
_CONV_PLAN = ((64, 11, 4, 2, True),
              (192, 5, 1, 2, True),
              (384, 3, 1, 1, False),
              (256, 3, 1, 1, False),
              (256, 3, 1, 1, True))


class AlexNet(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            feats = nn.HybridSequential(prefix="")
            for ch, k, s, p, pool in _CONV_PLAN:
                feats.add(nn.Conv2D(ch, kernel_size=k, strides=s, padding=p,
                                    activation="relu"))
                if pool:
                    feats.add(nn.MaxPool2D(pool_size=3, strides=2))
            feats.add(nn.Flatten())
            for _ in range(2):  # the two 4096-wide dropout-regularized FCs
                feats.add(nn.Dense(4096, activation="relu"))
                feats.add(nn.Dropout(0.5))
            self.features = feats
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def alexnet(pretrained=False, ctx=None, root=None, **kwargs):
    net = AlexNet(**kwargs)
    if pretrained:
        from ..model_store import get_model_file

        net.load_parameters(get_model_file("alexnet", root), ctx=ctx)
    return net
