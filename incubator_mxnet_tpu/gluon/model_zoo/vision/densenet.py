"""DenseNet 121/161/169/201, motif-driven.

Architectures per Huang et al. 1608.06993. Capability parity with the
reference zoo (ref: python/mxnet/gluon/model_zoo/vision/densenet.py),
re-expressed in this framework's idiom: DenseNet is three repetitions of a
single BN->relu->conv motif — the bottleneck pair inside a dense layer, the
1x1 in a transition, and the final head — so `_bn_relu_conv` is the one
building block and everything else is wiring plus the channel arithmetic.
"""
from functools import partial

from ...block import HybridBlock
from ... import nn

__all__ = ["DenseNet", "densenet_spec", "densenet121", "densenet161",
           "densenet169", "densenet201"]

# depth -> (stem channels, growth rate, layers per dense block)
densenet_spec = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
}


def _bn_relu_conv(seq, channels, kernel, padding=0):
    """The DenseNet motif: pre-activation conv appended to `seq`."""
    seq.add(nn.BatchNorm())
    seq.add(nn.Activation("relu"))
    seq.add(nn.Conv2D(channels, kernel_size=kernel, padding=padding,
                      use_bias=False))


class _DenseLayer(HybridBlock):
    """Bottleneck (1x1 to bn_size*k, then 3x3 to k) whose output is
    concatenated onto its input along channels."""

    def __init__(self, growth_rate, bn_size, dropout, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        _bn_relu_conv(self.body, bn_size * growth_rate, kernel=1)
        _bn_relu_conv(self.body, growth_rate, kernel=3, padding=1)
        if dropout:
            self.body.add(nn.Dropout(dropout))

    def hybrid_forward(self, F, x):
        return F.Concat(x, self.body(x), dim=1)


class DenseNet(HybridBlock):
    """Stem -> [dense block -> halving transition]* -> head."""

    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            feats = nn.HybridSequential(prefix="")
            feats.add(nn.Conv2D(num_init_features, kernel_size=7, strides=2,
                                padding=3, use_bias=False))
            feats.add(nn.BatchNorm())
            feats.add(nn.Activation("relu"))
            feats.add(nn.MaxPool2D(pool_size=3, strides=2, padding=1))
            channels = num_init_features
            for i, n_layers in enumerate(block_config):
                block = nn.HybridSequential(prefix=f"stage{i + 1}_")
                with block.name_scope():
                    for _ in range(n_layers):
                        block.add(_DenseLayer(growth_rate, bn_size, dropout))
                feats.add(block)
                channels += n_layers * growth_rate
                if i + 1 < len(block_config):
                    channels //= 2  # transition halves the channel count
                    _bn_relu_conv(feats, channels, kernel=1)
                    feats.add(nn.AvgPool2D(pool_size=2, strides=2))
            feats.add(nn.BatchNorm())
            feats.add(nn.Activation("relu"))
            feats.add(nn.AvgPool2D(pool_size=7))
            feats.add(nn.Flatten())
            self.features = feats
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def _get_densenet(depth, pretrained=False, ctx=None, root=None, **kwargs):
    stem, growth, blocks = densenet_spec[depth]
    net = DenseNet(stem, growth, blocks, **kwargs)
    if pretrained:
        from ..model_store import get_model_file

        net.load_parameters(get_model_file(f"densenet{depth}", root), ctx=ctx)
    return net


for _d in densenet_spec:
    _fn = partial(_get_densenet, _d)
    _fn.__name__ = f"densenet{_d}"
    _fn.__doc__ = f"DenseNet-{_d} (see densenet_spec)."
    globals()[f"densenet{_d}"] = _fn
