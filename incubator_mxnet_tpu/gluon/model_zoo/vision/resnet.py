"""ResNet v1/v2 model zoo, config-driven.

Architectures per He et al. (1512.03385 residual networks, 1603.05027
pre-activation variant). Capability parity with the reference's model zoo
(ref: python/mxnet/gluon/model_zoo/vision/resnet.py), re-expressed in this
framework's idiom: one parameterized residual unit driven by a declarative
conv plan instead of four hand-written block classes, and one ResNet class
covering both the post-activation (v1) and pre-activation (v2) orderings.
"""
from __future__ import annotations

from functools import partial

from ...block import HybridBlock
from ... import nn

__all__ = ["ResNet", "ResidualUnit", "get_resnet", "resnet_spec",
           "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
           "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
           "resnet101_v2", "resnet152_v2"]


# depth -> (bottleneck?, units per stage, channels per stage)
resnet_spec = {
    18: (False, (2, 2, 2, 2), (64, 64, 128, 256, 512)),
    34: (False, (3, 4, 6, 3), (64, 64, 128, 256, 512)),
    50: (True, (3, 4, 6, 3), (64, 256, 512, 1024, 2048)),
    101: (True, (3, 4, 23, 3), (64, 256, 512, 1024, 2048)),
    152: (True, (3, 8, 36, 3), (64, 256, 512, 1024, 2048)),
}


def _conv_plan(channels, stride, bottleneck, version):
    """Declarative conv stack for one residual unit:
    (out_channels, kernel, stride, padding, use_bias) per conv.

    The stride placement matches the reference zoo: v1 bottlenecks stride on
    the first 1x1 (torch-style), v2 bottlenecks stride on the 3x3."""
    if not bottleneck:
        return ((channels, 3, stride, 1, False),
                (channels, 3, 1, 1, False))
    mid = channels // 4
    if version == 1:
        return ((mid, 1, stride, 0, True),
                (mid, 3, 1, 1, False),
                (channels, 1, 1, 0, True))
    return ((mid, 1, 1, 0, False),
            (mid, 3, stride, 1, False),
            (channels, 1, 1, 0, False))


class ResidualUnit(HybridBlock):
    """One residual unit, v1 or v2 ordering.

    v1 (post-activation):  out = relu(x + bn(conv(...relu(bn(conv(x))))))
                           identity branch: 1x1-conv + BN when downsampling
    v2 (pre-activation):   h = relu(bn(x)); out = x' + conv(...relu(bn(conv(h))))
                           identity branch: 1x1-conv of h, no BN
    """

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 version=1, bottleneck=False, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        self._version = version
        bn_axis = -1 if layout == "NHWC" else 1
        plan = _conv_plan(channels, stride, bottleneck, version)
        # v1: norms[i] FOLLOWS convs[i]; v2: norms[i] PRECEDES convs[i]
        self.convs = nn.HybridSequential(prefix="")
        self.norms = nn.HybridSequential(prefix="")
        for c, k, s, p, bias in plan:
            self.convs.add(nn.Conv2D(c, kernel_size=k, strides=s, padding=p,
                                     use_bias=bias, layout=layout))
            self.norms.add(nn.BatchNorm(axis=bn_axis))
        if not downsample:
            self.proj = None
            self.proj_norm = None
        else:
            self.proj = nn.Conv2D(channels, kernel_size=1, strides=stride,
                                  use_bias=False, in_channels=in_channels,
                                  layout=layout)
            self.proj_norm = nn.BatchNorm(axis=bn_axis) if version == 1 else None

    def hybrid_forward(self, F, x):
        convs = [self.convs[i] for i in range(len(self.convs))]
        norms = [self.norms[i] for i in range(len(self.norms))]
        if self._version == 1:
            h = x
            for i, conv in enumerate(convs):
                h = norms[i](conv(h))
                if i < len(convs) - 1:
                    h = F.Activation(h, act_type="relu")
            skip = x if self.proj is None else self.proj_norm(self.proj(x))
            return F.Activation(skip + h, act_type="relu")
        # v2: BN+relu precede each conv; the first pre-activation also
        # feeds the projection shortcut
        h = x
        skip = x
        for i, conv in enumerate(convs):
            h = F.Activation(norms[i](h), act_type="relu")
            if i == 0 and self.proj is not None:
                skip = self.proj(h)
            h = conv(h)
        return skip + h


class ResNet(HybridBlock):
    """Stage-configured ResNet for both orderings.

    `thumbnail=True` swaps the 7x7/maxpool ImageNet stem for a single 3x3
    (the CIFAR stem), as in the reference zoo.
    """

    def __init__(self, version, layers, channels, bottleneck, classes=1000,
                 thumbnail=False, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        assert version in (1, 2)
        assert layout in ("NCHW", "NHWC")
        bn_axis = -1 if layout == "NHWC" else 1
        with self.name_scope():
            feats = nn.HybridSequential(prefix="")
            if version == 2:
                feats.add(nn.BatchNorm(scale=False, center=False,
                                       axis=bn_axis))
            if thumbnail:
                feats.add(nn.Conv2D(channels[0], kernel_size=3, strides=1,
                                    padding=1, use_bias=False, layout=layout))
            else:
                feats.add(nn.Conv2D(channels[0], 7, 2, 3, use_bias=False,
                                    layout=layout))
                feats.add(nn.BatchNorm(axis=bn_axis))
                feats.add(nn.Activation("relu"))
                feats.add(nn.MaxPool2D(3, 2, 1, layout=layout))
            in_c = channels[0]
            for i, n_units in enumerate(layers):
                stage = nn.HybridSequential(prefix=f"stage{i + 1}_")
                with stage.name_scope():
                    for j in range(n_units):
                        stride = 2 if (j == 0 and i > 0) else 1
                        stage.add(ResidualUnit(
                            channels[i + 1], stride,
                            downsample=(j == 0 and channels[i + 1] != in_c),
                            in_channels=in_c, version=version,
                            bottleneck=bottleneck, layout=layout, prefix=""))
                        in_c = channels[i + 1]
                feats.add(stage)
            if version == 2:
                feats.add(nn.BatchNorm(axis=bn_axis))
                feats.add(nn.Activation("relu"))
            feats.add(nn.GlobalAvgPool2D(layout=layout))
            feats.add(nn.Flatten())
            self.features = feats
            self.output = nn.Dense(classes, in_units=channels[-1])

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def get_resnet(version, num_layers, pretrained=False, ctx=None, root=None,
               **kwargs):
    """(ref: resnet.py get_resnet — same (version, depth) addressing)"""
    if num_layers not in resnet_spec:
        raise ValueError(
            f"unsupported depth {num_layers}; pick from {sorted(resnet_spec)}")
    bottleneck, layers, channels = resnet_spec[num_layers]
    net = ResNet(version, layers, channels, bottleneck, **kwargs)
    if pretrained:
        from ..model_store import get_model_file

        net.load_parameters(
            get_model_file(f"resnet{num_layers}_v{version}", root), ctx=ctx)
    return net


def _register_factories():
    for depth in resnet_spec:
        for version in (1, 2):
            name = f"resnet{depth}_v{version}"
            fn = partial(get_resnet, version, depth)
            fn.__name__ = name
            fn.__doc__ = f"ResNet-{depth} v{version} (see get_resnet)."
            globals()[name] = fn


_register_factories()
