"""Inception V3 (Szegedy et al. 1512.00567; capability parity with
python/mxnet/gluon/model_zoo/vision/inception.py).

Fully declarative: every inception block is a list of branch specs in a
tiny DSL — ("conv", ch, kernel, stride, pad), ("avgpool",), ("maxpool",),
and ("split", stem, b1, b2) for the fanned-out 3x3 factorizations — and a
single builder turns specs into blocks. The whole architecture is the
`_STEM` + `_TOWERS` tables below.
"""
from ...block import HybridBlock
from ... import nn

__all__ = ["Inception3", "inception_v3"]


def _cbr(channels, kernel, stride=1, pad=0):
    """conv(no bias) -> BN(eps 1e-3) -> relu, the basic inception unit."""
    seq = nn.HybridSequential(prefix="")
    seq.add(nn.Conv2D(channels, kernel_size=kernel, strides=stride,
                      padding=pad, use_bias=False))
    seq.add(nn.BatchNorm(epsilon=0.001))
    seq.add(nn.Activation("relu"))
    return seq


def _build_branch(spec):
    seq = nn.HybridSequential(prefix="")
    for step in spec:
        kind = step[0]
        if kind == "conv":
            _, ch, k, s, p = step
            seq.add(_cbr(ch, k, s, p))
        elif kind == "avgpool":
            seq.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
        elif kind == "maxpool":
            seq.add(nn.MaxPool2D(pool_size=3, strides=2))
        else:
            raise ValueError(step)
    return seq


class _Fanout(HybridBlock):
    """('split', stem, b1, b2): stem -> concat(b1(stem), b2(stem))."""

    def __init__(self, stem, b1, b2, **kwargs):
        super().__init__(**kwargs)
        self.stem = _build_branch(stem)
        self.b1 = _build_branch(b1)
        self.b2 = _build_branch(b2)

    def hybrid_forward(self, F, x):
        h = self.stem(x)
        return F.Concat(self.b1(h), self.b2(h), dim=1)


class _Tower(HybridBlock):
    """Parallel branches concatenated on channels."""

    def __init__(self, branch_specs, **kwargs):
        super().__init__(**kwargs)
        for spec in branch_specs:
            if spec and spec[0][0] == "split":
                self.register_child(_Fanout(*spec[0][1:]))
            else:
                self.register_child(_build_branch(spec))

    def hybrid_forward(self, F, x):
        return F.Concat(*[b(x) for b in self._children.values()], dim=1)


def _conv(ch, k, s=1, p=0):
    return ("conv", ch, k, s, p)


def _block_a(pool_ch):
    return [
        [_conv(64, 1)],
        [_conv(48, 1), _conv(64, 5, 1, 2)],
        [_conv(64, 1), _conv(96, 3, 1, 1), _conv(96, 3, 1, 1)],
        [("avgpool",), _conv(pool_ch, 1)],
    ]


def _block_c(c7):
    return [
        [_conv(192, 1)],
        [_conv(c7, 1), _conv(c7, (1, 7), 1, (0, 3)),
         _conv(192, (7, 1), 1, (3, 0))],
        [_conv(c7, 1), _conv(c7, (7, 1), 1, (3, 0)),
         _conv(c7, (1, 7), 1, (0, 3)), _conv(c7, (7, 1), 1, (3, 0)),
         _conv(192, (1, 7), 1, (0, 3))],
        [("avgpool",), _conv(192, 1)],
    ]


def _block_e():
    split1 = ("split", [_conv(384, 1)],
              [_conv(384, (1, 3), 1, (0, 1))], [_conv(384, (3, 1), 1, (1, 0))])
    split2 = ("split", [_conv(448, 1), _conv(384, 3, 1, 1)],
              [_conv(384, (1, 3), 1, (0, 1))], [_conv(384, (3, 1), 1, (1, 0))])
    return [
        [_conv(320, 1)],
        [split1],
        [split2],
        [("avgpool",), _conv(192, 1)],
    ]


_REDUCE_B = [
    [_conv(384, 3, 2)],
    [_conv(64, 1), _conv(96, 3, 1, 1), _conv(96, 3, 2)],
    [("maxpool",)],
]

_REDUCE_D = [
    [_conv(192, 1), _conv(320, 3, 2)],
    [_conv(192, 1), _conv(192, (1, 7), 1, (0, 3)),
     _conv(192, (7, 1), 1, (3, 0)), _conv(192, 3, 2)],
    [("maxpool",)],
]


class Inception3(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        towers = ([_block_a(32), _block_a(64), _block_a(64), _REDUCE_B]
                  + [_block_c(c) for c in (128, 160, 160, 192)]
                  + [_REDUCE_D, _block_e(), _block_e()])
        with self.name_scope():
            feats = nn.HybridSequential(prefix="")
            feats.add(_cbr(32, 3, 2))
            feats.add(_cbr(32, 3))
            feats.add(_cbr(64, 3, 1, 1))
            feats.add(nn.MaxPool2D(pool_size=3, strides=2))
            feats.add(_cbr(80, 1))
            feats.add(_cbr(192, 3))
            feats.add(nn.MaxPool2D(pool_size=3, strides=2))
            for i, specs in enumerate(towers):
                feats.add(_Tower(specs, prefix=f"tower{i}_"))
            feats.add(nn.AvgPool2D(pool_size=8))
            feats.add(nn.Dropout(0.5))
            self.features = feats
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def inception_v3(pretrained=False, ctx=None, root=None, **kwargs):
    net = Inception3(**kwargs)
    if pretrained:
        from ..model_store import get_model_file

        net.load_parameters(get_model_file("inceptionv3", root), ctx=ctx)
    return net
