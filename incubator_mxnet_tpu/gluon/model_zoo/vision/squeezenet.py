"""SqueezeNet 1.0/1.1 (Iandola et al. 1602.07360; capability parity with
python/mxnet/gluon/model_zoo/vision/squeezenet.py).

Spec-driven: each version is a flat plan mixing fire-module squeeze widths
and pool markers; the fire module itself is one block (squeeze 1x1 ->
parallel 1x1/3x3 expands, concatenated).
"""
from ...block import HybridBlock
from ... import nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]

# plans: "P" = 3x3/2 ceil maxpool; int s = fire module with squeeze width s
# (expands are always 4s + 4s, per the paper's table)
_PLANS = {
    "1.0": (96, 7, 2, ["P", 16, 16, 32, "P", 32, 48, 48, 64, "P", 64]),
    "1.1": (64, 3, 2, ["P", 16, 16, "P", 32, 32, "P", 48, 48, 64, 64]),
}


class Fire(HybridBlock):
    """squeeze 1x1 -> concat(expand 1x1, expand 3x3), all relu."""

    def __init__(self, squeeze, **kwargs):
        super().__init__(**kwargs)
        expand = 4 * squeeze
        with self.name_scope():
            self.squeeze = nn.Conv2D(squeeze, 1, activation="relu")
            self.left = nn.Conv2D(expand, 1)
            self.right = nn.Conv2D(expand, 3, padding=1)

    def hybrid_forward(self, F, x):
        s = self.squeeze(x)
        return F.Concat(F.Activation(self.left(s), act_type="relu"),
                        F.Activation(self.right(s), act_type="relu"), dim=1)


class SqueezeNet(HybridBlock):
    def __init__(self, version, classes=1000, **kwargs):
        super().__init__(**kwargs)
        if version not in _PLANS:
            raise ValueError(f"version must be one of {sorted(_PLANS)}")
        stem_ch, stem_k, stem_s, plan = _PLANS[version]
        with self.name_scope():
            feats = nn.HybridSequential(prefix="")
            feats.add(nn.Conv2D(stem_ch, kernel_size=stem_k, strides=stem_s,
                                activation="relu"))
            for item in plan:
                if item == "P":
                    feats.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
                else:
                    feats.add(Fire(item))
            feats.add(nn.Dropout(0.5))
            self.features = feats
            head = nn.HybridSequential(prefix="")
            head.add(nn.Conv2D(classes, kernel_size=1))
            head.add(nn.Activation("relu"))
            head.add(nn.GlobalAvgPool2D())
            head.add(nn.Flatten())
            self.output = head

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def _get(version, pretrained=False, ctx=None, root=None, **kwargs):
    net = SqueezeNet(version, **kwargs)
    if pretrained:
        from ..model_store import get_model_file

        net.load_parameters(get_model_file(f"squeezenet{version}", root),
                            ctx=ctx)
    return net


def squeezenet1_0(**kwargs):
    return _get("1.0", **kwargs)


def squeezenet1_1(**kwargs):
    return _get("1.1", **kwargs)
