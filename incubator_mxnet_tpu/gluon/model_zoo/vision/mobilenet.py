"""MobileNet v1/v2, spec-table driven.

Architectures per Howard et al. 1704.04861 (v1, depthwise-separable stacks)
and Sandler et al. 1801.04381 (v2, inverted residuals). Capability parity
with the reference zoo (ref: python/mxnet/gluon/model_zoo/vision/
mobilenet.py), re-expressed in this framework's idiom: each network is a
flat spec table — v1 rows are (out_channels, stride) separable units, v2
rows are (expansion, out_channels, stride, repeats) bottleneck groups — and
a single `_cba` (conv-BN-activation) helper is the only conv constructor in
the file. Width multipliers are applied when reading the table, not baked
into per-variant classes.
"""
from functools import partial

from ...block import HybridBlock
from ... import nn

__all__ = ["MobileNet", "MobileNetV2", "mobilenet1_0", "mobilenet0_75",
           "mobilenet0_5", "mobilenet0_25", "mobilenet_v2_1_0",
           "mobilenet_v2_0_75", "mobilenet_v2_0_5", "mobilenet_v2_0_25"]

# v1: (out_channels, stride) per depthwise-separable unit
V1_SPEC = ((64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
           (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
           (1024, 1))

# v2: (expansion t, out_channels, stride, repeats) per bottleneck group
V2_SPEC = ((1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 2, 3), (6, 64, 2, 4),
           (6, 96, 1, 3), (6, 160, 2, 3), (6, 320, 1, 1))


class _ReLU6(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.clip(x, a_min=0.0, a_max=6.0)


def _cba(seq, channels, kernel=1, stride=1, pad=0, groups=1, act="relu"):
    """conv -> BN -> activation; act in {'relu', 'relu6', None}."""
    seq.add(nn.Conv2D(channels, kernel, stride, pad, groups=groups,
                      use_bias=False))
    seq.add(nn.BatchNorm(scale=True))
    if act == "relu":
        seq.add(nn.Activation("relu"))
    elif act == "relu6":
        seq.add(_ReLU6())


class InvertedResidual(HybridBlock):
    """v2 unit: 1x1 expand -> 3x3 depthwise -> linear 1x1 project, with an
    identity shortcut when the unit preserves shape."""

    def __init__(self, in_channels, channels, t, stride, **kwargs):
        super().__init__(**kwargs)
        self._shortcut = stride == 1 and in_channels == channels
        mid = in_channels * t
        with self.name_scope():
            self.body = nn.HybridSequential()
            _cba(self.body, mid, act="relu6")
            _cba(self.body, mid, kernel=3, stride=stride, pad=1, groups=mid,
                 act="relu6")
            _cba(self.body, channels, act=None)

    def hybrid_forward(self, F, x):
        out = self.body(x)
        return out + x if self._shortcut else out


# keep the reference zoo's class name for the v2 unit
LinearBottleneck = InvertedResidual


class MobileNet(HybridBlock):
    """v1: stem + a stack of depthwise-separable units from V1_SPEC."""

    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        scale = lambda c: int(c * multiplier)  # noqa: E731
        with self.name_scope():
            feats = nn.HybridSequential(prefix="")
            _cba(feats, scale(32), kernel=3, stride=2, pad=1)
            in_c = scale(32)
            for out_c, stride in V1_SPEC:
                # depthwise 3x3 on in_c channels, then pointwise to out_c
                _cba(feats, in_c, kernel=3, stride=stride, pad=1, groups=in_c)
                _cba(feats, scale(out_c))
                in_c = scale(out_c)
            feats.add(nn.GlobalAvgPool2D())
            feats.add(nn.Flatten())
            self.features = feats
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


class MobileNetV2(HybridBlock):
    """v2: stem + inverted-residual groups from V2_SPEC + 1280-wide head
    with a 1x1-conv classifier."""

    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        scale = lambda c: int(c * multiplier)  # noqa: E731
        with self.name_scope():
            feats = nn.HybridSequential(prefix="features_")
            with feats.name_scope():
                _cba(feats, scale(32), kernel=3, stride=2, pad=1, act="relu6")
                in_c = scale(32)
                for t, out_c, stride, repeats in V2_SPEC:
                    for j in range(repeats):
                        feats.add(InvertedResidual(
                            in_c, scale(out_c), t, stride if j == 0 else 1))
                        in_c = scale(out_c)
                head = int(1280 * multiplier) if multiplier > 1.0 else 1280
                _cba(feats, head, act="relu6")
                feats.add(nn.GlobalAvgPool2D())
            self.features = feats
            self.output = nn.HybridSequential(prefix="output_")
            with self.output.name_scope():
                self.output.add(nn.Conv2D(classes, 1, use_bias=False,
                                          prefix="pred_"),
                                nn.Flatten())

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def _get(cls, multiplier, pretrained=False, ctx=None, root=None, **kwargs):
    net = cls(multiplier, **kwargs)
    if pretrained:
        from ..model_store import get_model_file

        base = "mobilenetv2_" if cls is MobileNetV2 else "mobilenet"
        net.load_parameters(
            get_model_file(f"{base}{multiplier}", root), ctx=ctx)
    return net


for _m, _tag in ((1.0, "1_0"), (0.75, "0_75"), (0.5, "0_5"), (0.25, "0_25")):
    for _cls, _name in ((MobileNet, f"mobilenet{_tag}"),
                        (MobileNetV2, f"mobilenet_v2_{_tag}")):
        _fn = partial(_get, _cls, _m)
        _fn.__name__ = _name
        _fn.__doc__ = f"{_cls.__name__} with width multiplier {_m}."
        globals()[_name] = _fn
