"""VGG 11/13/16/19 with optional BatchNorm (Simonyan & Zisserman 1409.1556;
capability parity with python/mxnet/gluon/model_zoo/vision/vgg.py).

Spec-driven like the rest of this zoo: each depth is a tuple of per-stage
conv repeat counts over the fixed 64->512 channel ladder; the `_bn`
variants are generated from the same table.
"""
from functools import partial

from ...block import HybridBlock
from ... import nn

__all__ = ["VGG", "vgg_spec", "get_vgg", "vgg11", "vgg13", "vgg16", "vgg19",
           "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn"]

_CHANNELS = (64, 128, 256, 512, 512)
# depth -> conv repeats per stage (stages always end in a stride-2 maxpool)
vgg_spec = {11: (1, 1, 2, 2, 2),
            13: (2, 2, 2, 2, 2),
            16: (2, 2, 3, 3, 3),
            19: (2, 2, 4, 4, 4)}


class VGG(HybridBlock):
    def __init__(self, layers, filters=_CHANNELS, classes=1000,
                 batch_norm=False, **kwargs):
        super().__init__(**kwargs)
        if len(layers) != len(filters):
            raise ValueError("per-stage repeats and channels must align")
        with self.name_scope():
            feats = nn.HybridSequential(prefix="")
            for repeats, ch in zip(layers, filters):
                for _ in range(repeats):
                    feats.add(nn.Conv2D(ch, kernel_size=3, padding=1))
                    if batch_norm:
                        feats.add(nn.BatchNorm())
                    feats.add(nn.Activation("relu"))
                feats.add(nn.MaxPool2D(strides=2))
            for _ in range(2):
                feats.add(nn.Dense(4096, activation="relu"))
                feats.add(nn.Dropout(rate=0.5))
            self.features = feats
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def get_vgg(num_layers, pretrained=False, ctx=None, root=None, **kwargs):
    net = VGG(vgg_spec[num_layers], **kwargs)
    if pretrained:
        from ..model_store import get_model_file

        bn = "_bn" if kwargs.get("batch_norm") else ""
        net.load_parameters(get_model_file(f"vgg{num_layers}{bn}", root),
                            ctx=ctx)
    return net


for _d in vgg_spec:
    for _bn in (False, True):
        _name = f"vgg{_d}_bn" if _bn else f"vgg{_d}"
        _fn = (partial(get_vgg, _d, batch_norm=True) if _bn
               else partial(get_vgg, _d))
        _fn.__name__ = _name
        _fn.__doc__ = f"VGG-{_d}{' with BatchNorm' if _bn else ''}."
        globals()[_name] = _fn
