"""Pretrained-weights store — offline variant of the reference's
model_store (ref: python/mxnet/gluon/model_zoo/model_store.py:1
get_model_file downloads `<name>-<sha1[:8]>.params` into
~/.mxnet/models).

This environment has no network egress, so the store resolves STRICTLY
locally: weights the user (or an offline mirror sync) placed under the
models root load exactly like downloaded ones — including
reference-format `.params` files, which `nd.load` reads natively
(ndarray/legacy_io.py). `pretrained=True` therefore works the moment the
file exists; otherwise it fails with the precise path to provision.
"""
from __future__ import annotations

import os

__all__ = ["get_model_file", "model_root"]


def model_root(root=None):
    """Default weights directory (ref: model_store.py root=~/.mxnet/models);
    override with MXTPU_MODELS_ROOT."""
    if root:
        return os.path.expanduser(root)
    from ... import config as _config

    env = _config.get("MXTPU_MODELS_ROOT")
    if env:
        return os.path.expanduser(env)
    return os.path.expanduser(os.path.join("~", ".mxnet", "models"))


def get_model_file(name, root=None):
    """Path to `<root>/<name>.params` (also accepts the reference's
    sha1-tagged `<name>-XXXXXXXX.params` spelling). Raises with the
    expected location when absent — there is no download fallback here."""
    root = model_root(root)
    exact = os.path.join(root, f"{name}.params")
    if os.path.exists(exact):
        return exact
    if os.path.isdir(root):
        tagged = sorted(f for f in os.listdir(root)
                        if f.startswith(f"{name}-") and
                        f.endswith(".params"))
        if tagged:
            return os.path.join(root, tagged[-1])
    raise FileNotFoundError(
        f"pretrained weights for {name!r} not found; this build has no "
        f"network egress — place the file at {exact} (reference-format "
        f".params files load directly)")
