"""Gluon Trainer (ref: python/mxnet/gluon/trainer.py:27 — kvstore wiring:169,
step:298, allreduce_grads:327, update:359).

TPU-native: gradients live in single (mesh-replicated) arrays, so the
per-device reduce of the reference collapses to the GSPMD all-reduce already
performed during backward; kvstore remains for dist (multi-host) setups.

Aggregated dispatch: the classic eager step issues O(2·P) tiny XLA programs
— one updater call per parameter plus one allreduce per gradient. Both
loops are bucketed here (ref: the reference's MXNET_OPTIMIZER_AGGREGATION_SIZE
aggregation through multi_sgd_update et al., src/operator/optimizer_op.cc:318,
and MXNET_KVSTORE_BIGARRAY_BOUND comms chunking): parameters are grouped
into dtype-homogeneous byte-capped buckets, each bucket's update runs as
ONE jitted multi-tensor program reusing the exact fused_update math every
built-in optimizer ships, and each bucket's dense gradients cross the
kvstore as ONE flattened pushpull. Tune with
MXNET_OPTIMIZER_AGGREGATION_SIZE / MXTPU_ALLREDUCE_BUCKET_KB (0 disables
either); dispatch counts are observable via mxtpu_trainer_dispatches_total.
"""
from __future__ import annotations

import math
import os
import signal
import time

import jax
import jax.numpy as jnp

from .. import compile_cache as _compile_cache
from .. import config as _config
from .. import optimizer as opt
from .. import kvstore as kvs
from .. import telemetry as _telemetry
from ..ndarray.ndarray import NDArray
from ..resilience import fault as _fault
from .parameter import ParameterDict

__all__ = ["GuardrailRollback", "Trainer"]

_DISPATCHES = "mxtpu_trainer_dispatches_total"
_DISPATCH_HELP = (
    "XLA program dispatches issued by the eager Trainer, by kind "
    "(optimizer_update | allreduce) and path (aggregated/bucketed = one "
    "per bucket; per_param/per_key = one per tensor).")
_BUCKET_BYTES = "mxtpu_trainer_bucket_bytes"
_BUCKET_HELP = ("Payload bytes of one aggregated-dispatch bucket "
                "(kind: optimizer_update | allreduce).")
_GUARDRAIL_METRIC = "mxtpu_guardrail_trips_total"
_GUARDRAIL_HELP = ("Divergence-guardrail trips in Trainer.step, by policy "
                   "(skip/backoff/rollback) and reason.")
_GUARDRAIL_POLICIES = ("skip", "backoff", "rollback")


class GuardrailRollback(RuntimeError):
    """The divergence guardrail (MXTPU_GUARDRAIL_POLICY=rollback) saw
    non-finite gradients: the step was NOT applied and the training loop
    should restore the last good checkpoint via `Trainer.auto_resume`
    and replay from there."""


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        self._params = [p for p in params if p.grad_req != "null"]
        self._all_params = list(params)
        self._scale = 1.0
        optimizer_params = dict(optimizer_params or {})
        idx2name = {i: p.name for i, p in enumerate(self._params)}
        if isinstance(optimizer, str):
            self._optimizer = opt.create(optimizer, param_idx2name=idx2name,
                                         **optimizer_params)
        else:
            self._optimizer = optimizer
            self._optimizer.idx2name.update(idx2name)
        self._updater = opt.get_updater(self._optimizer)
        self._kvstore_str = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore
        self._kv_shipped_rescale = None
        # aggregated dispatch: byte caps resolved once at construction (KB
        # knobs; 0 disables), bucket layout re-derived whenever the
        # parameter signature (dtype/shape/stype set) changes
        self._agg_bytes = max(
            0, int(_config.get("MXNET_OPTIMIZER_AGGREGATION_SIZE"))) * 1024
        self._allreduce_bucket_bytes = max(
            0, int(_config.get("MXTPU_ALLREDUCE_BUCKET_KB"))) * 1024
        self._agg_sig = None
        self._agg_buckets = []
        self._agg_rest = []
        self._agg_fn_cache = {}
        self._flat_fn_cache = {}
        # ZeRO on the eager path: under MXTPU_SHARD_POLICY=zero1/zero2,
        # optimizer state created for a mesh-committed parameter is
        # placed sharded over the 'data' axis (parallel.zero largest-
        # divisible-axis rule) and the bucketed multi-tensor updates
        # operate on the shards; GSPMD partitions the elementwise bucket
        # program accordingly. SR buckets keep their per-NAME fold_in
        # keys (optimizer._sr_key), so sharded and replicated runs stay
        # bit-identical.
        from ..parallel import zero as _zero

        self._shard_policy = _zero.resolve_policy(
            _config.get("MXTPU_SHARD_POLICY"))
        # PS-sharded embedding tier (embedding.ShardedEmbeddingService):
        # when attached, pending row-sparse embedding grads ship at the
        # step boundary, behind the dense gradient exchange
        self._sparse_service = None

    def attach_sparse_service(self, service):
        """Wire a ShardedEmbeddingService into the step boundary: after
        the dense allreduce/pushpull, the grads stashed by remote
        SparseEmbedding blocks push to their shard servers —
        asynchronously on the service's ordered worker when
        MXTPU_SPARSE_PREFETCH is on, so the RPCs overlap the local
        optimizer update while the NEXT step's prefetched pull still
        queues behind them (push N happens-before pull N+1)."""
        self._sparse_service = service
        return service

    @property
    def learning_rate(self):
        return self._optimizer.lr

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)
        self._ship_optimizer_attrs(lr=lr)

    def _ship_optimizer_attrs(self, **attrs):
        """Propagate live optimizer mutations to the server copy (the
        pickled optimizer shipped at init is otherwise a snapshot)."""
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.set_optimizer_attrs(attrs)

    def _init_kvstore(self):
        """(ref: trainer.py:169 _init_kvstore — dist_async forces
        update_on_kvstore: the server owns weights + optimizer)"""
        if self._kv_initialized:
            return
        if isinstance(self._kvstore_str, str) and "dist" in self._kvstore_str:
            self._kvstore = kvs.create(self._kvstore_str)
            server_mode = isinstance(self._kvstore, kvs.KVStoreDistAsyncServer)
            if self._update_on_kvstore is None:
                self._update_on_kvstore = server_mode
            if server_mode and not self._update_on_kvstore:
                raise ValueError(
                    "dist_async_server requires update_on_kvstore=True "
                    "(the server applies the optimizer)")
            if self._update_on_kvstore and not server_mode:
                # collective stores have no server-side optimizer; honoring
                # the flag would silently take the server push/pull path
                # (and crash on set_optimizer_attrs) — reject it loudly
                raise ValueError(
                    f"update_on_kvstore=True is only valid with kvstore="
                    f"'dist_async_server' (a true parameter server); "
                    f"{self._kvstore_str!r} is collective-based — the "
                    "optimizer runs on every worker. Drop the flag or "
                    "switch kvstore types.")
            if self._update_on_kvstore:
                # server-applied updates: seed the authoritative weights and
                # ship the optimizer (ref: trainer.py:221-227)
                self._kvstore.set_optimizer(self._optimizer)
                self._kv_shipped_rescale = self._optimizer.rescale_grad
                for i, p in enumerate(self._params):
                    self._kvstore.init(i, p.data())
            # else: allreduce mode — the store is a transient merge buffer,
            # never seeded with weights (optimizer runs locally everywhere)
        else:
            self._update_on_kvstore = False
        self._kv_initialized = True

    def allreduce_grads(self):
        """(ref: trainer.py:327) — multi-host sum via kvstore; intra-host is
        already reduced by GSPMD."""
        with _telemetry.span("trainer.allreduce_grads"), \
                _telemetry.stepstats.phase("allreduce"):
            self._allreduce_grads_impl()

    def _allreduce_grads_impl(self):
        self._init_kvstore()
        if self._update_on_kvstore:
            raise ValueError(
                "allreduce_grads() is not supported when the optimizer "
                "runs on the kvstore server; call step() "
                "(ref: trainer.py:333)")
        if self._kvstore is None:
            return
        kv = self._kvstore
        cap = self._allreduce_bucket_bytes
        if (cap <= 0
                or not getattr(kv, "supports_bucketed_allreduce", False)
                or getattr(kv, "_compression", None) is not None):
            # per-key path: bucketing disabled, or the store keeps per-key
            # state (async mix counters) / applies per-key compression —
            # flattening through a synthetic key would bypass both
            for i, p in enumerate(self._params):
                g = p.grad()
                # merge-and-reset one-shot allreduce (no cross-step carry)
                kv.pushpull(i, g, out=g)
            _telemetry.inc(_DISPATCHES, len(self._params), kind="allreduce",
                           path="per_key", help=_DISPATCH_HELP)
            return
        from ..ndarray.sparse import BaseSparseNDArray

        dense = []
        for i, p in enumerate(self._params):
            g = p.grad()
            if isinstance(g, BaseSparseNDArray):
                # sparse stays per-key: the store's row_sparse allreduce
                # needs the (indices, data) structure intact
                kv.pushpull(i, g, out=g)
                _telemetry.inc(_DISPATCHES, 1, kind="allreduce",
                               path="per_key", help=_DISPATCH_HELP)
            else:
                dense.append((i, g))
        buckets = []
        cur, cur_bytes, cur_dt = [], 0, None
        for i, g in dense:
            nb = g._data.nbytes
            dt = str(g._data.dtype)
            if cur and (dt != cur_dt or cur_bytes + nb > cap):
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append((i, g))
            cur_bytes += nb
            cur_dt = dt
        if cur:
            buckets.append(cur)
        for j, bucket in enumerate(buckets):
            if len(bucket) == 1:
                i, g = bucket[0]
                kv.pushpull(i, g, out=g)
                payload = g._data.nbytes
            else:
                fl, unfl = self._flat_fns(tuple(
                    (tuple(g._data.shape), str(g._data.dtype))
                    for _, g in bucket))
                flat = NDArray(fl([g._data for _, g in bucket]))
                payload = flat._data.nbytes
                # one synthetic key per bucket; the merge-and-reset store
                # deletes it after the pull, so steps never cross-talk
                kv.pushpull(f"__agg_bucket_{j}", flat, out=flat)
                for (_, g), piece in zip(bucket, unfl(flat._data)):
                    g._data = piece
            _telemetry.inc(_DISPATCHES, 1, kind="allreduce",
                           path="bucketed", help=_DISPATCH_HELP)
            _telemetry.observe(_BUCKET_BYTES, payload, help=_BUCKET_HELP,
                               buckets=_telemetry.BYTES_BUCKETS,
                               kind="allreduce")

    def _flat_fns(self, key):
        """Jitted (flatten, unflatten) pair for one bucket layout; slices
        and reshapes are baked, so each is a single fused program."""
        fns = self._flat_fn_cache.get(key)
        if fns is None:
            shapes = [s for s, _ in key]
            offs = []
            off = 0
            for s in shapes:
                n = int(math.prod(s))
                offs.append((off, off + n))
                off += n

            def fl(datas):
                return jnp.concatenate([d.ravel() for d in datas])

            def unfl(flat):
                return [flat[a:b].reshape(s)
                        for (a, b), s in zip(offs, shapes)]

            fns = (_compile_cache.wrap("trainer.flatten", jax.jit(fl)),
                   _compile_cache.wrap("trainer.unflatten",
                                       jax.jit(unfl)))
            self._flat_fn_cache[key] = fns
            if not _compile_cache.enabled():
                # a miss here is a fresh trace pair; a second layout for
                # the same trainer is a retrace (shape-driven bucket
                # churn). With the persistent cache on, the wrappers
                # register (hit or compile) themselves.
                _telemetry.compilereg.register("trainer.flatten", key)
        return fns

    def _grads_nonfinite(self):
        """One fused non-finite sweep over every live gradient: per-grad
        flags OR on device, ONE host sync total (the same discipline as
        amp's has_overflow / the reference's multi_all_finite)."""
        flag = None
        for p in self._params:
            if p._data is None:
                continue
            g = p.grad()
            if hasattr(g, "data") and hasattr(g, "indices"):  # row_sparse
                data = g.data._data
            else:
                data = g._data
            bad = ~jnp.isfinite(data).all()
            flag = bad if flag is None else flag | bad
        return bool(flag) if flag is not None else False

    def _guardrail_check(self, where):
        """Divergence guardrail (MXTPU_GUARDRAIL_POLICY): True means the
        caller must SKIP this step's update (the gradients were
        non-finite and the policy absorbed it); `rollback` raises
        GuardrailRollback instead. Runs BEFORE gradients reach the
        optimizer or the parameter server, on both step paths, so one
        poisoned step can never corrupt the weights."""
        policy = _config.get("MXTPU_GUARDRAIL_POLICY")
        if not policy:
            return False
        if policy not in _GUARDRAIL_POLICIES:
            raise ValueError(
                f"MXTPU_GUARDRAIL_POLICY={policy!r}; expected one of "
                f"{_GUARDRAIL_POLICIES} (or empty to disable)")
        inj = _fault.injector()
        if inj.active and inj.action("grad.nonfinite") is not None:
            # chaos poisoning: corrupt one gradient so the check below
            # trips at an exactly reproducible step
            for p in self._params:
                if p._data is None:
                    continue
                g = p.grad()
                if hasattr(g, "data") and hasattr(g, "indices"):
                    g.data._data = g.data._data * jnp.nan
                else:
                    g._data = g._data * jnp.nan
                break
        if not self._grads_nonfinite():
            return False
        from ..telemetry import recorder as _recorder

        _telemetry.inc(_GUARDRAIL_METRIC, 1, help=_GUARDRAIL_HELP,
                       policy=policy, reason="nonfinite-grad")
        _telemetry.log_event("guardrail_trip", policy=policy,
                             reason="nonfinite-grad", where=where)
        # a divergence event is exactly what post-mortems want context for
        _recorder.dump("guardrail-trip")
        if policy == "rollback":
            raise GuardrailRollback(
                "non-finite gradients detected; the step was not applied "
                "— restore the last good checkpoint (Trainer.auto_resume) "
                "and replay")
        if policy == "backoff":
            scaler = getattr(self, "_amp_scaler", None)
            if scaler is None:
                # no AMP in play: attach a unit scaler pinned at 1.0 (a
                # huge window forbids growth) — later steps gain the
                # overflow check without ever rescaling unscaled losses
                from ..contrib import amp as _amp

                scaler = _amp.DynamicLossScaler(
                    init_scale=1.0, scale_window=10 ** 9, min_scale=1.0)
                self._amp_scaler = scaler
            scaler.update_scale(True)
        return True

    def _amp_pre_update(self, rescale):
        """(skip_step, effective_rescale): overflow-skip + unscale factor
        for loss-scaled gradients (ref: contrib/amp loss-scaled step).
        Always runs when a scaler is attached — even at loss_scale 1.0 the
        overflow check must keep non-finite gradients out of the weights."""
        scaler = getattr(self, "_amp_scaler", None)
        if scaler is None:
            return False, rescale
        # scale_loss records the scale it actually applied (a user may
        # override it); fall back to the live scaler value
        applied = getattr(self, "_amp_applied_scale", None)
        if applied is None:
            applied = scaler.loss_scale
        if scaler.has_overflow([p.grad() for p in self._params
                                if p._data is not None]):
            scaler.update_scale(True)
            return True, rescale
        scaler.update_scale(False)
        return False, rescale / applied

    def step(self, batch_size, ignore_stale_grad=False):
        """(ref: trainer.py:298)"""
        if not _telemetry.enabled():
            return self._step_impl(batch_size, ignore_stale_grad)
        t0 = time.perf_counter()
        with _telemetry.span("trainer.step"):
            try:
                return self._step_impl(batch_size, ignore_stale_grad)
            finally:
                _telemetry.observe(
                    "mxtpu_trainer_step_seconds", time.perf_counter() - t0,
                    help="End-to-end Trainer.step latency (allreduce + "
                         "optimizer update; excludes forward/backward).")
                # step boundary: the agreed sampling point for device
                # memory watermarks (MXNET_TELEMETRY_MEM_INTERVAL) and the
                # HBM ledger (MXNET_TELEMETRY_LEDGER_INTERVAL)
                _telemetry.step_boundary()
                # close the StepStats step: phases fed since the previous
                # boundary (data fetch, dispatch, allreduce, update, sync)
                # roll into the per-phase p50/p99 window; the step total is
                # wall time since the previous boundary, so the anomaly
                # guard sees the whole loop iteration
                _telemetry.stepstats.step_end()

    def _step_impl(self, batch_size, ignore_stale_grad=False):
        inj = _fault.injector()
        if inj.active and inj.action("train.step") == "sigterm":
            # deterministic preemption: deliver SIGTERM to self at an
            # exact step; the drain handler only flags it, the step
            # completes, and the loop's boundary poll takes the bundle
            os.kill(os.getpid(), signal.SIGTERM)
        # rescale BEFORE _init_kvstore: server mode pickles the optimizer at
        # init, so the scale must already be baked in on the first step
        rescale = self._scale / batch_size
        self._optimizer.rescale_grad = rescale
        self._init_kvstore()
        if self._kvstore is not None and self._update_on_kvstore:
            if getattr(self, "_amp_scaler", None) is not None:
                # per-worker overflow skips + per-worker scales would feed
                # the SHARED server optimizer inconsistently (partial sums,
                # racing rescale ships) — refuse rather than corrupt
                raise NotImplementedError(
                    "amp loss scaling is not supported with server-side "
                    "updates (update_on_kvstore); train in allreduce mode "
                    "or without a loss scaler")
            if rescale != self._kv_shipped_rescale:
                self._ship_optimizer_attrs(rescale_grad=rescale)
                self._kv_shipped_rescale = rescale
            if self._guardrail_check("server_push"):
                # the poisoned gradients never reach the shared server
                return
            # push grads, pull server-updated weights — no local update.
            # Hierarchical path: ONE inter-host push_many/pull_many RPC
            # pair per byte-capped bucket after the store's intra-host
            # GSPMD reduction, vs one push+pull per parameter on the
            # flat fallback.
            kv = self._kvstore
            with _telemetry.stepstats.phase("pushpull"):
                if getattr(kv, "supports_hierarchical_pushpull", False):
                    kv.pushpull(list(range(len(self._params))),
                                [p.grad() for p in self._params],
                                out=[p.data() for p in self._params])
                    _telemetry.inc(_DISPATCHES, 1, kind="server_pushpull",
                                   path="hierarchical", help=_DISPATCH_HELP)
                else:
                    for i, p in enumerate(self._params):
                        kv.push(i, p.grad())
                        kv.pull(i, out=p.data())
                    _telemetry.inc(_DISPATCHES, len(self._params),
                                   kind="server_pushpull", path="per_key",
                                   help=_DISPATCH_HELP)
            if self._sparse_service is not None:
                self._sparse_service.push_grads()
            return
        if self._kvstore is not None:
            self.allreduce_grads()
        # row-sparse embedding grads ship NOW, behind the dense allreduce:
        # with prefetch on this only enqueues — the RPCs overlap the
        # optimizer update below
        if self._sparse_service is not None:
            self._sparse_service.push_grads()
        # AFTER allreduce: one worker's NaN poisons every replica's
        # reduced gradient, so the check must see the reduced values
        if self._guardrail_check("local_update"):
            return
        skip, eff = self._amp_pre_update(rescale)
        if skip:
            return
        self._optimizer.rescale_grad = eff
        with _telemetry.stepstats.phase("optimizer_update"):
            self._update(ignore_stale_grad)

    def update(self, batch_size, ignore_stale_grad=False):
        self._init_kvstore()
        if self._update_on_kvstore:
            raise ValueError(
                "update() is not supported when the optimizer runs on the "
                "kvstore server; call step() (ref: trainer.py:360)")
        rescale = self._scale / batch_size
        if self._guardrail_check("update"):
            return
        skip, eff = self._amp_pre_update(rescale)
        if skip:
            return
        self._optimizer.rescale_grad = eff
        with _telemetry.stepstats.phase("optimizer_update"):
            self._update(ignore_stale_grad)

    # -- aggregated multi-tensor update path --------------------------------

    def _aggregation_supported(self):
        """Aggregation needs a dedicated fused_update that reproduces the
        eager update step-for-step; custom optimizers inherit the base
        generic hook and stay on the per-param path."""
        if self._agg_bytes <= 0:
            return False
        o = self._optimizer
        if self._updater.optimizer is not o:
            # load_states(dump_optimizer=True) style divergence — the eager
            # updater would use a different optimizer than we would
            return False
        return (type(o).fused_update is not opt.Optimizer.fused_update
                and getattr(o, "fused_matches_eager", True))

    def _update(self, ignore_stale_grad=False):
        if not ignore_stale_grad and self._aggregation_supported():
            self._update_aggregated()
            return
        n = 0
        for i, p in enumerate(self._params):
            if p._data is None:
                continue
            self._updater(i, p.grad(), p.data())
            n += 1
        _telemetry.inc(_DISPATCHES, n, kind="optimizer_update",
                       path="per_param", help=_DISPATCH_HELP)

    def _bucket_signature(self):
        sig = []
        for p in self._params:
            d = p._data
            if d is None:
                sig.append(None)
            else:
                sig.append((str(d._data.dtype), tuple(d._data.shape),
                            getattr(p, "stype", "default"),
                            getattr(p, "grad_stype", "default")))
        return tuple(sig)

    def _build_update_buckets(self):
        """Greedy in-order grouping into dtype-homogeneous byte-capped
        buckets (ref: the reference's aggregation by MXNET_OPTIMIZER_
        AGGREGATION_SIZE); sparse-typed params go to the per-param rest."""
        buckets, rest = [], []
        cur, cur_bytes, cur_dt = [], 0, None
        for i, p in enumerate(self._params):
            if p._data is None:
                continue
            if (getattr(p, "stype", "default") != "default"
                    or getattr(p, "grad_stype", "default") != "default"):
                rest.append(i)
                continue
            d = p._data._data
            nb = d.nbytes
            dt = str(d.dtype)
            if cur and (dt != cur_dt or cur_bytes + nb > self._agg_bytes):
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += nb
            cur_dt = dt
        if cur:
            buckets.append(cur)
        return buckets, rest

    def _update_aggregated(self):
        from ..ndarray.sparse import BaseSparseNDArray

        sig = self._bucket_signature()
        if sig != self._agg_sig:
            self._agg_buckets, self._agg_rest = self._build_update_buckets()
            self._agg_sig = sig
            self._agg_fn_cache.clear()
        for bid, bucket in enumerate(self._agg_buckets):
            grads = [self._params[i].grad() for i in bucket]
            if any(isinstance(g, BaseSparseNDArray) for g in grads):
                # a dense-typed param still produced a sparse grad — the
                # lazy-update semantics only exist on the per-param path
                for i in bucket:
                    p = self._params[i]
                    self._updater(i, p.grad(), p.data())
                _telemetry.inc(_DISPATCHES, len(bucket),
                               kind="optimizer_update", path="per_param",
                               help=_DISPATCH_HELP)
                continue
            self._dispatch_bucket(bid, bucket, grads)
        for i in self._agg_rest:
            p = self._params[i]
            self._updater(i, p.grad(), p.data())
        if self._agg_rest:
            _telemetry.inc(_DISPATCHES, len(self._agg_rest),
                           kind="optimizer_update", path="per_param",
                           help=_DISPATCH_HELP)

    def _dispatch_bucket(self, bid, bucket, grads):
        o = self._optimizer
        u = self._updater
        weights = [self._params[i].data() for i in bucket]
        for i, w in zip(bucket, weights):
            if i not in u.states:
                u.states[i] = o.create_state_multi_precision(i, w)
                u.states_synced[i] = True
                if self._shard_policy != "replicated":
                    self._place_state_sharded(w, u.states[i])
                _telemetry.ledger.track(u.states[i], "optimizer_state")
        states = [u.states[i] for i in bucket]
        # advance every count BEFORE reading ts/base_lr: on the eager path
        # all params of step n already see num_update == n (the first
        # update of the step raises the running max)
        for i in bucket:
            o._update_count(i)
        ts = [o._index_update_count[i] for i in bucket]
        base_lr = (o.lr_scheduler(o.num_update)
                   if o.lr_scheduler is not None else o.lr)
        names = tuple(o.idx2name.get(i, i) for i in bucket)
        # stochastic-rounding SGD rides the generic fused_update loop: the
        # multi_sgd_* ops don't know the SR rounding contract
        use_sgd = (type(o) is opt.SGD
                   and not getattr(o, "stochastic_rounding", False))
        key = (bid, "sgd" if use_sgd else "generic", self._hyper_key(names))
        fn = self._agg_fn_cache.get(key)
        if fn is None:
            if len(self._agg_fn_cache) > 256:
                # hyperparameter churn (wd/momentum edits every step) would
                # otherwise pin one jitted program per historical value
                self._agg_fn_cache.clear()
            out_sh = self._bucket_out_shardings(weights, states)
            if use_sgd:
                fn = self._build_sgd_bucket_fn(
                    names, mp=isinstance(states[0], tuple),
                    out_shardings=out_sh)
            else:
                fn = self._build_bucket_fn(names, out_shardings=out_sh)
            donate = (0, 1) if jax.default_backend() != "cpu" else ()
            fn = _compile_cache.wrap(
                f"trainer.bucket_update[{bid}]", fn, donated=donate,
                static_key=key[1:])
            self._agg_fn_cache[key] = fn
            if not _compile_cache.enabled():
                # new (optimizer-kind, hyper) program for this bucket: a
                # second key for the same bucket id means hyper/signature
                # churn retraced it (each bucket id is its own program,
                # not a retrace). With the persistent cache on, the
                # wrapper registers (hit or compile) itself.
                _telemetry.compilereg.register(
                    f"trainer.bucket_update[{bid}]", key[1:])
        w_data = [w._data for w in weights]
        s_data = [self._state_data(s) for s in states]
        g_data = [g._data for g in grads]
        new_w, new_s = fn(
            w_data, s_data, g_data,
            jnp.asarray(base_lr, jnp.float32),
            [jnp.asarray(t, jnp.float32) for t in ts],
            jnp.asarray(o.rescale_grad, jnp.float32))
        for w, nw in zip(weights, new_w):
            w._data = nw
        for s, ns in zip(states, new_s):
            self._write_state(s, ns)
        _telemetry.inc(_DISPATCHES, 1, kind="optimizer_update",
                       path="aggregated", help=_DISPATCH_HELP)
        _telemetry.observe(_BUCKET_BYTES, sum(d.nbytes for d in w_data),
                           help=_BUCKET_HELP,
                           buckets=_telemetry.BYTES_BUCKETS,
                           kind="optimizer_update")

    def _place_state_sharded(self, w, state):
        """ZeRO placement for a freshly created optimizer state: when the
        parameter is committed to a mesh with a 'data' axis
        (Parameter.place / fused sync), put each state leaf — momentum
        AND the f32 master copy — on its largest divisible axis over
        that mesh (parallel.zero rule); ragged leaves stay replicated.
        Meshless parameters are left alone, so the knob is a no-op on a
        single device."""
        from jax.sharding import NamedSharding
        from ..parallel import zero as _zero

        wsh = getattr(w._data, "sharding", None)
        mesh = getattr(wsh, "mesh", None)
        if mesh is None or "data" not in getattr(mesh, "axis_names", ()):
            return
        n = mesh.shape["data"]

        def place(s):
            if isinstance(s, NDArray):
                spec = _zero.largest_axis_spec(tuple(s._data.shape), n)
                s._data = jax.device_put(s._data, NamedSharding(mesh, spec))

        if isinstance(state, tuple):
            for s in state:
                place(s)
        else:
            place(state)

    def _bucket_out_shardings(self, weights, states):
        """Pin bucket-update outputs to the input placements under a
        ZeRO policy: without this XLA may emit replicated state outputs,
        silently undoing the 1/N placement after the first dispatch.
        Returns None (jit's default) for the replicated policy or when
        no leaf in the bucket is mesh-committed — the knob-off program
        is byte-identical."""
        def sh(x):
            d = getattr(x, "_data", x)
            return getattr(d, "sharding", None)

        if self._shard_policy == "replicated":
            return None
        w_sh = [sh(w) for w in weights]
        s_sh = jax.tree_util.tree_map(sh, states)
        mesh_committed = any(
            getattr(s, "mesh", None) is not None
            for s in w_sh + jax.tree_util.tree_leaves(s_sh))
        if not mesh_committed:
            return None
        return (w_sh, s_sh)

    @staticmethod
    def _is_mp_state(w, s):
        """Multi-precision state shape: (mom_or_None, fp32 master) behind a
        low-precision weight — hyperparameter scalars must then stay fp32
        (the math runs on the master copy)."""
        return (isinstance(s, tuple) and len(s) == 2 and s[1] is not None
                and hasattr(s[1], "dtype") and str(s[1].dtype) == "float32"
                and str(w.dtype) != "float32")

    def _build_bucket_fn(self, names, out_shardings=None):
        """One jitted program applying each param's own fused_update — the
        exact math GluonTrainStep traces, so aggregated == eager for every
        optimizer whose fused hook matches (fused_matches_eager)."""
        o = self._optimizer
        donate = (0, 1) if jax.default_backend() != "cpu" else ()

        def run(w_data, s_data, g_data, lr, ts, rescale):
            old_rescale = o.rescale_grad
            new_w, new_s = [], []
            try:
                for name, w, s, g, t in zip(names, w_data, s_data, g_data,
                                            ts):
                    if self._is_mp_state(w, s) or (
                            getattr(o, "stochastic_rounding", False)
                            and str(w.dtype) == "bfloat16"):
                        # master-copy math and the SR master-free path both
                        # run in f32 — keep the traced scalars f32 too
                        lr_p, rs_p = lr, rescale
                    else:
                        # eager hyperparams are weak python scalars (bf16
                        # math stays bf16); a strong f32 traced scalar
                        # would promote — cast to the weight dtype
                        lr_p = lr.astype(w.dtype)
                        rs_p = rescale.astype(w.dtype)
                    o.rescale_grad = rs_p
                    w2, s2 = o.fused_update(name, w, g, s, lr_p, t=t)
                    new_w.append(w2.astype(w.dtype))
                    new_s.append(opt._cast_state_like(s2, s))
            finally:
                o.rescale_grad = old_rescale
            return new_w, new_s

        return jax.jit(run, donate_argnums=donate,
                       out_shardings=out_shardings)

    def _build_sgd_bucket_fn(self, names, mp, out_shardings=None):
        """SGD rides the registered multi-tensor ops (ref: optimizer_op.cc
        multi_sgd_update / multi_sgd_mom_update / multi_mp_sgd_*)."""
        o = self._optimizer
        from ..ops import optimizer as _oo

        mults = [self._mult_pair(n) for n in names]
        momentum = o.momentum
        clip = o.clip_gradient if o.clip_gradient else -1.0
        wd_base = o.wd
        donate = (0, 1) if jax.default_backend() != "cpu" else ()

        def run(w_data, s_data, g_data, lr, ts, rescale):
            n = len(w_data)
            wds = tuple(wd_base * wm for _, wm in mults)
            if mp:
                # math on the fp32 masters; scalars stay fp32
                lrs = tuple(lr * lm for lm, _ in mults)
                flat = []
                if momentum != 0.0:
                    for w, g, s in zip(w_data, g_data, s_data):
                        flat += [w, g, s[0], s[1]]
                    outs = _oo.multi_mp_sgd_mom_update(
                        *flat, lrs=lrs, wds=wds, num_weights=n,
                        momentum=momentum, rescale_grad=rescale,
                        clip_gradient=clip)
                    new_w = list(outs[:n])
                    new_s = list(zip(outs[n:2 * n], outs[2 * n:]))
                else:
                    for w, g, s in zip(w_data, g_data, s_data):
                        flat += [w, g, s[1]]
                    outs = _oo.multi_mp_sgd_update(
                        *flat, lrs=lrs, wds=wds, num_weights=n,
                        rescale_grad=rescale, clip_gradient=clip)
                    new_w = list(outs[:n])
                    new_s = [(None, w32) for w32 in outs[n:]]
                return new_w, new_s
            # non-mp: match eager weak-scalar typing — keep the math in the
            # bucket dtype
            dt = w_data[0].dtype
            lrs = tuple((lr * lm).astype(dt) for lm, _ in mults)
            rs = rescale.astype(dt)
            flat = []
            if momentum != 0.0:
                for w, g, m in zip(w_data, g_data, s_data):
                    flat += [w, g, m]
                outs = _oo.multi_sgd_mom_update(
                    *flat, lrs=lrs, wds=wds, num_weights=n,
                    momentum=momentum, rescale_grad=rs, clip_gradient=clip)
                return list(outs[:n]), list(outs[n:])
            for w, g in zip(w_data, g_data):
                flat += [w, g]
            outs = _oo.multi_sgd_update(
                *flat, lrs=lrs, wds=wds, num_weights=n,
                rescale_grad=rs, clip_gradient=clip)
            return list(outs), [None] * n

        return jax.jit(run, donate_argnums=donate,
                       out_shardings=out_shardings)

    def _mult_pair(self, name):
        o = self._optimizer
        if name in o.param_dict:
            p = o.param_dict[name]
            return (float(p.lr_mult), float(p.wd_mult))
        return (float(o.lr_mult.get(name, 1.0)),
                float(o.wd_mult.get(name, 1.0)))

    def _hyper_key(self, names):
        """Everything a bucket fn bakes at trace time: the optimizer's
        scalar hyperparams (minus the traced lr / rescale / counts) plus
        each param's lr/wd multipliers."""
        o = self._optimizer
        scalars = tuple(sorted(
            (k, v) for k, v in vars(o).items()
            if not k.startswith("_")
            and k not in ("rescale_grad", "lr", "num_update",
                          "begin_num_update")
            and isinstance(v, (int, float, bool, str, type(None)))))
        return scalars + tuple(self._mult_pair(n) for n in names)

    @staticmethod
    def _state_data(state):
        if state is None:
            return None
        if isinstance(state, tuple):
            return tuple(Trainer._state_data(s) for s in state)
        return state._data

    @staticmethod
    def _write_state(state, new):
        """Write updated raw arrays back into the SAME NDArray objects the
        Updater holds — save_states/load_states keep working unchanged."""
        if state is None or new is None:
            return
        if isinstance(state, tuple):
            for s, n in zip(state, new):
                Trainer._write_state(s, n)
            return
        state._data = new

    # -----------------------------------------------------------------------

    def save_states(self, fname):
        from .. import resilience as _resilience

        self._init_kvstore()
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.save_optimizer_states(fname)
            return
        _resilience.atomic_write_bytes(
            fname, self._updater.get_states(dump_optimizer=False),
            site="ckpt.states")

    def load_states(self, fname):
        self._init_kvstore()
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.load_optimizer_states(fname)
            return
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def save_checkpoint(self, prefix, epoch, net=None):
        """Crash-consistent epoch checkpoint: `prefix-%04d.params` (when
        `net` is given) + `prefix-%04d.states`, both through the
        tmp/fsync/rename + manifest protocol so `auto_resume` can walk
        back over torn epochs after a crash."""
        from .. import resilience as _resilience
        from .. import telemetry as _telemetry

        _telemetry.log_event("trainer_checkpoint", prefix=str(prefix),
                             epoch=int(epoch))
        if net is not None:
            _resilience.atomic_save(f"{prefix}-{epoch:04d}.params",
                                    net.save_parameters)
        self.save_states(f"{prefix}-{epoch:04d}.states")

    def save_bundle(self, prefix, epoch, net=None, loader=None):
        """Preemption resume bundle: params + optimizer states + data-
        pipeline cursor + global RNG position, crash-consistently under
        `prefix` (see resilience.preemption.write_bundle). Unlike
        `save_checkpoint` this captures a MID-EPOCH point."""
        from ..resilience import preemption as _preemption

        return _preemption.write_bundle(prefix, trainer=self, net=net,
                                        loader=loader, epoch=epoch)

    def auto_resume(self, prefix, net=None, loader=None):
        """Resume an interrupted run under `prefix`. Preference order:

        1. a verified preemption bundle whose epoch is at least as new as
           the epoch checkpoints — restores params, optimizer states, the
           global RNG position, and (when `loader` is given) the data
           pipeline's mid-epoch cursor, then returns the interrupted
           epoch so the caller re-enters it (the loader fast-forwards
           past the batches already trained);
        2. else the newest VERIFIED epoch checkpoint: loads the
           parameters into `net` (when given) and the optimizer states
           when the matching `.states` file verifies too, returning last
           valid epoch + 1;
        3. else 0 (fresh start)."""
        import os

        from .. import model as _model
        from .. import random as _random
        from .. import resilience as _resilience
        from ..resilience import preemption as _preemption

        from .. import telemetry as _telemetry

        epoch = _model.latest_valid_checkpoint(prefix)
        bundle = _preemption.read_bundle(prefix)
        if bundle is not None and (epoch is None
                                   or bundle["epoch"] >= epoch + 1):
            b_paths = _preemption.bundle_paths(prefix)
            _telemetry.log_event("trainer_resume", prefix=str(prefix),
                                 epoch=int(bundle["epoch"]), fresh=False,
                                 bundle=True)
            if net is not None and bundle["has_params"]:
                net.load_parameters(b_paths[1])
            if bundle["has_states"]:
                self.load_states(b_paths[2])
            if loader is not None and bundle["loader"] is not None:
                loader.load_state_dict(bundle["loader"])
            _random.set_state(bundle["rng"])
            return int(bundle["epoch"])
        if epoch is None:
            _telemetry.log_event("trainer_resume", prefix=str(prefix),
                                 epoch=-1, fresh=True)
            return 0
        _telemetry.log_event("trainer_resume", prefix=str(prefix),
                             epoch=int(epoch), fresh=False)
        if net is not None:
            net.load_parameters(f"{prefix}-{epoch:04d}.params")
        states = f"{prefix}-{epoch:04d}.states"
        if os.path.isfile(states) and _resilience.verify(states):
            self.load_states(states)
        return epoch + 1
