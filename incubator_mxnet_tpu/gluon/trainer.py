"""Gluon Trainer (ref: python/mxnet/gluon/trainer.py:27 — kvstore wiring:169,
step:298, allreduce_grads:327, update:359).

TPU-native: gradients live in single (mesh-replicated) arrays, so the
per-device reduce of the reference collapses to the GSPMD all-reduce already
performed during backward; kvstore remains for dist (multi-host) setups.
"""
from __future__ import annotations

from .. import optimizer as opt
from .. import kvstore as kvs
from .parameter import ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        self._params = [p for p in params if p.grad_req != "null"]
        self._all_params = list(params)
        self._scale = 1.0
        optimizer_params = dict(optimizer_params or {})
        idx2name = {i: p.name for i, p in enumerate(self._params)}
        if isinstance(optimizer, str):
            self._optimizer = opt.create(optimizer, param_idx2name=idx2name,
                                         **optimizer_params)
        else:
            self._optimizer = optimizer
            self._optimizer.idx2name.update(idx2name)
        self._updater = opt.get_updater(self._optimizer)
        self._kvstore_str = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore

    @property
    def learning_rate(self):
        return self._optimizer.lr

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _init_kvstore(self):
        """(ref: trainer.py:169 _init_kvstore)"""
        if self._kv_initialized:
            return
        if isinstance(self._kvstore_str, str) and "dist" in self._kvstore_str:
            # allreduce mode: the store is a transient merge buffer, never
            # seeded with weights (optimizer runs locally on every worker)
            self._kvstore = kvs.create(self._kvstore_str)
        self._kv_initialized = True

    def allreduce_grads(self):
        """(ref: trainer.py:327) — multi-host sum via kvstore; intra-host is
        already reduced by GSPMD."""
        self._init_kvstore()
        if self._kvstore is not None:
            for i, p in enumerate(self._params):
                g = p.grad()
                # merge-and-reset one-shot allreduce (no cross-step carry)
                self._kvstore.pushpull(i, g, out=g)

    def step(self, batch_size, ignore_stale_grad=False):
        """(ref: trainer.py:298)"""
        self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        if self._kvstore is not None:
            self.allreduce_grads()
        self._update(ignore_stale_grad)

    def update(self, batch_size, ignore_stale_grad=False):
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        for i, p in enumerate(self._params):
            if p._data is None:
                continue
            self._updater(i, p.grad(), p.data())

    def save_states(self, fname):
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer=False))

    def load_states(self, fname):
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())
