"""Gluon Trainer (ref: python/mxnet/gluon/trainer.py:27 — kvstore wiring:169,
step:298, allreduce_grads:327, update:359).

TPU-native: gradients live in single (mesh-replicated) arrays, so the
per-device reduce of the reference collapses to the GSPMD all-reduce already
performed during backward; kvstore remains for dist (multi-host) setups.
"""
from __future__ import annotations

import time

from .. import optimizer as opt
from .. import kvstore as kvs
from .. import telemetry as _telemetry
from .parameter import ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        self._params = [p for p in params if p.grad_req != "null"]
        self._all_params = list(params)
        self._scale = 1.0
        optimizer_params = dict(optimizer_params or {})
        idx2name = {i: p.name for i, p in enumerate(self._params)}
        if isinstance(optimizer, str):
            self._optimizer = opt.create(optimizer, param_idx2name=idx2name,
                                         **optimizer_params)
        else:
            self._optimizer = optimizer
            self._optimizer.idx2name.update(idx2name)
        self._updater = opt.get_updater(self._optimizer)
        self._kvstore_str = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore
        self._kv_shipped_rescale = None

    @property
    def learning_rate(self):
        return self._optimizer.lr

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)
        self._ship_optimizer_attrs(lr=lr)

    def _ship_optimizer_attrs(self, **attrs):
        """Propagate live optimizer mutations to the server copy (the
        pickled optimizer shipped at init is otherwise a snapshot)."""
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.set_optimizer_attrs(attrs)

    def _init_kvstore(self):
        """(ref: trainer.py:169 _init_kvstore — dist_async forces
        update_on_kvstore: the server owns weights + optimizer)"""
        if self._kv_initialized:
            return
        if isinstance(self._kvstore_str, str) and "dist" in self._kvstore_str:
            self._kvstore = kvs.create(self._kvstore_str)
            server_mode = isinstance(self._kvstore, kvs.KVStoreDistAsyncServer)
            if self._update_on_kvstore is None:
                self._update_on_kvstore = server_mode
            if server_mode and not self._update_on_kvstore:
                raise ValueError(
                    "dist_async_server requires update_on_kvstore=True "
                    "(the server applies the optimizer)")
            if self._update_on_kvstore and not server_mode:
                # collective stores have no server-side optimizer; honoring
                # the flag would silently take the server push/pull path
                # (and crash on set_optimizer_attrs) — reject it loudly
                raise ValueError(
                    f"update_on_kvstore=True is only valid with kvstore="
                    f"'dist_async_server' (a true parameter server); "
                    f"{self._kvstore_str!r} is collective-based — the "
                    "optimizer runs on every worker. Drop the flag or "
                    "switch kvstore types.")
            if self._update_on_kvstore:
                # server-applied updates: seed the authoritative weights and
                # ship the optimizer (ref: trainer.py:221-227)
                self._kvstore.set_optimizer(self._optimizer)
                self._kv_shipped_rescale = self._optimizer.rescale_grad
                for i, p in enumerate(self._params):
                    self._kvstore.init(i, p.data())
            # else: allreduce mode — the store is a transient merge buffer,
            # never seeded with weights (optimizer runs locally everywhere)
        else:
            self._update_on_kvstore = False
        self._kv_initialized = True

    def allreduce_grads(self):
        """(ref: trainer.py:327) — multi-host sum via kvstore; intra-host is
        already reduced by GSPMD."""
        with _telemetry.span("trainer.allreduce_grads"):
            self._allreduce_grads_impl()

    def _allreduce_grads_impl(self):
        self._init_kvstore()
        if self._update_on_kvstore:
            raise ValueError(
                "allreduce_grads() is not supported when the optimizer "
                "runs on the kvstore server; call step() "
                "(ref: trainer.py:333)")
        if self._kvstore is not None:
            for i, p in enumerate(self._params):
                g = p.grad()
                # merge-and-reset one-shot allreduce (no cross-step carry)
                self._kvstore.pushpull(i, g, out=g)

    def _amp_pre_update(self, rescale):
        """(skip_step, effective_rescale): overflow-skip + unscale factor
        for loss-scaled gradients (ref: contrib/amp loss-scaled step).
        Always runs when a scaler is attached — even at loss_scale 1.0 the
        overflow check must keep non-finite gradients out of the weights."""
        scaler = getattr(self, "_amp_scaler", None)
        if scaler is None:
            return False, rescale
        # scale_loss records the scale it actually applied (a user may
        # override it); fall back to the live scaler value
        applied = getattr(self, "_amp_applied_scale", None)
        if applied is None:
            applied = scaler.loss_scale
        if scaler.has_overflow([p.grad() for p in self._params
                                if p._data is not None]):
            scaler.update_scale(True)
            return True, rescale
        scaler.update_scale(False)
        return False, rescale / applied

    def step(self, batch_size, ignore_stale_grad=False):
        """(ref: trainer.py:298)"""
        if not _telemetry.enabled():
            return self._step_impl(batch_size, ignore_stale_grad)
        t0 = time.perf_counter()
        with _telemetry.span("trainer.step"):
            try:
                return self._step_impl(batch_size, ignore_stale_grad)
            finally:
                _telemetry.observe(
                    "mxtpu_trainer_step_seconds", time.perf_counter() - t0,
                    help="End-to-end Trainer.step latency (allreduce + "
                         "optimizer update; excludes forward/backward).")
                # step boundary: the agreed sampling point for device
                # memory watermarks (MXNET_TELEMETRY_MEM_INTERVAL)
                _telemetry.step_boundary()

    def _step_impl(self, batch_size, ignore_stale_grad=False):
        # rescale BEFORE _init_kvstore: server mode pickles the optimizer at
        # init, so the scale must already be baked in on the first step
        rescale = self._scale / batch_size
        self._optimizer.rescale_grad = rescale
        self._init_kvstore()
        if self._kvstore is not None and self._update_on_kvstore:
            if getattr(self, "_amp_scaler", None) is not None:
                # per-worker overflow skips + per-worker scales would feed
                # the SHARED server optimizer inconsistently (partial sums,
                # racing rescale ships) — refuse rather than corrupt
                raise NotImplementedError(
                    "amp loss scaling is not supported with server-side "
                    "updates (update_on_kvstore); train in allreduce mode "
                    "or without a loss scaler")
            if rescale != self._kv_shipped_rescale:
                self._ship_optimizer_attrs(rescale_grad=rescale)
                self._kv_shipped_rescale = rescale
            # push grads, pull server-updated weights — no local update
            for i, p in enumerate(self._params):
                self._kvstore.push(i, p.grad())
                self._kvstore.pull(i, out=p.data())
            return
        if self._kvstore is not None:
            self.allreduce_grads()
        skip, eff = self._amp_pre_update(rescale)
        if skip:
            return
        self._optimizer.rescale_grad = eff
        self._update(ignore_stale_grad)

    def update(self, batch_size, ignore_stale_grad=False):
        self._init_kvstore()
        if self._update_on_kvstore:
            raise ValueError(
                "update() is not supported when the optimizer runs on the "
                "kvstore server; call step() (ref: trainer.py:360)")
        rescale = self._scale / batch_size
        skip, eff = self._amp_pre_update(rescale)
        if skip:
            return
        self._optimizer.rescale_grad = eff
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        for i, p in enumerate(self._params):
            if p._data is None:
                continue
            self._updater(i, p.grad(), p.data())

    def save_states(self, fname):
        self._init_kvstore()
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.save_optimizer_states(fname)
            return
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer=False))

    def load_states(self, fname):
        self._init_kvstore()
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.load_optimizer_states(fname)
            return
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())
