"""Gluon RNN cells (ref: python/mxnet/gluon/rnn/rnn_cell.py)."""
from __future__ import annotations

from ... import initializer as init_mod
from ...ndarray import zeros as nd_zeros
from ..block import HybridBlock

__all__ = [
    "RecurrentCell", "RNNCell", "LSTMCell", "GRUCell", "SequentialRNNCell",
    "DropoutCell", "ZoneoutCell", "ResidualCell", "BidirectionalCell",
]


class RecurrentCell(HybridBlock):
    """(ref: rnn_cell.py RecurrentCell)"""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            states.append(nd_zeros(info["shape"]))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """(ref: rnn_cell.py unroll) — python loop; under hybridize the whole
        unrolled graph compiles into one XLA program.

        With valid_length (B,), outputs past each sequence's length are
        zeroed and the returned states are each sequence's states at its
        LAST VALID step (the reference's SequenceMask + SequenceLast
        semantics), so padded batches train identically to packed ones."""
        self.reset()
        axis = layout.find("T")
        from ... import ndarray as nd

        if not isinstance(inputs, (list, tuple)):
            inputs = [
                x.squeeze(axis=axis)
                for x in nd.split(inputs, num_outputs=length, axis=axis, squeeze_axis=False)
            ]
        states = begin_state if begin_state is not None else self.begin_state(inputs[0].shape[0])
        begin = states
        outputs = []
        step_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                step_states.append(states)
        if valid_length is not None:
            vl = valid_length if isinstance(valid_length, nd.NDArray) \
                else nd.array(valid_length)
            for i in range(length):
                alive = (vl > float(i)).astype(outputs[i].dtype)
                shape = (-1,) + (1,) * (len(outputs[i].shape) - 1)
                outputs[i] = outputs[i] * alive.reshape(shape)
            # per-sequence last-valid state: one-hot select over
            # [begin] + steps, so valid_length 0 (an all-padding row)
            # returns the untouched begin state
            final = []
            for k in range(len(states)):
                stacked = nd.stack(begin[k],
                                   *[s[k] for s in step_states], axis=0)
                sel = nd.one_hot(vl, depth=length + 1)  # (B, T+1)
                sshape = (length + 1, -1) + (1,) * (len(states[k].shape) - 1)
                w = nd.transpose(sel, axes=(1, 0)).reshape(sshape)
                final.append(nd.sum(stacked * w.astype(stacked.dtype),
                                    axis=0))
            states = final
        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states

    def forward(self, inputs, states):
        self._counter += 1
        self._pre_forward(inputs, states)
        return self.hybrid_forward(None, inputs, states)


class RNNCell(RecurrentCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0, prefix=None, params=None,
                 i2h_weight_initializer=None, h2h_weight_initializer=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get("i2h_weight", shape=(hidden_size, input_size),
                                              allow_deferred_init=True, init=i2h_weight_initializer)
            self.h2h_weight = self.params.get("h2h_weight", shape=(hidden_size, hidden_size),
                                              init=h2h_weight_initializer)
            self.i2h_bias = self.params.get("i2h_bias", shape=(hidden_size,), init=init_mod.Zero())
            self.h2h_bias = self.params.get("h2h_bias", shape=(hidden_size,), init=init_mod.Zero())

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def _pre_forward(self, x, states):
        if not self.i2h_weight._shape_known():
            self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, **kwargs):
        from ... import ndarray as nd

        h = states[0]
        i2h = nd.FullyConnected(inputs, self.i2h_weight.data(), self.i2h_bias.data(),
                                num_hidden=self._hidden_size)
        h2h = nd.FullyConnected(h, self.h2h_weight.data(), self.h2h_bias.data(),
                                num_hidden=self._hidden_size)
        out = nd.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(RecurrentCell):
    def __init__(self, hidden_size, input_size=0, prefix=None, params=None,
                 i2h_weight_initializer=None, h2h_weight_initializer=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get("i2h_weight", shape=(4 * hidden_size, input_size),
                                              allow_deferred_init=True, init=i2h_weight_initializer)
            self.h2h_weight = self.params.get("h2h_weight", shape=(4 * hidden_size, hidden_size),
                                              init=h2h_weight_initializer)
            self.i2h_bias = self.params.get("i2h_bias", shape=(4 * hidden_size,), init=init_mod.Zero())
            self.h2h_bias = self.params.get("h2h_bias", shape=(4 * hidden_size,), init=init_mod.Zero())

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)},
                {"shape": (batch_size, self._hidden_size)}]

    def _pre_forward(self, x, states):
        if not self.i2h_weight._shape_known():
            self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, **kwargs):
        from ... import ndarray as nd

        h, c = states
        gates = (
            nd.FullyConnected(inputs, self.i2h_weight.data(), self.i2h_bias.data(),
                              num_hidden=4 * self._hidden_size)
            + nd.FullyConnected(h, self.h2h_weight.data(), self.h2h_bias.data(),
                                num_hidden=4 * self._hidden_size)
        )
        i, f, g, o = nd.split(gates, num_outputs=4, axis=-1)
        c_new = nd.sigmoid(f) * c + nd.sigmoid(i) * nd.tanh(g)
        h_new = nd.sigmoid(o) * nd.tanh(c_new)
        return h_new, [h_new, c_new]


class GRUCell(RecurrentCell):
    def __init__(self, hidden_size, input_size=0, prefix=None, params=None,
                 i2h_weight_initializer=None, h2h_weight_initializer=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get("i2h_weight", shape=(3 * hidden_size, input_size),
                                              allow_deferred_init=True, init=i2h_weight_initializer)
            self.h2h_weight = self.params.get("h2h_weight", shape=(3 * hidden_size, hidden_size),
                                              init=h2h_weight_initializer)
            self.i2h_bias = self.params.get("i2h_bias", shape=(3 * hidden_size,), init=init_mod.Zero())
            self.h2h_bias = self.params.get("h2h_bias", shape=(3 * hidden_size,), init=init_mod.Zero())

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def _pre_forward(self, x, states):
        if not self.i2h_weight._shape_known():
            self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, **kwargs):
        from ... import ndarray as nd

        h = states[0]
        gx = nd.FullyConnected(inputs, self.i2h_weight.data(), self.i2h_bias.data(),
                               num_hidden=3 * self._hidden_size)
        gh = nd.FullyConnected(h, self.h2h_weight.data(), self.h2h_bias.data(),
                               num_hidden=3 * self._hidden_size)
        rx, zx, nx = nd.split(gx, num_outputs=3, axis=-1)
        rh, zh, nh = nd.split(gh, num_outputs=3, axis=-1)
        r = nd.sigmoid(rx + rh)
        z = nd.sigmoid(zx + zh)
        n = nd.tanh(nx + r * nh)
        h_new = (1 - z) * n + z * h
        return h_new, [h_new]


class SequentialRNNCell(RecurrentCell):
    """(ref: rnn_cell.py SequentialRNNCell)"""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        infos = []
        for cell in self._children.values():
            infos.extend(cell.state_info(batch_size))
        return infos

    def begin_state(self, batch_size=0, **kwargs):
        states = []
        for cell in self._children.values():
            states.extend(cell.begin_state(batch_size, **kwargs))
        return states

    def _pre_forward(self, *args):
        return

    def hybrid_forward(self, F, inputs, states, **kwargs):
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            inputs, new = cell(inputs, states[p : p + n])
            next_states.extend(new)
            p += n
        return inputs, next_states

    def __len__(self):
        return len(self._children)


class DropoutCell(RecurrentCell):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _pre_forward(self, *args):
        return

    def hybrid_forward(self, F, inputs, states, **kwargs):
        from ... import ndarray as nd

        if self._rate > 0:
            inputs = nd.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(RecurrentCell):
    def __init__(self, base_cell):
        super().__init__(prefix=base_cell.prefix + "mod_", params=None)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return self.base_cell.begin_state(batch_size, **kwargs)

    def _pre_forward(self, *args):
        return


class ZoneoutCell(ModifierCell):
    """(ref: rnn_cell.py ZoneoutCell)"""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states, **kwargs):
        from ... import autograd, ndarray as nd

        next_output, next_states = self.base_cell(inputs, states)
        if autograd.is_training():
            def mask(p, new, old):
                m = nd.Dropout(nd.ones_like(new), p=p, mode="always")
                keep = (m > 0)
                return nd.where(keep, new, old)

            prev = self._prev_output if self._prev_output is not None else nd.zeros_like(next_output)
            if self.zoneout_outputs > 0:
                output = mask(self.zoneout_outputs, next_output, prev)
            else:
                output = next_output
            if self.zoneout_states > 0:
                next_states = [mask(self.zoneout_states, ns, s)
                               for ns, s in zip(next_states, states)]
        else:
            output = next_output
        self._prev_output = output
        return output, next_states


class ResidualCell(ModifierCell):
    def hybrid_forward(self, F, inputs, states, **kwargs):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states


class BidirectionalCell(RecurrentCell):
    """(ref: rnn_cell.py BidirectionalCell)"""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")

    def state_info(self, batch_size=0):
        return (self._children["l_cell"].state_info(batch_size)
                + self._children["r_cell"].state_info(batch_size))

    def begin_state(self, batch_size=0, **kwargs):
        return (self._children["l_cell"].begin_state(batch_size, **kwargs)
                + self._children["r_cell"].begin_state(batch_size, **kwargs))

    def _pre_forward(self, *args):
        return

    def __call__(self, inputs, states):
        raise NotImplementedError("BidirectionalCell supports unroll() only")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as nd

        axis = layout.find("T")
        if not isinstance(inputs, (list, tuple)):
            inputs = [
                x.squeeze(axis=axis)
                for x in nd.split(inputs, num_outputs=length, axis=axis, squeeze_axis=False)
            ]
        l_cell = self._children["l_cell"]
        r_cell = self._children["r_cell"]
        begin = begin_state or self.begin_state(inputs[0].shape[0])
        nl = len(l_cell.state_info())
        if valid_length is None:
            l_out, l_states = l_cell.unroll(length, inputs, begin[:nl],
                                            layout="NTC")
            r_out, r_states = r_cell.unroll(length, list(reversed(inputs)),
                                            begin[nl:], layout="NTC")
            r_out = list(reversed(r_out))
        else:
            # padded batches: the reverse direction must see each
            # sequence's VALID prefix reversed (ref: SequenceReverse with
            # use_sequence_length), not the padding first
            vl = valid_length if isinstance(valid_length, nd.NDArray) \
                else nd.array(valid_length)
            stacked = nd.stack(*inputs, axis=0)  # (T, B, ...)
            rev = nd.SequenceReverse(stacked, vl, use_sequence_length=True)
            rev_inputs = [rev[i] for i in range(length)]
            l_out, l_states = l_cell.unroll(length, inputs, begin[:nl],
                                            layout="NTC", valid_length=vl)
            r_out, r_states = r_cell.unroll(length, rev_inputs, begin[nl:],
                                            layout="NTC", valid_length=vl)
            # un-reverse the valid prefix; masked tail is zeros either way
            r_back = nd.SequenceReverse(nd.stack(*r_out, axis=0), vl,
                                        use_sequence_length=True)
            r_out = [r_back[i] for i in range(length)]
        outputs = [nd.concat(lo, ro, dim=-1) for lo, ro in zip(l_out, r_out)]
        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, l_states + r_states
