"""Gluon recurrent layers (ref: python/mxnet/gluon/rnn/rnn_layer.py).

Parameters are stored unfused ({l,r}{layer}_{i2h,h2h}_{weight,bias}) for
reference-compatible naming, and packed into the fused scan-based RNN op at
forward; XLA folds the packing away under jit.
"""
from __future__ import annotations

import jax.numpy as jnp

from ... import initializer as init_mod
from ...ndarray.ndarray import NDArray
from ...ndarray import zeros as nd_zeros
from ...ops.nn import _GATES
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, mode, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._mode = mode
        self._gates = _GATES[mode]
        self._unfused_params = []
        ng, ni, nh = self._gates, input_size, hidden_size
        with self.name_scope():
            for layer in range(num_layers):
                for d in (["l", "r"] if bidirectional else ["l"]):
                    self._register_layer_params(layer, d, ni)
                ni = hidden_size * self._dir

    def _register_layer_params(self, layer, d, input_size):
        ng, nh = self._gates, self._hidden_size
        for kind, shape in (
            ("i2h_weight", (ng * nh, input_size)),
            ("h2h_weight", (ng * nh, nh)),
            ("i2h_bias", (ng * nh,)),
            ("h2h_bias", (ng * nh,)),
        ):
            name = f"{d}{layer}_{kind}"
            p = self.params.get(
                name, shape=shape,
                init=init_mod.Zero() if kind.endswith("bias") else None,
                allow_deferred_init=True,
            )
            self._unfused_params.append((name, p))

    def _pre_forward(self, inputs, *args):
        if self._input_size == 0:
            axis = 2 if self._layout == "TNC" else 2
            in_size = inputs.shape[axis]
            self._input_size = in_size
            ng, nh = self._gates, self._hidden_size
            for name, p in self._unfused_params:
                if not p._shape_known():
                    if name.endswith("i2h_weight"):
                        layer = int(name[1:].split("_")[0])
                        isz = in_size if layer == 0 else nh * self._dir
                        p.shape = (ng * nh, isz)

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """(ref: rnn_layer.py begin_state)"""
        states = []
        for info in self.state_info(batch_size):
            states.append(nd_zeros(info["shape"]))
        return states

    def _pack_params(self):
        """Pack unfused params through the autograd dispatcher so gradients
        flow back to the individual weights in eager mode too."""
        from ... import autograd

        names = [n for n, _ in self._unfused_params]
        arrays = [p.data() for _, p in self._unfused_params]

        def pack(*datas):
            ws = [d.reshape(-1) for d, n in zip(datas, names) if n.endswith("weight")]
            bs = [d.reshape(-1) for d, n in zip(datas, names) if n.endswith("bias")]
            return jnp.concatenate(ws + bs)

        return autograd.invoke_recorded(pack, arrays)[0]

    def hybrid_forward(self, F, inputs, states=None, **kwargs):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        batch_size = inputs.shape[1]
        skip_states = states is None
        if states is None:
            states = self.begin_state(batch_size)
        if isinstance(states, NDArray):
            states = [states]
        packed = self._pack_params()
        rnn_args = [inputs, packed] + list(states)
        out = F.RNN(
            *rnn_args, state_size=self._hidden_size, num_layers=self._num_layers,
            mode=self._mode, bidirectional=self._dir == 2, p=self._dropout,
            state_outputs=not skip_states,
        )
        if skip_states:
            output, new_states = out, []
        else:
            output, new_states = out[0], list(out[1:])
        if self._layout == "NTC":
            output = F.swapaxes(output, dim1=0, dim2=1)
        if skip_states:
            return output
        return output, new_states

    def __repr__(self):
        return (f"{type(self).__name__}({self._hidden_size}, layers={self._num_layers}, "
                f"bidirectional={self._dir == 2})")


class RNN(_RNNLayer):
    """(ref: rnn_layer.py RNN)"""

    def __init__(self, hidden_size, num_layers=1, activation="relu", layout="TNC",
                 dropout=0, bidirectional=False, input_size=0, **kwargs):
        mode = "rnn_relu" if activation == "relu" else "rnn_tanh"
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, mode, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size, self._hidden_size)}]


class LSTM(_RNNLayer):
    """(ref: rnn_layer.py LSTM)"""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape}, {"shape": shape}]


class GRU(_RNNLayer):
    """(ref: rnn_layer.py GRU)"""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size, self._hidden_size)}]
