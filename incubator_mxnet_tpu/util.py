"""Small utilities."""

def is_np_array():
    return False
