"""Symbolic API (ref: python/mxnet/symbol/)."""
from __future__ import annotations

from .symbol import Symbol, Variable, var, Group, load, load_json  # noqa: F401
from . import register as _register

_register.install_ops(globals())

# public creation aliases (ref: python/mxnet/symbol/symbol.py zeros/ones)
zeros = globals()["_zeros"]
ones = globals()["_ones"]

from . import infer  # noqa: E402,F401
from . import contrib  # noqa: E402,F401
