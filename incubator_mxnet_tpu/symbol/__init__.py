"""Placeholder."""
Symbol = None
