"""Graph shape inference.

TPU-native analog of the reference's fused shape/type inference pass
(ref: src/executor/infer_graph_attr_pass.cc + per-op FInferShape). Parameter
shapes (conv weights, BN stats, RNN packed params, ...) come from explicit
rules; everything else falls out of `jax.eval_shape` over the op function —
no hand-written output-shape formulas to drift from the kernels.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..base import dtype_np
from ..ops import nn as _nn

# name -> fn(attrs, in_shapes list[tuple|None], in_dtypes) -> {input_name: shape}
PARAM_SHAPE_RULES = {}


class ShapeInferenceError(ValueError):
    """Shape/dtype inference failed at one op boundary.

    Carries the provenance a bare eval_shape traceback lacks: the node
    name, its op type, and each input's name/shape/dtype (the reference
    reports the same triple from InferShapeAttr — ref:
    src/executor/infer_graph_attr_pass.cc error paths). `input_info` is
    ((display_name, shape_or_None, dtype_str_or_None), ...);
    `missing_inputs` distinguishes "inputs never got shapes" (MXA011)
    from "shapes present but the op rejected them" (MXA010).
    """

    def __init__(self, node_name, op_name, input_info, reason,
                 missing_inputs=False):
        self.node_name = node_name
        self.op_name = op_name
        self.input_info = tuple(input_info)
        self.missing_inputs = missing_inputs
        ins = ", ".join(
            f"{n}: {'shape ' + str(s) if s is not None else 'unknown shape'}"
            + (f" {d}" if d else "")
            for n, s, d in self.input_info) or "no inputs"
        super().__init__(
            f"shape inference failed at node {node_name!r} (op {op_name}): "
            f"{reason} [inputs: {ins}]")


def _input_info(node, op, in_shapes, in_dtypes):
    info = []
    for j, (src, _i) in enumerate(node.inputs):
        pname = op.inputs[j] if (not op.variadic and j < len(op.inputs)) \
            else f"arg{j}"
        shp = in_shapes[j] if j < len(in_shapes) else None
        dt = in_dtypes[j] if j < len(in_dtypes) else None
        info.append((f"{pname}={src.name}", shp,
                     str(np.dtype(dt)) if dt is not None else None))
    return info


def rule(name):
    def deco(fn):
        PARAM_SHAPE_RULES[name] = fn
        return fn

    return deco


@rule("FullyConnected")
def _fc(attrs, shapes, names):
    data = shapes[0]
    nh = int(attrs["num_hidden"])
    in_dim = int(np.prod(data[1:])) if attrs.get("flatten", True) else data[-1]
    return {"weight": (nh, in_dim), "bias": (nh,)}


@rule("Convolution")
def _conv(attrs, shapes, names):
    data = shapes[0]
    nf = int(attrs["num_filter"])
    g = int(attrs.get("num_group", 1) or 1)
    k = tuple(attrs["kernel"])
    return {"weight": (nf, data[1] // g) + k, "bias": (nf,)}


@rule("Deconvolution")
def _deconv(attrs, shapes, names):
    data = shapes[0]
    nf = int(attrs["num_filter"])
    g = int(attrs.get("num_group", 1) or 1)
    k = tuple(attrs["kernel"])
    return {"weight": (data[1], nf // g) + k, "bias": (nf,)}


@rule("BatchNorm")
def _bn(attrs, shapes, names):
    data = shapes[0]
    axis = int(attrs.get("axis", 1) or 1)
    c = data[axis % len(data)]
    return {"gamma": (c,), "beta": (c,), "moving_mean": (c,), "moving_var": (c,)}


@rule("LayerNorm")
def _ln(attrs, shapes, names):
    data = shapes[0]
    axis = int(attrs.get("axis", -1) if attrs.get("axis") is not None else -1)
    c = data[axis % len(data)]
    return {"gamma": (c,), "beta": (c,)}


@rule("InstanceNorm")
def _in(attrs, shapes, names):
    return {"gamma": (shapes[0][1],), "beta": (shapes[0][1],)}


@rule("Embedding")
def _emb(attrs, shapes, names):
    return {"weight": (int(attrs["input_dim"]), int(attrs["output_dim"]))}


@rule("LeakyReLU")
def _lrelu(attrs, shapes, names):
    if attrs.get("act_type") == "prelu":
        return {"gamma": (shapes[0][1],)}
    return {}


@rule("SoftmaxOutput")
def _softmax_out(attrs, shapes, names):
    # label shape derives from the scores (bidirectional inference: the
    # reference infers it backward, ref: softmax_output-inl.h InferShape) —
    # this is what lets `Module.bind(data_shapes)` work without labels at
    # predict time
    data = shapes[0]
    if attrs.get("multi_output"):
        return {"label": (data[0],) + tuple(data[2:])}
    if attrs.get("preserve_shape"):
        return {"label": tuple(data[:-1])}
    return {"label": (data[0],)}


@rule("SVMOutput")
def _svm_out(attrs, shapes, names):
    return {"label": (shapes[0][0],)}


def _same_shape_label(attrs, shapes, names):
    return {"label": tuple(shapes[0])}


for _name in ("LinearRegressionOutput", "LogisticRegressionOutput",
              "MAERegressionOutput"):
    PARAM_SHAPE_RULES[_name] = _same_shape_label


@rule("RNN")
def _rnn(attrs, shapes, names):
    data = shapes[0]  # (T, B, I)
    H = int(attrs["state_size"])
    L = int(attrs.get("num_layers", 1) or 1)
    D = 2 if attrs.get("bidirectional") else 1
    mode = attrs.get("mode", "lstm")
    psize = _nn.rnn_param_size(L, data[2], H, bool(attrs.get("bidirectional")), mode)
    out = {"parameters": (psize,), "state": (L * D, data[1], H)}
    if mode == "lstm":
        out["state_cell"] = (L * D, data[1], H)
    return out


def infer_shapes(symbol, given: dict, partial=False, dtypes_given=None,
                 errors=None, entry_out=None):
    """Walk the graph, assigning shapes to every entry.

    Returns {var_name: shape, ..., "__outputs__": [out shapes]}.

    Failure modes: by default a per-node failure raises
    `ShapeInferenceError` naming the node, its op, and the input
    shapes/dtypes that failed to unify. With `partial=True` failing nodes
    are skipped silently (partial-inference API contract). With `errors`
    set to a list, each failure is appended and inference continues —
    the graph validator's collect-everything mode. `entry_out`, when a
    dict, is filled with {(id(node), out_idx): (shape, dtype)} for
    downstream per-entry analyses.
    """
    nodes = symbol._topo_nodes()
    entry_shape = {}  # (id(node), idx) -> shape
    entry_dtype = {}
    var_shapes = {}
    key = jax.random.PRNGKey(0)
    collect = errors is not None

    for node in nodes:
        if node.is_var:
            shp = given.get(node.name) or node.misc_attrs.get("__shape__")
            if shp is not None:
                shp = tuple(int(s) for s in shp)
                entry_shape[(id(node), 0)] = shp
                var_shapes[node.name] = shp
            dt = node.misc_attrs.get("__dtype__")
            entry_dtype[(id(node), 0)] = dtype_np(dt) if dt else np.float32
            continue

        op = node.op
        in_shapes = []
        in_dtypes = []
        for src, i in node.inputs:
            in_shapes.append(entry_shape.get((id(src), i)))
            in_dtypes.append(entry_dtype.get((id(src), i), np.float32))

        # fill unknown parameter inputs from rules
        if any(s is None for s in in_shapes) and op.name in PARAM_SHAPE_RULES and in_shapes and in_shapes[0] is not None:
            rules = PARAM_SHAPE_RULES[op.name](
                {**op.attrs, **node.attrs}, in_shapes, op.inputs
            )
            for j, (src, i) in enumerate(node.inputs):
                if in_shapes[j] is None and j < len(op.inputs):
                    pname = op.inputs[j] if not op.variadic else None
                    if pname in rules:
                        in_shapes[j] = tuple(rules[pname])
                        entry_shape[(id(src), i)] = in_shapes[j]
                        if src.is_var:
                            var_shapes[src.name] = in_shapes[j]

        if any(s is None for s in in_shapes):
            if partial:
                continue
            missing = [
                op.inputs[j] if (not op.variadic and j < len(op.inputs))
                else src.name
                for j, (src, i) in enumerate(node.inputs)
                if in_shapes[j] is None
            ]
            err = ShapeInferenceError(
                node.name, op.name,
                _input_info(node, op, in_shapes, in_dtypes),
                f"no shape known for input(s) {missing} — give them via "
                f"infer_shape kwargs or a Variable(shape=...) attr",
                missing_inputs=True)
            if collect:
                errors.append(err)
                continue
            raise err

        call_attrs = dict(op.attrs)
        call_attrs.update(node.attrs)
        call_attrs.pop("name", None)
        if op.needs_rng:
            call_attrs["_rng"] = key
        if op.needs_training:
            call_attrs["_training"] = False

        structs = [jax.ShapeDtypeStruct(s, d) for s, d in zip(in_shapes, in_dtypes)]
        if not op.variadic and len(structs) < len(op.inputs):
            pad = [None] * (len(op.inputs) - len(structs))
        else:
            pad = []

        def _fn(*xs):
            return op.fn(*(list(xs) + pad), **call_attrs)

        try:
            out = jax.eval_shape(_fn, *structs)
        except Exception as e:
            if partial:
                continue
            # surface the op boundary, not jax's anonymous trace: name the
            # node, its op type, and every input's shape/dtype
            reason = str(e).strip().split("\n")[0] or type(e).__name__
            err = ShapeInferenceError(
                node.name, op.name,
                _input_info(node, op, in_shapes, in_dtypes), reason)
            if collect:
                errors.append(err)
                continue
            raise err from e
        outs = out if isinstance(out, tuple) else (out,)
        for i, o in enumerate(outs):
            entry_shape[(id(node), i)] = tuple(o.shape)
            entry_dtype[(id(node), i)] = np.dtype(o.dtype)

    if entry_out is not None:
        for k, s in entry_shape.items():
            entry_out[k] = (s, entry_dtype.get(k, np.float32))
    result = dict(var_shapes)
    outs = []
    for node, i in symbol._outputs:
        outs.append(entry_shape.get((id(node), i)))
    result["__outputs__"] = outs
    return result
