"""Symbol: declarative graph API.

TPU-native equivalent of the reference's nnvm::Symbol + graph passes
(ref: python/mxnet/symbol/symbol.py, src/nnvm/). A Symbol is a small
immutable DAG over registered ops; binding it turns the DAG into ONE pure
jax function that XLA compiles whole — the analog of GraphExecutor's
InitCachedOps+bulking (ref: src/executor/graph_executor.cc:1073,1187), with
XLA fusion playing the role of the memory planner (src/nnvm/plan_memory.cc).

Shape inference = per-op parameter-shape rules (for weight auto-shaping,
ref: FInferShape) + `jax.eval_shape` over the composed function.
"""
from __future__ import annotations

import itertools
import json
from collections import defaultdict

import numpy as np

import jax
import jax.numpy as jnp

from ..base import dtype_np
from ..ops.registry import OP_REGISTRY, OpDef

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json", "name_uid"]

_UID = defaultdict(itertools.count)


def name_uid(prefix):
    return f"{prefix}{next(_UID[prefix])}"


class _Node:
    """One graph node: a registered op application or a variable."""

    __slots__ = ("op", "name", "attrs", "inputs", "aux_inputs", "num_outputs", "misc_attrs")

    def __init__(self, op, name, attrs, inputs):
        self.op: OpDef | None = op  # None => variable
        self.name = name
        self.attrs = attrs  # static op attrs
        self.inputs = inputs  # list[(Node, int)]
        self.misc_attrs = {}  # user __attr__ like ctx_group / lr_mult
        if op is None:
            self.num_outputs = 1
        else:
            n = op.num_outputs
            full = dict(op.attrs)
            full.update(attrs)
            self.num_outputs = n(full) if callable(n) else n

    @property
    def is_var(self):
        return self.op is None


class Symbol:
    """A list of output entries over the node DAG."""

    def __init__(self, outputs):
        self._outputs = list(outputs)  # list[(Node, int)]

    # -- composition helpers ----------------------------------------------
    @property
    def name(self):
        node, idx = self._outputs[0]
        return node.name

    def __repr__(self):
        return f"<Symbol {self.name}>"

    def __iter__(self):
        for i in range(len(self._outputs)):
            yield Symbol([self._outputs[i]])

    def __len__(self):
        return len(self._outputs)

    def __getitem__(self, idx):
        if isinstance(idx, str):
            names = self.list_outputs()
            idx = names.index(idx)
        return Symbol([self._outputs[idx]])

    def get_internals(self):
        """Symbol grouping every node's outputs (ref: Symbol::GetInternals)."""
        outs = []
        for node in self._topo_nodes():
            for i in range(node.num_outputs):
                outs.append((node, i))
        return Symbol(outs)

    def get_children(self):
        node, _ = self._outputs[0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    # -- traversal ---------------------------------------------------------
    def _topo_nodes(self):
        """Topological order (inputs before consumers), deterministic."""
        order, visited, stack = [], set(), []
        for node, _ in self._outputs:
            if id(node) in visited:
                continue
            stack.append((node, False))
            while stack:
                n, processed = stack.pop()
                if processed:
                    order.append(n)
                    continue
                if id(n) in visited:
                    continue
                visited.add(id(n))
                stack.append((n, True))
                for inp, _i in reversed(n.inputs):
                    if id(inp) not in visited:
                        stack.append((inp, False))
        return order

    def list_arguments(self):
        """Variable names in traversal order (ref: Symbol::ListArguments)."""
        args = []
        aux = set(self._aux_var_ids())
        for n in self._topo_nodes():
            if n.is_var and id(n) not in aux:
                args.append(n.name)
        return args

    def list_outputs(self):
        names = []
        for node, idx in self._outputs:
            if node.is_var:
                names.append(node.name)
            elif node.num_outputs == 1:
                names.append(f"{node.name}_output")
            else:
                names.append(f"{node.name}_output{idx}")
        return names

    def _aux_var_ids(self):
        ids = []
        for n in self._topo_nodes():
            if n.is_var or not n.op.aux:
                continue
            for aux_name in n.op.aux:
                pos = n.op.inputs.index(aux_name)
                if pos < len(n.inputs):
                    src = n.inputs[pos][0]
                    if src.is_var:
                        ids.append(id(src))
        return ids

    def list_auxiliary_states(self):
        """Aux-state variable names, e.g. BN moving stats (ref:
        Symbol::ListAuxiliaryStates)."""
        aux_ids = set(self._aux_var_ids())
        return [n.name for n in self._topo_nodes() if n.is_var and id(n) in aux_ids]

    def list_inputs(self):
        return [n.name for n in self._topo_nodes() if n.is_var]

    # -- attrs -------------------------------------------------------------
    def attr(self, key):
        node, _ = self._outputs[0]
        return node.misc_attrs.get(key)

    def _set_attr(self, **kwargs):
        node, _ = self._outputs[0]
        node.misc_attrs.update(kwargs)

    def attr_dict(self):
        out = {}
        for n in self._topo_nodes():
            if n.misc_attrs:
                out[n.name] = dict(n.misc_attrs)
        return out

    def list_attr(self, recursive=False):
        """This node's attributes as strings (ref: symbol.py list_attr) —
        op parameters and user attrs in one map."""
        if recursive:
            raise DeprecationWarning(
                "Symbol.list_attr with recursive=True has been deprecated; "
                "please use attr_dict instead")
        node, _ = self._outputs[0]
        out = {}
        if not node.is_var:
            out.update({k: _attr_str(v) for k, v in node.attrs.items()})
        for k, v in node.misc_attrs.items():
            s = _misc_attr_str(v)
            if s is not None:
                out[k] = s
        return out

    def __reduce__(self):
        # pickling rides the json graph (ref: symbols pickle via handle
        # serialization); live Initializer instances in attrs degrade to
        # their dumps() form
        return (load_json, (self.tojson(),))

    # -- arithmetic --------------------------------------------------------
    def _binop(self, other, op_name, scalar_op, reverse=False):
        from . import register as _r

        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _r.invoke_symbol(op_name, (a, b), {})
        return _r.invoke_symbol(scalar_op, (self,), {"scalar": float(other)})

    def __add__(self, other):
        return self._binop(other, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binop(other, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, other):
        return self._binop(other, "broadcast_sub", "_rminus_scalar", reverse=True)

    def __mul__(self, other):
        return self._binop(other, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binop(other, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, other):
        return self._binop(other, "broadcast_div", "_rdiv_scalar", reverse=True)

    def __pow__(self, other):
        return self._binop(other, "broadcast_power", "_power_scalar")

    def __neg__(self):
        return self._binop(-1.0, "broadcast_mul", "_mul_scalar")

    def __eq__(self, other):
        if isinstance(other, (Symbol, int, float)):
            return self._binop(other, "broadcast_equal", "_equal_scalar")
        return NotImplemented

    def __ne__(self, other):
        if isinstance(other, (Symbol, int, float)):
            return self._binop(other, "broadcast_not_equal", "_not_equal_scalar")
        return NotImplemented

    def __gt__(self, other):
        return self._binop(other, "broadcast_greater", "_greater_scalar")

    def __ge__(self, other):
        return self._binop(other, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, other):
        return self._binop(other, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, other):
        return self._binop(other, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    # -- evaluation --------------------------------------------------------
    def make_eval_fn(self):
        """Compose the DAG into one pure function.

        Returns fn(arg_dict, aux_dict, rng_key, training) ->
        (outputs_tuple, new_aux_dict). This single function is what gets
        jit-compiled — the whole-graph analog of the reference's per-node
        cached engine ops.
        """
        nodes = self._topo_nodes()
        aux_names = set(self.list_auxiliary_states())
        out_entries = list(self._outputs)

        def eval_fn(arg_dict, aux_dict, rng_key, training):
            env = {}  # id(node) -> tuple of outputs
            new_aux = dict(aux_dict)
            key = rng_key
            for node in nodes:
                if node.is_var:
                    if node.name in arg_dict:
                        val = arg_dict[node.name]
                    elif node.name in new_aux:
                        val = new_aux[node.name]
                    else:
                        raise KeyError(f"unbound variable {node.name}")
                    env[id(node)] = (val,)
                    continue
                op = node.op
                in_vals = [env[id(src)][i] for src, i in node.inputs]
                call_attrs = dict(op.attrs)
                call_attrs.update(node.attrs)
                call_attrs.pop("name", None)
                if op.needs_rng:
                    if key is not None:
                        key, sub = jax.random.split(key)
                    else:
                        sub = None
                    call_attrs["_rng"] = sub
                if op.needs_training:
                    call_attrs["_training"] = training
                # pad optional missing inputs with None
                if not op.variadic and len(in_vals) < len(op.inputs):
                    in_vals = in_vals + [None] * (len(op.inputs) - len(in_vals))
                if op.aux:
                    n_primary = op.num_outputs(call_attrs) if callable(op.num_outputs) else op.num_outputs
                    from jax import lax as _lax

                    aux_pos = [op.inputs.index(a) for a in op.aux]
                    in_vals = [
                        _lax.stop_gradient(v) if j in aux_pos and v is not None else v
                        for j, v in enumerate(in_vals)
                    ]
                    res = op.fn(*in_vals, **call_attrs)
                    res = res if isinstance(res, tuple) else (res,)
                    if training and len(res) > n_primary:
                        # write back new aux values
                        for aux_name, new_val in zip(op.aux, res[n_primary:]):
                            pos = op.inputs.index(aux_name)
                            src = node.inputs[pos][0]
                            if src.is_var:
                                new_aux[src.name] = new_val
                        res = res[:n_primary]
                    env[id(node)] = res
                else:
                    res = op.fn(*in_vals, **call_attrs)
                    env[id(node)] = res if isinstance(res, tuple) else (res,)
            outs = tuple(env[id(node)][i] for node, i in out_entries)
            return outs, new_aux

        return eval_fn

    # -- shape/type inference ---------------------------------------------
    def infer_shape(self, **kwargs):
        """Infer (arg_shapes, out_shapes, aux_shapes) from given input shapes
        (ref: Symbol::InferShape). Uses parameter-shape rules + eval_shape."""
        from .infer import infer_shapes

        try:
            shapes = infer_shapes(self, kwargs)
        except Exception:
            return None, None, None
        args = [shapes.get(n) for n in self.list_arguments()]
        auxs = [shapes.get(n) for n in self.list_auxiliary_states()]
        outs = shapes["__outputs__"]
        return args, outs, auxs

    def infer_shape_partial(self, **kwargs):
        from .infer import infer_shapes

        shapes = infer_shapes(self, kwargs, partial=True)
        args = [shapes.get(n) for n in self.list_arguments()]
        auxs = [shapes.get(n) for n in self.list_auxiliary_states()]
        outs = shapes.get("__outputs__")
        return args, outs, auxs

    def infer_type(self, **kwargs):
        """Per-argument dtypes: a given dtype (or a Variable's __dtype__
        attr) wins; everything else is float32, the framework's parameter
        default (MXNet v1's own float-centric contract). Outputs take the
        promoted type of the inputs."""
        var_dtypes = {}
        for n in self._topo_nodes():
            if n.is_var and n.misc_attrs.get("__dtype__"):
                var_dtypes[n.name] = dtype_np(n.misc_attrs["__dtype__"])

        def arg_dt(name):
            if kwargs.get(name) is not None:
                return dtype_np(kwargs[name])
            return var_dtypes.get(name, np.float32)

        args = [arg_dt(n) for n in self.list_arguments()]
        auxs = [arg_dt(n) for n in self.list_auxiliary_states()]
        # outputs follow the floating compute dtype (int args like labels
        # or indices must not promote everything to float64)
        out_dt = next(
            (np.dtype(d) for d in args
             if np.issubdtype(np.dtype(d), np.floating)), np.float32)
        outs = [out_dt for _ in self.list_outputs()]
        return args, outs, auxs

    # -- static analysis ---------------------------------------------------
    def validate(self, _raise=False, **shapes):
        """Run the static graph validator over this Symbol.

        `shapes` are input shapes (same kwargs as `infer_shape`); the
        structural and hazard passes run even without them. Returns an
        `analysis.Report` of `MXA0xx` diagnostics with per-node
        provenance; `_raise=True` raises `GraphValidationError` on any
        error-severity finding. See docs/STATIC_ANALYSIS.md.
        """
        from ..analysis import validate as _validate

        report = _validate(self, shapes=shapes)
        if _raise:
            report.raise_if_errors()
        return report

    # -- binding -----------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None, stype_dict=None,
                    group2ctx=None, shared_arg_names=None, shared_exec=None,
                    shared_buffer=None, **kwargs):
        """Allocate arrays by shape inference and bind
        (ref: symbol.py:1368 simple_bind -> GraphExecutor::Init)."""
        from ..executor import Executor
        from ..context import current_context
        from ..ndarray import zeros

        ctx = ctx or current_context()
        # call the inference pass directly (not the tuple-API infer_shape,
        # which collapses every failure to (None, None, None)) so binding
        # errors name the offending node, op, and input shapes
        from .infer import infer_shapes

        try:
            shapes = infer_shapes(self, kwargs)
        except ValueError as e:
            raise ValueError(
                f"simple_bind: cannot infer shapes from {kwargs}: {e}"
            ) from e
        arg_shapes = [shapes.get(n) for n in self.list_arguments()]
        aux_shapes = [shapes.get(n) for n in self.list_auxiliary_states()]
        type_dict = type_dict or {}
        args = {}
        for name, shp in zip(self.list_arguments(), arg_shapes):
            args[name] = zeros(shp, ctx=ctx, dtype=type_dict.get(name, "float32"))
        auxs = {}
        for name, shp in zip(self.list_auxiliary_states(), aux_shapes):
            auxs[name] = zeros(shp, ctx=ctx, dtype=type_dict.get(name, "float32"))
        if isinstance(grad_req, str):
            reqs = {n: grad_req for n in args}
        elif isinstance(grad_req, dict):
            reqs = {n: grad_req.get(n, "write") for n in args}
        else:
            reqs = {n: r for n, r in zip(args, grad_req)}
        grads = {n: zeros(a.shape, ctx=ctx, dtype=str(a.dtype)) for n, a in args.items() if reqs[n] != "null"}
        return Executor(self, ctx, args, grads, reqs, auxs)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write", aux_states=None,
             group2ctx=None, shared_exec=None):
        """Bind with caller-provided arrays (ref: symbol.py:1632 bind)."""
        from ..executor import Executor
        from ..context import current_context

        ctx = ctx or current_context()
        names = self.list_arguments()
        if isinstance(args, (list, tuple)):
            args = dict(zip(names, args))
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(names, args_grad))
        aux_names = self.list_auxiliary_states()
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(aux_names, aux_states))
        if isinstance(grad_req, str):
            reqs = {n: grad_req for n in names}
        elif isinstance(grad_req, dict):
            reqs = {n: grad_req.get(n, "write") for n in names}
        else:
            reqs = dict(zip(names, grad_req))
        if args_grad is None:
            from ..ndarray import zeros

            args_grad = {
                n: zeros(args[n].shape, ctx=ctx, dtype=str(args[n].dtype))
                for n in names if reqs.get(n, "write") != "null"
            }
        return Executor(self, ctx, args, args_grad, reqs, aux_states or {})

    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx=ctx, args=kwargs, grad_req="null")
        return ex.forward()

    # -- gradient ----------------------------------------------------------
    def gradient(self, wrt):  # pragma: no cover - parity stub
        raise NotImplementedError("use Executor.backward / autograd")

    # -- serialization -----------------------------------------------------
    def tojson(self):
        """JSON graph (schema mirrors the reference's nnvm json for
        tooling/visualization parity)."""
        nodes = self._topo_nodes()
        nid = {id(n): i for i, n in enumerate(nodes)}
        out_nodes = []
        for n in nodes:
            entry = {
                "op": "null" if n.is_var else n.op.name,
                "name": n.name,
                "attrs": {k: _attr_str(v) for k, v in n.attrs.items()},
                "inputs": [[nid[id(src)], i, 0] for src, i in n.inputs],
            }
            # user attrs (__lr_mult__, ctx_group, __shape__, ...) ride a
            # SEPARATE map with native JSON types: merging them into
            # "attrs" would let a user key shadow a real op parameter on
            # load, and stringifying would mutate '4' into 4 on round-trip
            user = {}
            for k, v in n.misc_attrs.items():
                j = _misc_attr_json(v)
                if j is None and v is not None:
                    import warnings

                    warnings.warn(
                        f"symbol attr {k!r} on node {n.name!r} has an "
                        f"unserializable value ({type(v).__name__}); "
                        "dropped from the serialized graph")
                    continue
                user[k] = j
            if user:
                entry["user_attrs"] = user
            out_nodes.append(entry)
        heads = [[nid[id(node)], i, 0] for node, i in self._outputs]
        arg_nodes = [i for i, n in enumerate(nodes) if n.is_var]
        return json.dumps(
            {"nodes": out_nodes, "arg_nodes": arg_nodes, "heads": heads,
             "attrs": {"framework": "incubator_mxnet_tpu", "version": "0.1"}},
            indent=2,
        )

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    def debug_str(self):
        lines = []
        for n in self._topo_nodes():
            kind = "Variable" if n.is_var else n.op.name
            ins = ", ".join(f"{src.name}[{i}]" for src, i in n.inputs)
            lines.append(f"{kind} {n.name}({ins})")
        return "\n".join(lines)


def _attr_str(v):
    if isinstance(v, (tuple, list)):
        return "(" + ", ".join(str(x) for x in v) + ")"
    return str(v)


def _misc_attr_str(v):
    """User attr value as a display string (list_attr)."""
    from ..initializer import Initializer

    if isinstance(v, Initializer):
        try:
            return v.dumps()
        except TypeError:
            return None
    if isinstance(v, (str, int, float, bool, tuple, list)):
        return _attr_str(v)
    return None


_TUPLE_TAG = "__tuple__"


def _misc_attr_json(v):
    """User attr value as a JSON value preserving its type, or None if it
    cannot round-trip (the caller warns). Tuples are tagged so lists stay
    lists; numpy scalars become their Python value; Initializer instances
    degrade to their dumps() string, which initializer.create() parses
    back."""
    import numpy as _np

    from ..initializer import Initializer

    if isinstance(v, Initializer):
        try:
            return v.dumps()
        except TypeError:
            return None
    if isinstance(v, _np.generic):
        v = v.item()
    if isinstance(v, tuple):
        return {_TUPLE_TAG: list(v)}
    if isinstance(v, (str, int, float, bool, list, dict)) or v is None:
        try:
            json.dumps(v)  # nested unserializable values
        except (TypeError, ValueError):
            return None
        return v
    return None


def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
             init=None, stype=None, **kwargs):
    """Create a symbolic variable (ref: sym.Variable)."""
    from .. import attribute

    node = _Node(None, name, {}, [])
    scope_attrs = attribute.resolve(None)
    if scope_attrs:
        node.misc_attrs.update(scope_attrs)
    if shape is not None:
        node.misc_attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        node.misc_attrs["__dtype__"] = str(dtype)
    if lr_mult is not None:
        # both spellings like the reference; optimizers read the dunder
        # form from attr_dict (ref: symbol.py Variable -> __lr_mult__)
        node.misc_attrs["lr_mult"] = lr_mult
        node.misc_attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        node.misc_attrs["wd_mult"] = wd_mult
        node.misc_attrs["__wd_mult__"] = wd_mult
    if init is not None:
        node.misc_attrs["__init__"] = init
    if attr:
        node.misc_attrs.update(attr)
    return Symbol([(node, 0)])


var = Variable


def Group(symbols):
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def load_json(json_str):
    """Rebuild a Symbol from `tojson` output."""
    import ast

    d = json.loads(json_str)
    nodes = []
    for nd_ in d["nodes"]:
        if nd_["op"] == "null":
            node = _Node(None, nd_["name"], {}, [])
        else:
            attrs = {}
            for k, v in nd_.get("attrs", {}).items():
                try:
                    attrs[k] = ast.literal_eval(v)
                except (ValueError, SyntaxError):
                    attrs[k] = v
            node = _Node(OP_REGISTRY[nd_["op"]], nd_["name"], attrs,
                         [(nodes[i], oi) for i, oi, _ in nd_["inputs"]])
        # user attrs round-trip typed; tuples rode tagged
        for k, v in nd_.get("user_attrs", {}).items():
            if isinstance(v, dict) and set(v) == {_TUPLE_TAG}:
                v = tuple(v[_TUPLE_TAG])
            node.misc_attrs[k] = v
        nodes.append(node)
    return Symbol([(nodes[i], oi) for i, oi, _ in d["heads"]])


def load(fname):
    with open(fname) as f:
        return load_json(f.read())
