"""sym.contrib namespace (ref: python/mxnet/symbol/contrib.py).

Every `_contrib_*` registry op surfaces here under its short name, as
symbolic builders (same codegen idea as the reference's frontend generation).
"""
from __future__ import annotations

from ..ops.registry import OP_REGISTRY
from . import register as _register


def _install():
    for _name, _op in list(OP_REGISTRY.items()):
        if not _name.startswith("_contrib_"):
            continue
        short = _name[len("_contrib_"):]
        if short in globals():
            continue

        def _make(op_name):
            def f(*args, **kwargs):
                return _register.invoke_symbol(op_name, args, kwargs)
            return f

        fn = _make(_name)
        fn.__name__ = short
        fn.__doc__ = _op.fn.__doc__
        globals()[short] = fn


_install()
