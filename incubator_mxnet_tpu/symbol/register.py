"""Generated symbolic op builders.

TPU-native analog of the reference's symbol frontend codegen
(ref: python/mxnet/symbol/register.py). Each registered op gets a builder
`sym.OpName(*inputs, **attrs, name=...)`; missing parameter inputs
auto-become Variables named `{name}_{input}` exactly like the reference's
auto-created weight/bias variables.
"""
from __future__ import annotations

from ..ops.registry import OP_REGISTRY, OpDef
from .symbol import Symbol, Variable, _Node, name_uid

__all__ = ["invoke_symbol", "install_ops"]


def _should_create_input(op: OpDef, input_name: str, attrs: dict) -> bool:
    """Whether a missing input slot should auto-create a Variable."""
    if input_name not in op.optional:
        return True
    # gates mirroring reference op semantics
    if input_name == "bias":
        return not attrs.get("no_bias", False)
    if input_name == "gamma" and op.name == "LeakyReLU":
        return attrs.get("act_type") == "prelu"
    if input_name == "state_cell":
        return attrs.get("mode", "lstm") == "lstm"
    if input_name == "sequence_length":
        return bool(attrs.get("use_sequence_length", False))
    if input_name in ("data_lengths", "label_lengths"):
        return bool(attrs.get(f"use_{input_name}", False))
    return False


def invoke_symbol(op_name, args, kwargs):
    from .. import attribute, name as name_scope

    op = OP_REGISTRY[op_name]
    kwargs = dict(kwargs)
    name = kwargs.pop("name", None)
    explicit_attrs = dict(kwargs.pop("attr", None) or {})
    for mult in ("lr_mult", "wd_mult"):
        # accepted on any op like the reference; stored under both the
        # plain and dunder spellings (optimizers read the dunder form)
        v = kwargs.pop(mult, explicit_attrs.pop(mult, None))
        if v is not None:
            explicit_attrs[mult] = v
            explicit_attrs[f"__{mult}__"] = v
    scope_attrs = attribute.resolve(explicit_attrs)
    base = op.name.lower().lstrip("_")
    name = name_scope.resolve(name, base)

    if op.variadic:
        inputs = [a for a in args if isinstance(a, Symbol)]
        # variadic ops may also receive a list as first arg
        if len(args) == 1 and isinstance(args[0], (list, tuple)):
            inputs = list(args[0])
        attrs = dict(kwargs)
        entries = [s._outputs[0] for s in inputs]
        node = _Node(op, name, attrs, entries)
        if scope_attrs:
            node.misc_attrs.update(scope_attrs)
        return Symbol([(node, i) for i in range(node.num_outputs)])

    slots: list = [None] * len(op.inputs)
    attrs = {}
    positional_attrs = set()
    attr_names = list(op.attrs)
    for i, a in enumerate(args):
        if i < len(slots):
            slots[i] = a
        else:
            # positional overflow maps onto attrs in signature order,
            # mirroring the eager frontend (e.g. sym.one_hot(idx, depth))
            j = i - len(slots)
            if j >= len(attr_names):
                raise TypeError(
                    f"op {op.name}: too many positional arguments")
            attrs[attr_names[j]] = a
            positional_attrs.add(attr_names[j])
    for k, v in kwargs.items():
        if k in op.inputs:
            slots[op.inputs.index(k)] = v
        elif k in op.attrs:
            if k in positional_attrs:
                raise TypeError(f"op {op.name}: got multiple values for "
                                f"argument {k!r}")
            attrs[k] = v
        else:
            raise TypeError(f"op {op.name}: unknown argument {k!r}")

    merged_attrs = dict(op.attrs)
    merged_attrs.update(attrs)

    entries = []
    for i, s in enumerate(slots):
        in_name = op.inputs[i]
        if s is None:
            if not _should_create_input(op, in_name, merged_attrs):
                # truncate trailing missing optionals
                continue
            aux = in_name in op.aux
            v = Variable(f"{name}_{in_name}")
            if scope_attrs:
                # the op's attrs reach its auto-created params too
                # (ref: conv attr= stamps conv_weight/conv_bias)
                v._set_attr(**scope_attrs)
            s = v
        if not isinstance(s, Symbol):
            raise TypeError(f"op {op.name}: input {in_name} must be a Symbol, got {type(s)}")
        entries.append(s._outputs[0])

    node = _Node(op, name, attrs, entries)
    if scope_attrs:
        node.misc_attrs.update(scope_attrs)
    return Symbol([(node, i) for i in range(node.num_outputs)])


def _make_builder(opdef: OpDef, public_name: str):
    def builder(*args, **kwargs):
        return invoke_symbol(public_name, args, kwargs)

    builder.__name__ = public_name
    builder.__doc__ = (opdef.fn.__doc__ or "") + "\n(symbolic builder)"
    return builder


def install_ops(module_dict):
    for name, opdef in OP_REGISTRY.items():
        if name not in module_dict:
            module_dict[name] = _make_builder(opdef, name)
