"""HBM memory ledger: role-tagged live-bytes accounting for NDArrays.

The reference tracks allocations through its storage managers
(ref: src/storage/pooled_storage_manager.h) and can answer "what is
resident and why"; under JAX the buffers belong to PJRT, so this ledger
reconstructs the framework-side view: every tracked NDArray contributes
its bytes to a per-role total (params / grads / optimizer_state /
activations / kv_buffers), release is automatic via weakref death (or
explicit, for buffers donated to XLA before the Python object dies).

Three consumers ride the accounting:

- gauges `mxtpu_ledger_live_bytes{role=}` and `mxtpu_ledger_peak_bytes`,
  with peak attribution: `peak_info()` names the span (and phase tag)
  active when the high-watermark was set — the "what allocated at the
  peak" answer ROADMAP's bandwidth work needs.
- a leak heuristic: `step_sample()` (driven from the Trainer step
  boundary every `MXNET_TELEMETRY_LEDGER_INTERVAL` steps) fires a
  `memory_leak_suspect` flight event after `MXNET_TELEMETRY_LEAK_WINDOW`
  monotonically growing samples; any non-growing sample re-arms it, so
  a steady-state loop never trips.
- Perfetto: when MXTPU_TRACE_DIR tracing is active each sample is also
  written to the trace stream as a `kind="mem"` record, rendered by
  `tools/trace_merge.py --memory` as a counter track beside the spans.

Every entry point returns immediately while telemetry is disabled (no
registry writes, no recorder events); weakref callbacks from entries
tracked while enabled keep the *internal* byte counts consistent but
also skip the registry when the switch is off.
"""
from __future__ import annotations

import time
import weakref

from .. import config as _config
from ..analysis.sanitizers import san_lock
from .metrics import REGISTRY
from .spans import current_span
from . import distributed as _distributed
from . import recorder as _recorder

__all__ = ["track", "untrack", "donate", "live_bytes", "peak_info",
           "step_sample", "samples", "reset", "ROLES"]

ROLES = ("params", "grads", "optimizer_state", "activations", "kv_buffers",
         "embedding")

LIVE_BYTES = "mxtpu_ledger_live_bytes"
_LIVE_HELP = ("Live NDArray bytes tracked by the HBM ledger, by role "
              "(params/grads/optimizer_state/activations/kv_buffers).")
PEAK_BYTES = "mxtpu_ledger_peak_bytes"
_PEAK_HELP = ("High-watermark of ledger-tracked live bytes; "
              "ledger.peak_info() names the span active at the peak.")
LEAKS_TOTAL = "mxtpu_ledger_leak_events_total"
_LEAKS_HELP = ("Leak-heuristic firings: the tracked live set grew for "
               "MXNET_TELEMETRY_LEAK_WINDOW consecutive samples.")

_MAX_SAMPLES = 4096

_lock = san_lock("telemetry.ledger")
_entries = {}        # token (weakref | int) -> (role, nbytes, obj_id, ref)
_by_id = {}          # id(obj) -> token
_by_role = {}        # role -> live bytes
_total = 0
_peak = 0
_peak_span = None
_peak_breakdown = {}
_samples = []        # [(ts_ns, step, {role: bytes}, total)]
_growth_run = 0
_last_total = None

_enabled_fn = None


def _on():
    global _enabled_fn
    fn = _enabled_fn
    if fn is None:
        from . import enabled as fn
        _enabled_fn = fn
    return fn()


def _nbytes(obj):
    """Per-device footprint of `obj`: for an array committed to a mesh
    this is the addressable (local-shard) bytes on the most loaded
    device, NOT the global logical nbytes — a ZeRO-sharded optimizer
    state costs 1/N of its logical size per device and the HBM ledger
    must show that saving (a replicated array still reports full size:
    every device holds a whole copy)."""
    data = getattr(obj, "_data", obj)
    try:
        shards = getattr(data, "addressable_shards", None)
        if shards:
            per_device = {}
            for s in shards:
                per_device[s.device] = (per_device.get(s.device, 0)
                                        + int(s.data.nbytes))
            return max(per_device.values())
        return int(getattr(data, "nbytes", 0))
    except (TypeError, RuntimeError):
        # tracers, deleted/donated buffers, non-jax arrays mid-teardown
        try:
            return int(getattr(data, "nbytes", 0))
        except TypeError:
            return 0


def _add_locked(role, nbytes):
    """Caller holds _lock. Returns True when a new peak was set."""
    global _total, _peak, _peak_span, _peak_breakdown
    _by_role[role] = _by_role.get(role, 0) + nbytes
    _total += nbytes
    if nbytes > 0 and _total > _peak:
        _peak = _total
        sp = current_span()
        if sp is not None and getattr(sp, "name", None):
            tag = (sp.tags or {}).get("phase")
            _peak_span = f"{sp.name}[{tag}]" if tag else sp.name
        else:
            _peak_span = None
        _peak_breakdown = dict(_by_role)
        return True
    return False


def _publish(role, new_peak):
    REGISTRY.gauge(LIVE_BYTES, _LIVE_HELP).set(_by_role.get(role, 0),
                                               role=role)
    if new_peak:
        REGISTRY.gauge(PEAK_BYTES, _PEAK_HELP).set_max(_peak)


def track(obj, role):
    """Start accounting `obj` (NDArray, raw array, or a tuple/list of
    them — optimizer states come as tuples) under `role`. Bytes are
    released automatically when the object is collected, or explicitly
    via untrack()/donate(). Returns the number of bytes tracked."""
    if not _on():
        return 0
    if isinstance(obj, (tuple, list)):
        return sum(track(o, role) for o in obj)
    if obj is None:
        return 0
    nbytes = _nbytes(obj)
    if nbytes <= 0:
        return 0
    obj_id = id(obj)
    ref = None
    try:
        ref = weakref.ref(obj, _dead)
        hash(ref)  # a weakref hashes via its referent...
        token = ref
    except TypeError:
        # ...and raw jax Arrays (fused optimizer states) are weakref-able
        # but UNhashable — key those entries by id and keep a ref with an
        # id-based death callback alive inside the entry instead
        token = obj_id
        try:
            ref = weakref.ref(obj, lambda _r, _i=obj_id: _dead_id(_i))
        except TypeError:
            ref = None
    with _lock:
        if obj_id in _by_id:
            return 0  # already tracked; first role wins
        _entries[token] = (role, nbytes, obj_id, ref)
        _by_id[obj_id] = token
        new_peak = _add_locked(role, nbytes)
    _publish(role, new_peak)
    return nbytes


def _release_token(token):
    with _lock:
        entry = _entries.pop(token, None)
        if entry is None:
            return None
        role, nbytes, obj_id = entry[:3]
        _by_id.pop(obj_id, None)
        _add_locked(role, -nbytes)
    return role, nbytes


def _dead(ref):
    released = _release_token(ref)
    if released is not None and _on():
        _publish(released[0], False)


def _dead_id(obj_id):
    """Death callback for id-keyed entries (unhashable referents)."""
    with _lock:
        token = _by_id.get(obj_id)
    if token is None:
        return
    released = _release_token(token)
    if released is not None and _on():
        _publish(released[0], False)


def untrack(obj):
    """Stop accounting `obj` (idempotent). Returns bytes released."""
    if isinstance(obj, (tuple, list)):
        return sum(untrack(o) for o in obj)
    with _lock:
        token = _by_id.get(id(obj))
    if token is None:
        return 0
    released = _release_token(token)
    if released is None:
        return 0
    if _on():
        _publish(released[0], False)
    return released[1]


def donate(obj):
    """Release `obj`'s bytes NOW: its buffer was donated to an XLA
    computation, so the device memory is gone even while the Python
    object lingers (jax donate_argnums semantics)."""
    return untrack(obj)


def live_bytes(role=None):
    """Current tracked bytes, for one role or in total."""
    with _lock:
        if role is None:
            return _total
        return _by_role.get(role, 0)


def peak_info():
    """The high-watermark: bytes, the span active when it was set (None
    when outside any span), and the per-role breakdown at that moment."""
    with _lock:
        return {"peak_bytes": _peak, "span": _peak_span,
                "breakdown": dict(_peak_breakdown)}


def step_sample(step):
    """Sample the live set at a step boundary: refresh role gauges, feed
    the leak heuristic, and mirror to the trace stream when distributed
    tracing is on. Driven by memory.step_boundary every
    MXNET_TELEMETRY_LEDGER_INTERVAL steps."""
    global _growth_run, _last_total
    if not _on():
        return
    with _lock:
        role_bytes = {r: _by_role.get(r, 0) for r in ROLES}
        for extra in _by_role:
            if extra not in role_bytes:
                role_bytes[extra] = _by_role[extra]
        total = _total
        _samples.append((time.time_ns(), int(step), role_bytes, total))
        del _samples[:-_MAX_SAMPLES]
        leak_window = int(_config.get("MXNET_TELEMETRY_LEAK_WINDOW"))
        fired = False
        if leak_window > 0:
            if _last_total is not None and total > _last_total:
                _growth_run += 1
            else:
                _growth_run = 0
            _last_total = total
            if _growth_run >= leak_window:
                fired = True
                run = _growth_run
                _growth_run = 0  # re-arm: fire again only after a new run
    g = REGISTRY.gauge(LIVE_BYTES, _LIVE_HELP)
    for role, b in role_bytes.items():
        g.set(b, role=role)
    REGISTRY.gauge(PEAK_BYTES, _PEAK_HELP).set_max(_peak)
    if fired:
        REGISTRY.counter(LEAKS_TOTAL, _LEAKS_HELP).inc()
        _recorder.log_event(
            "memory_leak_suspect", step=int(step), total_bytes=int(total),
            growing_samples=run,
            roles={r: int(b) for r, b in sorted(role_bytes.items()) if b})
    if _distributed.trace_active():
        _distributed.record_span({
            "kind": "mem", "name": "hbm_ledger", "ts": time.time_ns(),
            "bytes": {r: int(b) for r, b in role_bytes.items()},
            "total": int(total)})


def samples():
    """Copy of the retained step samples:
    [(ts_ns, step, {role: bytes}, total_bytes), ...]."""
    with _lock:
        return list(_samples)


def reset():
    """Forget everything tracked (tests). Live objects stay alive; their
    later weakref deaths find no entry and are no-ops."""
    global _total, _peak, _peak_span, _peak_breakdown, _growth_run, \
        _last_total
    with _lock:
        _entries.clear()
        _by_id.clear()
        _by_role.clear()
        _total = 0
        _peak = 0
        _peak_span = None
        _peak_breakdown = {}
        del _samples[:]
        _growth_run = 0
        _last_total = None
