"""Always-on flight recorder: a black box for post-mortem debugging.

A fixed-size ring buffer of structured events — span boundaries, RPC
retries, reconnects, quorum evictions, checkpoint writes, injected
faults — that records continuously at ~zero cost and is only ever *read*
when something dies. On an uncaught exception (process or thread), on
retry exhaustion, or when `resilience` evicts a rank, the ring is dumped
as one JSON file together with a full metrics snapshot and the resolved
config knobs: everything needed to reconstruct the last N events before
the failure without having had DEBUG logging on.

Lock-free under the GIL: each event claims a monotonically increasing
sequence number from `itertools.count()` (a single atomic bytecode) and
stores `(seq, event)` into `slots[seq % capacity]` — one list-item store,
no lock, no allocation beyond the event dict itself. A reader sorts the
occupied slots by seq; a slot being overwritten mid-snapshot yields a
newer event, never a torn one.

Knobs: `MXTPU_FLIGHT_RECORDER_EVENTS` (capacity; 0 disables),
`MXTPU_FLIGHT_RECORDER_DIR` (dump destination, falls back to
`MXTPU_TRACE_DIR`; empty = never write files, the ring still records),
`MXTPU_FLIGHT_RECORDER_MAX_DUMPS` (per-process dump cap).
"""
from __future__ import annotations

import itertools
import json
import os
import re
import sys
import threading
import time

from ..analysis.sanitizers import san_lock

__all__ = [
    "FlightRecorder", "log_event", "snapshot", "dump", "recording",
    "refresh_from_env", "install_hooks",
]

_DUMPS_TOTAL = "mxtpu_flight_recorder_dumps_total"
_DUMPS_HELP = ("Post-mortem flight-recorder dump files written, by reason "
               "(uncaught-exception, retry-exhausted-*, eviction, ...).")


class FlightRecorder:
    """The ring itself — usable standalone in tests; the module-level
    `log_event()`/`snapshot()`/`dump()` drive one process-wide instance."""

    __slots__ = ("capacity", "_slots", "_seq")

    def __init__(self, capacity):
        self.capacity = int(capacity)
        self._slots = [None] * self.capacity
        self._seq = itertools.count()

    def record(self, event):
        seq = next(self._seq)
        self._slots[seq % self.capacity] = (seq, event)
        return seq

    def snapshot(self):
        """Events currently in the ring, oldest first."""
        held = [s for s in list(self._slots) if s is not None]
        held.sort()
        return [event for _seq, event in held]

    def total_recorded(self):
        """Events ever recorded (>= len(snapshot()) once wrapped)."""
        held = [seq for seq in (s[0] for s in list(self._slots) if s)] or [-1]
        return max(held) + 1


_state_lock = san_lock("telemetry.recorder_state")
_ring = None          # FlightRecorder, False when capacity == 0, None unresolved
_dump_lock = san_lock("telemetry.recorder_dump")
_dumps_written = 0
_hooks_installed = False


def _get_ring():
    r = _ring
    if r is None:
        from .. import config as _config

        with _state_lock:
            if _ring is None:
                cap = _config.get("MXTPU_FLIGHT_RECORDER_EVENTS")
                globals()["_ring"] = FlightRecorder(cap) if cap > 0 else False
                if _ring:
                    install_hooks()
            r = _ring
    return r


def recording():
    """Whether the ring is active (capacity > 0)."""
    return bool(_get_ring())


def refresh_from_env():
    """Re-resolve the recorder knobs and start an empty ring (tests that
    monkeypatch env). Does not uninstall exception hooks — they are
    idempotent and chain to the previous hook anyway."""
    global _ring, _dumps_written
    with _state_lock:
        _ring = None
        _dumps_written = 0
    return recording()


def log_event(kind, **fields):
    """Append one structured event to the ring. This is THE entry point
    for framework event logging — resilience retries, PS reconnects,
    evictions, checkpoint writes, injected faults all come through here,
    so the crash dump and any future structured-log exporter see one
    schema: `{"ts": epoch_ns, "kind": ..., "lane": ..., **fields}`."""
    ring = _get_ring()
    if not ring:
        return None
    from . import distributed as _distributed

    event = {"ts": time.time_ns(), "kind": kind,
             "lane": _distributed.current_lane()}
    if fields:
        event.update(fields)
    ring.record(event)
    return event


def snapshot():
    """Events currently held by the process-wide ring, oldest first."""
    ring = _get_ring()
    return ring.snapshot() if ring else []


def _dump_dir():
    from .. import config as _config

    return (_config.get("MXTPU_FLIGHT_RECORDER_DIR")
            or _config.get("MXTPU_TRACE_DIR"))


def dump(reason, extra=None):
    """Write the post-mortem dump: ring contents + metrics snapshot +
    resolved config knobs. `extra` (a JSON-serializable dict) is merged
    into the payload top-level — the SLO monitor rides it to attach the
    last-N request timelines to a breach dump. Returns the path, or None
    when no destination directory is configured (the common interactive
    case — the ring is always recording, but files appear only where a
    dump dir was chosen) or the per-process dump cap is spent."""
    global _dumps_written
    directory = _dump_dir()
    if not directory:
        return None
    from .. import config as _config

    with _dump_lock:
        if _dumps_written >= _config.get("MXTPU_FLIGHT_RECORDER_MAX_DUMPS"):
            return None
        _dumps_written += 1
        seq = _dumps_written
    from . import distributed as _distributed
    from .exporters import to_dict
    from .metrics import REGISTRY

    slug = re.sub(r"[^A-Za-z0-9._-]+", "-", str(reason))[:64] or "unknown"
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(
        directory, f"flightrec-{os.getpid()}-{seq}-{slug}.json")
    ring = _get_ring()
    payload = {
        "schema": "mxtpu-flight-recorder-v1",
        "reason": str(reason),
        "pid": os.getpid(),
        "lane": _distributed.current_lane(),
        "time_ns": time.time_ns(),
        "events_recorded_total": ring.total_recorded() if ring else 0,
        "events": ring.snapshot() if ring else [],
        "metrics": to_dict(),
        "config": {name: _config.get(name)
                   for name in sorted(_config.KNOBS)},
    }
    if extra:
        for key, value in extra.items():
            payload.setdefault(key, value)  # core schema keys win
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, separators=(",", ":"), sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    REGISTRY.counter(_DUMPS_TOTAL, _DUMPS_HELP).inc(1, reason=slug)
    return path


# -- fault hooks -------------------------------------------------------------

def install_hooks():
    """Chain the flight recorder into sys.excepthook / threading.excepthook
    so an uncaught exception anywhere dumps the black box before the
    interpreter's (or the previously installed) handler runs. Idempotent;
    installed automatically the first time the ring activates."""
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True

    prev_sys = sys.excepthook

    def _sys_hook(exc_type, exc, tb):
        try:
            log_event("uncaught_exception",
                      exc=getattr(exc_type, "__name__", str(exc_type)))
            dump("uncaught-exception")
        except Exception:
            pass  # the black box must never mask the original failure
        prev_sys(exc_type, exc, tb)

    sys.excepthook = _sys_hook

    prev_thread = threading.excepthook

    def _thread_hook(args):
        try:
            log_event(
                "uncaught_exception",
                exc=getattr(args.exc_type, "__name__", str(args.exc_type)),
                thread=args.thread.name if args.thread else "?")
            dump("uncaught-thread-exception")
        except Exception:
            pass
        prev_thread(args)

    threading.excepthook = _thread_hook
