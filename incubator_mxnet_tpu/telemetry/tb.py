"""Periodic TensorBoard logger for the telemetry registry.

Callback-protocol compatible with `contrib.tensorboard.LogMetricsCallback`
(callable on a BatchEndParam, safe to drop into a `batch_end_callback`
list), but sourcing scalars from the metrics registry instead of an
eval_metric: counters and gauges log their value, histograms log count /
rate-friendly sum / mean.
"""
from __future__ import annotations

__all__ = ["LogTelemetryCallback"]


class LogTelemetryCallback:
    """Every `interval` invocations, write each registry series as a
    TensorBoard scalar keyed `prefix/metric_name[/label=value,...]`.

    `summary_writer` may be injected (anything with add_scalar/flush);
    otherwise torch's SummaryWriter backs it, with the same ImportError
    gating as contrib.tensorboard.LogMetricsCallback.
    """

    def __init__(self, logging_dir=None, interval=1, prefix="telemetry",
                 registry=None, summary_writer=None):
        from .metrics import REGISTRY

        self.interval = max(1, int(interval))
        self.prefix = prefix
        self.registry = registry or REGISTRY
        self.step = 0
        if summary_writer is None:
            try:
                from torch.utils.tensorboard import SummaryWriter
            except ImportError as e:
                raise ImportError(
                    "LogTelemetryCallback needs a tensorboard writer; "
                    "install `tensorboard` (torch.utils.tensorboard "
                    "backend) or inject summary_writer=") from e
            summary_writer = SummaryWriter(logging_dir)
        self.summary_writer = summary_writer

    def _tag(self, name, labels):
        if not labels:
            return f"{self.prefix}/{name}"
        body = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
        return f"{self.prefix}/{name}/{body}"

    def __call__(self, param=None):
        """BatchEndParam/epoch-end callback protocol; `param` is unused —
        the registry is the data source."""
        self.step += 1
        if self.step % self.interval:
            return
        for metric in self.registry.collect():
            for labels, child in metric.series():
                tag = self._tag(metric.name, labels)
                if metric.kind == "histogram":
                    _b, _n, count, total, _mn, _mx = child.snapshot()
                    self.summary_writer.add_scalar(
                        f"{tag}/count", count, self.step)
                    self.summary_writer.add_scalar(
                        f"{tag}/sum", total, self.step)
                    if count:
                        self.summary_writer.add_scalar(
                            f"{tag}/mean", total / count, self.step)
                else:
                    self.summary_writer.add_scalar(
                        tag, child.value, self.step)
        self.summary_writer.flush()

    def flush(self):
        self.summary_writer.flush()

    def close(self):
        if hasattr(self.summary_writer, "close"):
            self.summary_writer.close()
