"""Compile/retrace registry: every jit executable, and why it re-traced.

Compile time is the framework's cold-start cost (ROADMAP item 4: 81-111s
per model) and a silent retrace is how it comes back at step N. This
registry records every (function, abstract-shape-signature) pair the
framework jits — graph hash, compile wall time, XLA cost stats where the
caller has them (fused.GluonTrainStep.cost_stats) — and distinguishes
two events:

- first signature for a function  -> `mxtpu_compiles_total{fn=}` (+ a
  `compile` flight event);
- a NEW signature for an already-seen function -> additionally
  `mxtpu_retraces_total{fn=}` and a `retrace` flight event naming the
  shape delta (old vs new, per differing position).

Re-registering an already-seen signature is free and counts nothing, so
the retrace counter increments exactly once per new signature — a
steady-shape second epoch registers zero events. The (fn, signature,
graph_hash) triple is the observational groundwork for a persistent
compile-cache key (ROADMAP item 4).

All entry points return immediately while telemetry is disabled.
"""
from __future__ import annotations

import hashlib
import threading
import time

from .metrics import REGISTRY
from . import recorder as _recorder

__all__ = ["register", "register_cached", "seen", "annotate",
           "signature_of", "snapshot", "reset", "COMPILES_TOTAL",
           "RETRACES_TOTAL", "COMPILE_SECONDS"]

COMPILES_TOTAL = "mxtpu_compiles_total"
_COMPILES_HELP = ("New (function, shape-signature) pairs registered with "
                  "the compile registry, by fn.")
RETRACES_TOTAL = "mxtpu_retraces_total"
_RETRACES_HELP = ("Recompilations of an already-seen function with a NEW "
                  "shape signature, by fn (each also logs a retrace flight "
                  "event naming the shape delta).")
COMPILE_SECONDS = "mxtpu_compile_seconds"
_COMPILE_S_HELP = ("Trace+compile wall time observed for first-seen shape "
                   "signatures, by fn.")
# compiles run seconds-to-minutes, far past the latency default buckets
COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                   60.0, 120.0, 300.0)

_lock = threading.Lock()
_fns = {}   # fn -> {"order": [sig, ...], "entries": {sig: info}, "retraces": n}

_enabled_fn = None


def _on():
    global _enabled_fn
    fn = _enabled_fn
    if fn is None:
        from . import enabled as fn
        _enabled_fn = fn
    return fn()


def _dtype_name(dt):
    """Canonical dtype spelling: np.dtype('float32').name == 'float32'
    whether the caller held a dtype object, a scalar type, or a string —
    `str(np.float32)` would spell the same dtype three different ways
    and fork the cross-process cache key."""
    name = getattr(dt, "name", None)
    if isinstance(name, str):
        return name
    return getattr(dt, "__name__", None) or str(dt)


def _canon(v):
    """One value -> a canonical, repr-stable signature element. Dicts
    hash by SORTED key (insertion order is a per-process accident);
    containers recurse; arrays collapse to (shape, dtype-name)."""
    if v is None:
        return None
    if isinstance(v, type):
        # scalar types (np.float32) expose a class-level `shape`
        # descriptor — canonicalize dtype-like classes by name instead
        return ("dtype", _dtype_name(v))
    name = getattr(v, "name", None)
    if isinstance(name, str) and getattr(v, "kind", None) is not None:
        # np.dtype instances (duck-typed: .name + .kind, no numpy import)
        return ("dtype", name)
    if hasattr(v, "shape"):
        dt = getattr(v, "dtype", None)
        return (tuple(v.shape), _dtype_name(dt) if dt is not None else "?")
    if isinstance(v, dict):
        return ("dict", tuple(
            (str(k), _canon(v[k])) for k in sorted(v, key=str)))
    if isinstance(v, (list, tuple)):
        return (type(v).__name__, tuple(_canon(x) for x in v))
    if isinstance(v, (bool, int, float, str, bytes)):
        return (type(v).__name__, repr(v))
    return (type(v).__name__,)


def signature_of(*arrays):
    """Canonical abstract signature of positional args: (shape,
    dtype-name) per array, sorted-key tuples for dicts, values for
    plain scalars (None placeholders pass through). repr() of the
    result is identical across processes for the same program — the
    property the persistent compile-cache key requires."""
    return tuple(_canon(a) for a in arrays)


def _fmt_sig(sig):
    s = repr(sig)
    return s if len(s) <= 256 else s[:253] + "..."


def _sig_delta(old, new):
    """Human-readable positional diff between two signatures."""
    if (isinstance(old, tuple) and isinstance(new, tuple)
            and len(old) == len(new)):
        diffs = [f"arg{i}: {o!r} -> {n!r}"
                 for i, (o, n) in enumerate(zip(old, new)) if o != n]
        if diffs:
            return "; ".join(diffs)[:512]
    return f"{_fmt_sig(old)} -> {_fmt_sig(new)}"


def seen(fn, signature):
    """True when (fn, signature) is already registered — callers use this
    to decide whether a dispatch they are about to time is a compile."""
    if not _on():
        return True
    with _lock:
        entry = _fns.get(fn)
        return entry is not None and signature in entry["entries"]


def register(fn, signature, compile_s=None, graph_hash=None, cost=None):
    """Record that `fn` was traced/compiled for `signature`. Returns
    "new" (first signature for fn), "retrace" (new signature, fn already
    seen — counted and flight-logged), or "seen" (no-op)."""
    if not _on():
        return None
    if graph_hash is None:
        # signature-derived default; callers with a real graph fingerprint
        # (jaxpr hash) pass their own — this is the compile-cache-key seed
        graph_hash = hashlib.sha1(repr((fn, signature)).encode()).hexdigest()[:16]
    with _lock:
        entry = _fns.setdefault(
            fn, {"order": [], "entries": {}, "retraces": 0})
        if signature in entry["entries"]:
            return "seen"
        prev = entry["order"][-1] if entry["order"] else None
        entry["order"].append(signature)
        entry["entries"][signature] = {
            "graph_hash": graph_hash, "compile_s": compile_s, "cost": cost,
            "ts_ns": time.time_ns()}
        is_retrace = prev is not None
        if is_retrace:
            entry["retraces"] += 1
        n_sigs = len(entry["entries"])
    REGISTRY.counter(COMPILES_TOTAL, _COMPILES_HELP).inc(fn=fn)
    if compile_s is not None:
        REGISTRY.histogram(COMPILE_SECONDS, _COMPILE_S_HELP,
                           buckets=COMPILE_BUCKETS).observe(
            float(compile_s), fn=fn)
    if is_retrace:
        REGISTRY.counter(RETRACES_TOTAL, _RETRACES_HELP).inc(fn=fn)
        _recorder.log_event(
            "retrace", fn=fn, delta=_sig_delta(prev, signature),
            signatures=n_sigs, graph_hash=graph_hash,
            compile_s=compile_s)
        return "retrace"
    _recorder.log_event(
        "compile", fn=fn, signature=_fmt_sig(signature),
        graph_hash=graph_hash, compile_s=compile_s)
    return "new"


def register_cached(fn, signature, graph_hash=None):
    """Record that `fn` resolved `signature` from the persistent
    compile cache: the signature becomes known (so `seen()` is True and
    snapshot() lists it with cached=True) WITHOUT counting a compile or
    retrace — a fully-warm process must show zero compile events.
    Returns "cached", or "seen" when already registered."""
    if not _on():
        return None
    if graph_hash is None:
        graph_hash = hashlib.sha1(
            repr((fn, signature)).encode()).hexdigest()[:16]
    with _lock:
        entry = _fns.setdefault(
            fn, {"order": [], "entries": {}, "retraces": 0})
        if signature in entry["entries"]:
            return "seen"
        entry["order"].append(signature)
        entry["entries"][signature] = {
            "graph_hash": graph_hash, "compile_s": None, "cost": None,
            "cached": True, "ts_ns": time.time_ns()}
    _recorder.log_event(
        "compile_cache_hit", fn=fn, signature=_fmt_sig(signature),
        graph_hash=graph_hash)
    return "cached"


def annotate(fn, signature=None, compile_s=None, cost=None):
    """Attach late-arriving data (XLA cost stats, a measured compile
    time) to a registered signature — the most recent one when
    `signature` is None."""
    if not _on():
        return False
    with _lock:
        entry = _fns.get(fn)
        if entry is None or not entry["order"]:
            return False
        sig = signature if signature is not None else entry["order"][-1]
        info = entry["entries"].get(sig)
        if info is None:
            return False
        if compile_s is not None:
            info["compile_s"] = float(compile_s)
        if cost is not None:
            info["cost"] = dict(cost)
    return True


def snapshot():
    """{fn: {"signatures": n, "retraces": n, "entries": [info...]}} —
    entries carry graph_hash / compile_s / cost / ts_ns per signature."""
    with _lock:
        out = {}
        for fn, entry in _fns.items():
            out[fn] = {
                "signatures": len(entry["entries"]),
                "retraces": entry["retraces"],
                "entries": [
                    {"signature": _fmt_sig(sig), **entry["entries"][sig]}
                    for sig in entry["order"]],
            }
        return out


def reset():
    """Forget every registered executable (tests)."""
    with _lock:
        _fns.clear()
