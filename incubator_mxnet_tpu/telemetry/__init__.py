"""Unified runtime telemetry: metrics registry + tracing spans + exporters.

The engine/executor hot path is one fused XLA program, so framework
observability lives host-side: this package instruments Executor
forward/backward, gluon.Trainer.step, kvstore push/pull (bytes + latency),
gluon DataLoader batch fetch, engine.waitall barriers, and per-device
memory watermarks, all feeding one thread-safe registry with Prometheus
and JSON exporters.

Off by default. `MXNET_TELEMETRY=1` (or `telemetry.enable()`) turns it on;
while off every instrumented site short-circuits through no-op stubs —
`span()` hands back a shared do-nothing context manager and the module
helpers return before touching the registry, so the cost is one cached
boolean check per site.

    import incubator_mxnet_tpu as mx
    mx.telemetry.enable()
    ... train ...
    print(mx.telemetry.prometheus_text())
    mx.telemetry.dump_json("metrics.json")

`MXNET_TELEMETRY_PORT=9090` additionally serves /metrics for scrapers.
"""
from __future__ import annotations

import threading

from .. import config as _config
from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, REGISTRY, DEFAULT_BUCKETS,
    BYTES_BUCKETS,
)
from .names import (  # noqa: F401
    METRIC_NAMES, SPAN_NAMES, is_registered_metric, is_registered_span,
)
from . import distributed  # noqa: F401
from . import recorder  # noqa: F401
from .spans import Span, NoopSpan, NOOP_SPAN, current_span, SPAN_HISTOGRAM  # noqa: F401
from .recorder import log_event  # noqa: F401
from .exporters import (  # noqa: F401
    dump_json, prometheus_text, start_http_server, to_dict,
    register_debug_handler, unregister_debug_handler,
)
from .memory import sample_device_memory, step_boundary  # noqa: F401
from . import stepstats  # noqa: F401
from . import ledger  # noqa: F401
from . import compilereg  # noqa: F401
from . import slo  # noqa: F401
from .tb import LogTelemetryCallback  # noqa: F401

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "DEFAULT_BUCKETS", "BYTES_BUCKETS",
    "Span", "NoopSpan", "current_span", "span",
    "distributed", "recorder", "log_event",
    "dump_json", "prometheus_text", "start_http_server", "to_dict",
    "register_debug_handler", "unregister_debug_handler",
    "sample_device_memory", "step_boundary", "LogTelemetryCallback",
    "stepstats", "ledger", "compilereg", "slo",
    "enabled", "enable", "disable", "refresh_from_env",
    "counter", "gauge", "histogram", "inc", "observe", "set_gauge",
    "METRIC_NAMES", "SPAN_NAMES", "is_registered_metric",
    "is_registered_span",
]

_state_lock = threading.Lock()
_enabled = None  # None = not yet resolved from MXNET_TELEMETRY
_http_server = None


def enabled():
    """Master switch. First call resolves MXNET_TELEMETRY (and starts the
    /metrics endpoint when MXNET_TELEMETRY_PORT is set); afterwards this
    is a cached-boolean read — the whole cost of the disabled path."""
    e = _enabled
    if e is None:
        e = _set_enabled(bool(_config.get("MXNET_TELEMETRY")))
    return e


def _set_enabled(value):
    global _enabled
    with _state_lock:
        _enabled = bool(value)
        if _enabled:
            _maybe_start_http()
        return _enabled


def _maybe_start_http():
    global _http_server
    if _http_server is not None:
        return
    port = _config.get("MXNET_TELEMETRY_PORT")
    if port > 0:
        _http_server = start_http_server(port)


def enable(port=None):
    """Turn telemetry on for this process (overrides the env default).
    `port` additionally starts a /metrics endpoint there — bound BEFORE
    the enable flag flips, so an explicit port wins over
    MXNET_TELEMETRY_PORT (processes sharing an env, e.g. PS servers on a
    rank-offset port, would otherwise race onto the base port)."""
    global _http_server
    if port is not None and _http_server is None:
        with _state_lock:
            if _http_server is None:
                _http_server = start_http_server(port)
    _set_enabled(True)
    return _http_server


def disable():
    """Turn telemetry off: instrumented sites go back to the no-op stubs.
    Already-recorded metrics stay in the registry (reset it explicitly)."""
    _set_enabled(False)


def refresh_from_env():
    """Re-resolve MXNET_TELEMETRY (mainly for tests that monkeypatch env)."""
    global _enabled
    _enabled = None
    return enabled()


def span(name, **tags):
    """Timed, nestable tracing region; see spans.Span. Returns the shared
    no-op span while both telemetry and distributed tracing are off; a
    trace-only span (no registry/profiler sinks) when only
    MXTPU_TRACE_DIR is set."""
    if enabled():
        return Span(name, tags)
    if distributed.trace_active():
        return Span(name, tags, metrics=False)
    return NOOP_SPAN


# -- registry conveniences (always live; instrument through the helpers
#    below when the call must be free while disabled) -----------------------

def counter(name, help=""):
    return REGISTRY.counter(name, help)


def gauge(name, help=""):
    return REGISTRY.gauge(name, help)


def histogram(name, help="", buckets=DEFAULT_BUCKETS):
    return REGISTRY.histogram(name, help, buckets)


# -- guarded fast-path helpers for instrumented framework sites ------------

def inc(name, amount=1.0, help="", **labels):
    if not enabled():
        return
    REGISTRY.counter(name, help).inc(amount, **labels)


def observe(name, value, help="", buckets=DEFAULT_BUCKETS, **labels):
    if not enabled():
        return
    REGISTRY.histogram(name, help, buckets).observe(value, **labels)


def set_gauge(name, value, help="", **labels):
    if not enabled():
        return
    REGISTRY.gauge(name, help).set(value, **labels)
