"""Cluster-wide causal tracing: trace-context propagation + trace export.

PR 1's `span()` timed regions inside one process; this module makes those
spans CAUSAL across the PS cluster (Dapper-style context propagation,
Sigelman et al. 2010): every span carries `trace_id`/`span_id`/`parent_id`,
`ps.PSClient` attaches the current context to each RPC envelope, and the
`ParameterServer` adopts it as the parent of the child span it opens per
handled command — so one training step yields a single causally-linked
tree spanning worker `trainer.step` → `ps.client.rpc` (kvstore push) →
server `merge`/`barrier` → worker resume.

Export: when `MXTPU_TRACE_DIR` is set, every completed span is appended
to a per-process binary-framed trace file

    <dir>/trace-<pid>-<suffix>.mxtrace
    file   := MAGIC frame*
    frame  := u32_be(len) json_utf8(span record)

(one frame per span; a reader can stop at the first torn frame after a
crash and keep everything before it — same reasoning as the PS wire's
length-prefixed framing). `tools/trace_merge.py` merges the files from
all processes into one Chrome-trace/Perfetto timeline with per-rank
lanes and clock-skew correction from RPC send/recv timestamp pairs.

Lanes: each record carries a `lane` — the per-process default is
`r<MXTPU_PROCESS_ID>`, a thread may override it (`set_thread_lane`) so
single-process multi-worker harnesses (tests, tools/chaos_train.py) get
one timeline lane per simulated rank, and the server's handler threads
run under lane "server" via `remote_context`.
"""
from __future__ import annotations

import atexit
import itertools
import json
import os
import re
import secrets
import struct
import threading

__all__ = [
    "TRACE_MAGIC", "trace_active", "refresh_from_env", "new_id",
    "current_context", "remote_context", "remote_parent",
    "set_thread_lane", "current_lane", "record_span", "flush",
    "read_trace_file", "format_traceparent", "parse_traceparent",
]

TRACE_MAGIC = b"MXTRACE1"
_FRAME = struct.Struct(">I")

# span/trace ids: 16 hex chars — a per-process random prefix (collision
# avoidance across the cluster without coordination) + a monotonic
# counter (uniqueness + cheapness within the process)
_ID_PREFIX = secrets.token_hex(4)
_ID_COUNTER = itertools.count(1)

_tls = threading.local()

_state_lock = threading.Lock()
_active = None      # None = not yet resolved from MXTPU_TRACE_DIR
_writer = None      # _TraceWriter once the first span is recorded
_proc_lane = None   # cached per-process default lane


def new_id():
    """A new 16-hex-char span/trace id, unique across the cluster."""
    return f"{_ID_PREFIX}{next(_ID_COUNTER) & 0xFFFFFFFF:08x}"


# -- activation --------------------------------------------------------------

def trace_active():
    """Whether trace export is on (MXTPU_TRACE_DIR set). First call
    resolves the knob; afterwards a cached-boolean read, so the disabled
    path costs the same as disabled telemetry."""
    a = _active
    if a is None:
        from .. import config as _config

        with _state_lock:
            if _active is None:
                globals()["_active"] = bool(_config.get("MXTPU_TRACE_DIR"))
            a = _active
    return a


def refresh_from_env():
    """Re-resolve MXTPU_TRACE_DIR (tests that monkeypatch env); flushes
    and detaches any open trace file first."""
    global _active, _writer, _proc_lane
    with _state_lock:
        if _writer is not None:
            _writer.close()
        _writer = None
        _active = None
        _proc_lane = None
    return trace_active()


# -- lanes -------------------------------------------------------------------

def current_lane():
    """The timeline lane for this thread: thread override, else
    r<MXTPU_PROCESS_ID> (role-qualified for server processes)."""
    lane = getattr(_tls, "lane", None)
    if lane is not None:
        return lane
    global _proc_lane
    if _proc_lane is None:
        from .. import config as _config

        role = os.environ.get("MXTPU_ROLE", "")  # mxlint: disable=MXL007
        _proc_lane = ("server" if role == "server"
                      else f"r{_config.get('MXTPU_PROCESS_ID')}")
    return _proc_lane


def set_thread_lane(lane):
    """Override this thread's lane (None restores the process default).
    Returns the previous override — callers restore it when simulating
    multiple ranks from one process."""
    prev = getattr(_tls, "lane", None)
    _tls.lane = lane
    return prev


# -- remote (cross-process) parent context -----------------------------------

def current_context():
    """(trace_id, span_id) of the innermost active span on this thread,
    or None — what an RPC client attaches to its envelope."""
    from .spans import current_span

    sp = current_span()
    if sp is None or getattr(sp, "span_id", None) is None:
        return None
    return (sp.trace_id, sp.span_id)


def remote_parent():
    """The (trace_id, span_id) a remote peer shipped for this thread, or
    None. A root span adopts it as its parent, linking the server-side
    subtree into the client's trace."""
    return getattr(_tls, "remote", None)


class remote_context:
    """Adopt a peer's trace context (and optionally a lane) for the
    spans this thread opens inside the `with` block. `ctx` is the
    (trace_id, span_id) pair off the wire — None/missing deactivates
    cleanly so untraced requests cost nothing."""

    __slots__ = ("_ctx", "_lane", "_prev", "_prev_lane", "_set_lane")

    def __init__(self, ctx, lane=None):
        self._ctx = tuple(ctx) if ctx else None
        self._lane = lane
        self._set_lane = lane is not None

    def __enter__(self):
        self._prev = getattr(_tls, "remote", None)
        _tls.remote = self._ctx
        if self._set_lane:
            self._prev_lane = set_thread_lane(self._lane)
        return self

    def __exit__(self, *exc):
        _tls.remote = self._prev
        if self._set_lane:
            set_thread_lane(self._prev_lane)
        return False


# -- W3C traceparent interop (the gateway's external correlation seam) -------

# https://www.w3.org/TR/trace-context/: 00-<32hex trace>-<16hex parent>-<2hex>
_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def format_traceparent(trace_id, span_id):
    """Render an internal (trace_id, span_id) pair as a W3C traceparent
    header value. Internal ids are 16 hex chars; the 32-hex W3C trace-id
    field is left-padded with zeros (an inbound 32-hex id adopted by
    `parse_traceparent` round-trips unchanged). Flags are always 01
    (sampled) — a traceparent only exists while tracing is active."""
    return f"00-{str(trace_id).zfill(32)}-{span_id}-01"


def parse_traceparent(header):
    """Parse a W3C traceparent header into an internal
    (trace_id, parent_span_id) pair, or None when the header is missing
    or malformed (the request then starts a fresh trace). The 32-hex
    trace id is adopted verbatim minus redundant left zero-padding, so
    a client-minted id survives the echo and internally-minted 16-hex
    ids round-trip through `format_traceparent`."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(str(header).strip().lower())
    if m is None:
        return None
    trace_hex, parent_hex = m.group(1), m.group(2)
    if set(trace_hex) == {"0"} or set(parent_hex) == {"0"}:
        return None  # all-zero ids are invalid per the spec
    trimmed = trace_hex.lstrip("0")
    trace_id = trace_hex[-16:] if len(trimmed) <= 16 else trace_hex
    return (trace_id, parent_hex)


# -- trace file writer -------------------------------------------------------

class _TraceWriter:
    """Buffered, thread-safe appender of framed span records."""

    def __init__(self, directory, buffer_spans):
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(
            directory, f"trace-{os.getpid()}-{secrets.token_hex(3)}.mxtrace")
        self._lock = threading.Lock()
        self._buf = []
        self._cap = max(1, buffer_spans)
        self._file = open(self.path, "wb")
        self._file.write(TRACE_MAGIC)

    def add(self, record):
        with self._lock:
            self._buf.append(record)
            if len(self._buf) >= self._cap:
                self._flush_locked()

    def _flush_locked(self):
        if not self._buf or self._file is None:
            return
        chunks = []
        for rec in self._buf:
            payload = json.dumps(rec, separators=(",", ":"),
                                 sort_keys=True).encode("utf-8")
            chunks.append(_FRAME.pack(len(payload)) + payload)
        self._buf = []
        self._file.write(b"".join(chunks))
        self._file.flush()

    def flush(self):
        with self._lock:
            self._flush_locked()

    def close(self):
        with self._lock:
            self._flush_locked()
            if self._file is not None:
                self._file.close()
                self._file = None


def _sink():
    global _writer
    w = _writer
    if w is None:
        from .. import config as _config

        with _state_lock:
            if _writer is None:
                _writer = _TraceWriter(
                    _config.get("MXTPU_TRACE_DIR"),
                    _config.get("MXTPU_TRACE_BUFFER_SPANS"))
                atexit.register(_writer.close)
            w = _writer
    return w


def record_span(record):
    """Append one completed-span record to this process's trace file
    (no-op unless trace export is active)."""
    if not trace_active():
        return
    if "lane" not in record:
        record["lane"] = current_lane()
    # thread id separates concurrently-open spans (server handler threads)
    # into distinct Chrome-trace rows inside the lane
    record.setdefault("thr", threading.get_ident() % 1000000)
    _sink().add(record)


def flush():
    """Flush buffered spans to disk (tests; end-of-phase barriers)."""
    if _active and _writer is not None:
        _writer.flush()


# -- reader (used by tools/trace_merge.py and tests) -------------------------

def read_trace_file(path):
    """Decode one .mxtrace file into a list of span records. Stops at the
    first torn/truncated frame (everything before it is intact — the
    crash-tolerance the framing exists for); raises ValueError on a bad
    magic header."""
    records = []
    with open(path, "rb") as f:
        magic = f.read(len(TRACE_MAGIC))
        if magic != TRACE_MAGIC:
            raise ValueError(f"{path}: not a trace file "
                             f"(bad magic {magic!r})")
        while True:
            head = f.read(_FRAME.size)
            if len(head) < _FRAME.size:
                break
            (n,) = _FRAME.unpack(head)
            payload = f.read(n)
            if len(payload) < n:
                break  # torn tail frame: crash mid-write
            try:
                records.append(json.loads(payload.decode("utf-8")))
            except ValueError:
                break
    return records
