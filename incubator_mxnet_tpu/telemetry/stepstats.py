"""Step-time decomposition: rolling per-phase stats + anomaly events.

The reference profiler attributes operator wall time to fixed categories
(ref: src/profiler/profiler.h ProfileDomain); with the executor fused
into one XLA program the interesting decomposition is the *step
pipeline* instead: data fetch, host->device transfer, compute dispatch,
device sync, gradient exchange (allreduce / pushpull), optimizer update.
This module aggregates those phases over a rolling window
(`MXNET_TELEMETRY_STEPSTATS_WINDOW`), exposes per-phase p50/p99 gauges
(`mxtpu_step_phase_seconds{phase=,q=}`), and emits a flight-recorder
`step_anomaly` event when a step exceeds
`MXNET_TELEMETRY_ANOMALY_FACTOR` x the rolling median of recent steps —
the measurement substrate for ROADMAP's HBM-bandwidth work.

Phases are fed two ways:

- ``phase(name)`` — context manager that times a region, opens a
  ``trainer.phase`` span (so traces and flight events line up with the
  breakdown), and accumulates into the current step. Sites that nest
  phases double-count; keep phases flat.
- ``record(name, seconds)`` — for sites that already measured (the
  DataLoader fetch timer).

``step_end()`` closes the current step. The Trainer calls it at its
step boundary; fused ``GluonTrainStep`` calls it per ``__call__``.
Without an explicit total it uses wall time since the previous step end,
so the breakdown denominator is the full loop iteration — phase
coverage (phase sum / total) then measures how much of the real step
the instrumentation explains.

Everything here is a no-op while telemetry is disabled: zero registry
writes, zero recorder events (see tests/test_telemetry.py::
test_disabled_paths_hit_noop_stubs).
"""
from __future__ import annotations

import collections
import threading
import time

from .. import config as _config
from .metrics import REGISTRY
from .spans import Span
from . import distributed as _distributed
from . import recorder as _recorder

__all__ = ["phase", "record", "step_end", "snapshot", "reset",
           "PHASE_SPAN", "PHASE_GAUGE", "ANOMALIES_TOTAL"]

PHASE_SPAN = "trainer.phase"
PHASE_GAUGE = "mxtpu_step_phase_seconds"
_PHASE_HELP = ("Rolling per-phase step-time quantiles from StepStats, by "
               "phase and quantile (q=0.5/0.99); phase=total is the whole "
               "step.")
ANOMALIES_TOTAL = "mxtpu_step_anomalies_total"
_ANOM_HELP = ("Steps whose wall time exceeded MXNET_TELEMETRY_ANOMALY_FACTOR"
              " x the rolling median (each also logs a step_anomaly flight "
              "event).")

# canonical phase names (open set — these are the framework-fed ones).
# sparse_pull is the time a step BLOCKED waiting for embedding rows from
# the PS fleet: with MXTPU_SPARSE_PREFETCH the background thread absorbs
# the RPC wall time and this phase shrinks toward zero — the direct
# observatory readout of the pull/forward overlap win.
PHASES = ("data_fetch", "h2d", "sparse_pull", "dispatch", "device_sync",
          "allreduce", "pushpull", "optimizer_update")

_lock = threading.Lock()
_acc = {}            # phase -> accumulated seconds, current step
_window = None       # deque of (total_s, {phase: s}); sized lazily
_last_end = None     # perf_counter at the previous step_end
_steps = 0
_anomalies = 0

_enabled_fn = None   # resolved lazily: the package defines enabled() after
                     # this module is imported


def _on():
    global _enabled_fn
    fn = _enabled_fn
    if fn is None:
        from . import enabled as fn
        _enabled_fn = fn
    return fn()


class _NoopPhase:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_PHASE = _NoopPhase()


class _Phase:
    """Times a region, mirrors it as a trainer.phase span, and feeds the
    current step's accumulator (unless trace-only)."""

    __slots__ = ("name", "_span", "_feed", "_t0")

    def __init__(self, name, span, feed):
        self.name = name
        self._span = span
        self._feed = feed

    def __enter__(self):
        self._span.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dt = time.perf_counter() - self._t0
        if self._feed:
            record(self.name, dt)
        return self._span.__exit__(exc_type, exc, tb)


def phase(name):
    """Context manager for one step phase. No-op while both telemetry and
    distributed tracing are off; trace-only (span, no stats) when only
    MXTPU_TRACE_DIR is set."""
    if _on():
        return _Phase(name, Span(PHASE_SPAN, {"phase": name}), feed=True)
    if _distributed.trace_active():
        return _Phase(name, Span(PHASE_SPAN, {"phase": name}, metrics=False),
                      feed=False)
    return _NOOP_PHASE


def record(name, seconds):
    """Accumulate `seconds` into phase `name` of the current step (for
    sites that already hold a measurement)."""
    if not _on():
        return
    with _lock:
        _acc[name] = _acc.get(name, 0.0) + float(seconds)


def _get_window():
    global _window
    w = _window
    if w is None:
        size = max(2, int(_config.get("MXNET_TELEMETRY_STEPSTATS_WINDOW")))
        w = _window = collections.deque(maxlen=size)
    return w


def _quantile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def step_end(step_seconds=None):
    """Close the current step: roll the accumulated phases into the
    window, refresh the p50/p99 gauges, and check the anomaly guard.
    With `step_seconds=None` the total is wall time since the previous
    step_end (first step: sum of its phases)."""
    global _last_end, _steps, _anomalies
    if not _on():
        return
    now = time.perf_counter()
    with _lock:
        phases = dict(_acc)
        _acc.clear()
        if step_seconds is not None:
            total = float(step_seconds)
        elif _last_end is not None:
            total = now - _last_end
        else:
            total = sum(phases.values())
        _last_end = now
        win = _get_window()
        prior_totals = [t for t, _ in win]
        win.append((total, phases))
        snap = list(win)
        _steps += 1

    g = REGISTRY.gauge(PHASE_GAUGE, _PHASE_HELP)
    names = set()
    for _, ph in snap:
        names.update(ph)
    for name in names:
        vals = sorted(p.get(name, 0.0) for _, p in snap)
        g.set(_quantile(vals, 0.5), phase=name, q="0.5")
        g.set(_quantile(vals, 0.99), phase=name, q="0.99")
    totals = sorted(t for t, _ in snap)
    g.set(_quantile(totals, 0.5), phase="total", q="0.5")
    g.set(_quantile(totals, 0.99), phase="total", q="0.99")

    min_steps = int(_config.get("MXNET_TELEMETRY_ANOMALY_MIN_STEPS"))
    factor = float(_config.get("MXNET_TELEMETRY_ANOMALY_FACTOR"))
    if factor > 0 and len(prior_totals) >= min_steps:
        median = sorted(prior_totals)[len(prior_totals) // 2]
        if median > 0 and total > factor * median:
            with _lock:
                _anomalies += 1
            REGISTRY.counter(ANOMALIES_TOTAL, _ANOM_HELP).inc()
            _recorder.log_event(
                "step_anomaly", total_s=round(total, 6),
                median_s=round(median, 6), factor=factor,
                phases={k: round(v, 6) for k, v in sorted(phases.items())})


def snapshot():
    """Point-in-time view for benches/tests: per-phase quantiles over the
    window, phase coverage (mean of per-step phase-sum/total), counts."""
    with _lock:
        snap = list(_window) if _window is not None else []
        steps, anomalies = _steps, _anomalies
    out = {"steps": steps, "window": len(snap), "anomalies": anomalies,
           "phases": {}, "total": {}, "coverage": None}
    if not snap:
        return out
    names = set()
    for _, ph in snap:
        names.update(ph)
    for name in sorted(names):
        vals = sorted(p.get(name, 0.0) for _, p in snap)
        out["phases"][name] = {
            "p50": _quantile(vals, 0.5), "p99": _quantile(vals, 0.99),
            "mean": sum(vals) / len(vals)}
    totals = sorted(t for t, _ in snap)
    out["total"] = {"p50": _quantile(totals, 0.5),
                    "p99": _quantile(totals, 0.99),
                    "mean": sum(totals) / len(totals)}
    ratios = [sum(p.values()) / t for t, p in snap if t > 0]
    if ratios:
        out["coverage"] = sum(ratios) / len(ratios)
    return out


def reset():
    """Drop all rolling state (tests; also on registry reset)."""
    global _window, _last_end, _steps, _anomalies
    with _lock:
        _acc.clear()
        _window = None
        _last_end = None
        _steps = 0
        _anomalies = 0
