"""Registered telemetry names: the single source of truth for every
metric family and span the framework emits.

The reference gets this property from its profiler's fixed category set
(ref: src/profiler/profiler.h ProfileDomain); here, where any call site
can mint a Counter by name, drift is a real hazard — a typo'd name forks
a metric family and silently splits a dashboard series. So: every
`mxtpu_*` metric name and every `span()` name used inside
`incubator_mxnet_tpu/` MUST be declared here. `tools/mxlint.py` enforces
it statically (rule MXL004), and docs/OBSERVABILITY.md documents each
entry.

User code is unconstrained — this registry governs the framework's own
instrumentation, not application metrics.
"""
from __future__ import annotations

__all__ = ["METRIC_NAMES", "SPAN_NAMES", "is_registered_metric",
           "is_registered_span"]

# name -> (kind, one-line description). Kind is documentation (the
# registry in metrics.py enforces kind consistency at runtime).
METRIC_NAMES = {
    "mxtpu_span_seconds": (
        "histogram", "Duration of telemetry spans, labeled by span name."),
    "mxtpu_device_bytes_in_use": (
        "gauge", "Current device (or host-RSS) memory, by device."),
    "mxtpu_device_peak_bytes_in_use": (
        "gauge", "Watermark of device (or host-RSS) memory, by device."),
    "mxtpu_trainer_steps_total": (
        "counter", "Trainer.step boundaries seen by the memory sampler."),
    "mxtpu_trainer_step_seconds": (
        "histogram", "End-to-end Trainer.step latency."),
    "mxtpu_trainer_dispatches_total": (
        "counter", "XLA program dispatches issued by the eager Trainer, "
                   "by kind and path."),
    "mxtpu_trainer_bucket_bytes": (
        "histogram", "Payload bytes of one aggregated-dispatch bucket."),
    "mxtpu_dataloader_fetch_seconds": (
        "histogram", "Time the training loop blocked fetching a batch."),
    "mxtpu_dataloader_queue_depth": (
        "gauge", "Prefetch batches in flight."),
    "mxtpu_kvstore_seconds": (
        "histogram", "Latency of scalar-key kvstore operations."),
    "mxtpu_kvstore_bytes_total": (
        "counter", "Payload bytes through kvstore push/pull."),
    "mxtpu_engine_waitall_seconds": (
        "histogram", "Blocking time in engine.waitall barriers."),
    "mxtpu_engine_waitall_errors_total": (
        "counter", "Exceptions swallowed while draining waitall."),
    "mxtpu_eager_jit_cache_size": (
        "gauge", "Entries in the eager-dispatch jit cache (LRU)."),
    "mxtpu_graph_validate_findings_total": (
        "counter", "Findings emitted by bind-time graph validation "
                   "(MXNET_GRAPH_VALIDATE), by code and severity."),
    "mxtpu_retry_attempts_total": (
        "counter", "Retry attempts issued by resilience.RetryPolicy, by "
                   "site and outcome (retried/exhausted)."),
    "mxtpu_ps_reconnects_total": (
        "counter", "PSClient transparent reconnects after a mid-frame "
                   "socket error, by cause."),
    "mxtpu_ps_dedup_hits_total": (
        "counter", "Retried mutating RPCs the ParameterServer suppressed "
                   "via the per-client dedup window, by command."),
    "mxtpu_ps_evictions_total": (
        "counter", "Workers evicted from the barrier/sync quorum after "
                   "heartbeat staleness (dist graceful degradation)."),
    "mxtpu_ps_joins_total": (
        "counter", "Join RPCs the ParameterServer accepted, by outcome "
                   "(registered / readmitted / pending)."),
    "mxtpu_ps_readmissions_total": (
        "counter", "Evicted ranks re-admitted to the quorum, via a fresh "
                   "heartbeat or a join RPC (elastic membership)."),
    "mxtpu_ps_stale_epoch_rejections_total": (
        "counter", "Sync contributions rejected for carrying a stale "
                   "membership epoch, by command."),
    "mxtpu_ps_membership_epoch": (
        "gauge", "Current membership epoch of the ParameterServer; bumps "
                 "on every membership change (readmission, rank "
                 "takeover, world growth)."),
    "mxtpu_fault_injections_total": (
        "counter", "Faults fired by the deterministic injector "
                   "(MXTPU_FAULT_SPEC), by site and mode."),
    "mxtpu_ckpt_writes_total": (
        "counter", "Checkpoint file writes through resilience.checkpoint, "
                   "by outcome (ok/injected-fail/injected-torn)."),
    "mxtpu_ckpt_verify_failures_total": (
        "counter", "Checkpoint files failing manifest verification at "
                   "load, by reason."),
    "mxtpu_span_errors_total": (
        "counter", "Spans whose body raised an exception, by span name "
                   "(the span itself is tagged error=<ExcType>)."),
    "mxtpu_flight_recorder_dumps_total": (
        "counter", "Post-mortem flight-recorder dump files written, by "
                   "reason."),
    "mxtpu_ps_leaves_total": (
        "counter", "Ranks that left the sync quorum via the graceful-leave "
                   "RPC (preemption drain) — the quorum shrinks "
                   "immediately, without a heartbeat timeout."),
    "mxtpu_preemptions_total": (
        "counter", "Preemption drains completed: a termination signal "
                   "arrived, the in-flight step finished, and a resume "
                   "bundle was written, by signal."),
    "mxtpu_loss_scale": (
        "gauge", "Current dynamic loss scale of the AMP scaler (moves on "
                 "overflow backoff and growth-window promotion)."),
    "mxtpu_guardrail_trips_total": (
        "counter", "Divergence-guardrail trips in Trainer.step, by policy "
                   "(skip/backoff/rollback) and reason."),
    "mxtpu_step_phase_seconds": (
        "gauge", "Rolling per-phase step-time quantiles from StepStats, "
                 "by phase and quantile (q=0.5/0.99)."),
    "mxtpu_step_anomalies_total": (
        "counter", "Steps whose wall time exceeded "
                   "MXNET_TELEMETRY_ANOMALY_FACTOR x the rolling median "
                   "(each also logs a step_anomaly flight event)."),
    "mxtpu_ledger_live_bytes": (
        "gauge", "Live NDArray bytes tracked by the HBM ledger, by role "
                 "(params/grads/optimizer_state/activations/kv_buffers)."),
    "mxtpu_ledger_peak_bytes": (
        "gauge", "High-watermark of ledger-tracked live bytes; "
                 "ledger.peak_info() names the span active at the peak."),
    "mxtpu_ledger_leak_events_total": (
        "counter", "Leak-heuristic firings: the tracked live set grew for "
                   "MXNET_TELEMETRY_LEAK_WINDOW consecutive samples."),
    "mxtpu_compiles_total": (
        "counter", "New (function, shape-signature) pairs registered with "
                   "the compile registry, by fn."),
    "mxtpu_retraces_total": (
        "counter", "Recompilations of an already-seen function with a NEW "
                   "shape signature, by fn (each also logs a retrace "
                   "flight event naming the shape delta)."),
    "mxtpu_compile_seconds": (
        "histogram", "Trace+compile wall time observed for first-seen "
                     "shape signatures, by fn."),
    "mxtpu_compile_cache_hits_total": (
        "counter", "Executables served from the persistent compile "
                   "cache instead of XLA, by fn."),
    "mxtpu_compile_cache_misses_total": (
        "counter", "Compile-cache lookups that fell through to a fresh "
                   "XLA compile (the entry is then written back), "
                   "by fn."),
    "mxtpu_compile_cache_evictions_total": (
        "counter", "Compile-cache entries deleted, by reason "
                   "(corrupt / version / lru / clear) and fn."),
    "mxtpu_compile_cache_saved_seconds": (
        "counter", "Compile wall-clock skipped by cache hits: stored "
                   "compile time minus deserialize cost, by fn."),
    "mxtpu_decode_dense_fallbacks_total": (
        "counter", "flash_decode calls that fell back to the dense "
                   "(non-Pallas) cache attention because the cache "
                   "length does not tile into decode blocks, by reason."),
    "mxtpu_flash_dense_fallbacks_total": (
        "counter", "Training flash-attention calls that fell back to the "
                   "dense S×S attention (non-causal sequences that do "
                   "not tile into blocks — causal remainders are padded "
                   "into the Pallas path instead), by site and reason."),
    "mxtpu_embedding_pull_rpcs_total": (
        "counter", "Row-pull RPCs issued by the sharded embedding "
                   "service, by path (batched = one multi-table RPC per "
                   "server, per_key = naive one RPC per table per "
                   "server)."),
    "mxtpu_embedding_push_rpcs_total": (
        "counter", "Row-sparse grad-push RPCs issued by the sharded "
                   "embedding service, by path (batched / per_key)."),
    "mxtpu_embedding_rows_pulled_total": (
        "counter", "Embedding rows fetched over the wire by the sharded "
                   "embedding service (after dedup, including bucket "
                   "padding)."),
    "mxtpu_embedding_dedup_saved_rows_total": (
        "counter", "Embedding row fetches avoided by per-step id "
                   "dedup: requested ids minus unique ids, summed over "
                   "pulls (the zipfian dedup win in rows)."),
    "mxtpu_embedding_prefetch_hits_total": (
        "counter", "Embedding pulls served from a completed or in-flight "
                   "background prefetch, by outcome (ready = zero "
                   "blocking, wait = blocked on the remainder)."),
    "mxtpu_serving_queue_depth": (
        "gauge", "Requests waiting in the serving engine's admission "
                 "queue (not yet holding a decode slot)."),
    "mxtpu_serving_slots_in_use": (
        "gauge", "Decode slots currently running a request, out of "
                 "MXTPU_DECODE_SLOTS."),
    "mxtpu_serving_pages_in_use": (
        "gauge", "KV-cache pages currently owned by live requests "
                 "(excludes the reserved null page)."),
    "mxtpu_serving_page_utilization": (
        "gauge", "Fraction of allocatable KV-cache pages in use "
                 "(pages_in_use / (num_pages - 1))."),
    "mxtpu_serving_requests_total": (
        "counter", "Requests finished by the serving engine, by outcome "
                   "(eos / length / evicted / cancelled)."),
    "mxtpu_serving_tokens_total": (
        "counter", "Tokens processed by the serving engine, by kind "
                   "(prefill = prompt tokens cached, decode = tokens "
                   "generated, pad = prefill bucket padding rows)."),
    "mxtpu_serving_request_seconds": (
        "histogram", "Per-request wall time from submit to finish "
                     "(queue wait + prefill + all decode steps)."),
    "mxtpu_serving_queue_wait_seconds": (
        "histogram", "Per-request wall time from submit to slot "
                     "admission (backpressure latency)."),
    "mxtpu_serving_ttft_seconds": (
        "histogram", "Per-request time to first token: submit until the "
                     "prefill emits the first sampled token."),
    "mxtpu_serving_oldest_queued_seconds": (
        "gauge", "Age of the head-of-queue request (0 when the queue is "
                 "empty) — a wedged queue is visible BEFORE it drains."),
    "mxtpu_serving_admission_blocked_total": (
        "counter", "Scheduler iterations in which admission stalled with "
                   "requests still queued, by reason (slots = no free "
                   "decode slot, pages = KV page pool exhausted)."),
    "mxtpu_serving_wasted_tokens_total": (
        "counter", "Device token-positions that produced no delivered "
                   "output, by reason (prefill_pad = bucket padding "
                   "rows, evicted = prompt+generated tokens of requests "
                   "evicted mid-stream)."),
    "mxtpu_serving_goodput": (
        "gauge", "Fraction of processed serving tokens that were useful "
                 "(neither padding nor spent on evicted requests)."),
    "mxtpu_serving_prefix_lookups_total": (
        "counter", "Prefix-cache lookups at admission, by outcome (hit "
                   "= at least one cached page mapped, miss = full "
                   "prefill)."),
    "mxtpu_serving_prefix_tokens_saved_total": (
        "counter", "Prompt tokens NOT prefilled because their KV pages "
                   "came from the prefix cache (table writes instead of "
                   "device compute)."),
    "mxtpu_serving_prefix_cached_pages": (
        "gauge", "KV pages currently held by the prefix cache (each "
                 "carries one allocator reference until LRU-evicted)."),
    "mxtpu_serving_cow_copies_total": (
        "counter", "Copy-on-write page copies, by site (admit = cached "
                   "partial page copied before a tail prefill writes "
                   "into it, decode = first decode token landing in a "
                   "shared partially-filled page)."),
    "mxtpu_serving_prefill_chunks_total": (
        "counter", "Prefill chunks executed by the chunked-prefill "
                   "path (one wide-query program call covers every "
                   "mid-prefill slot's next chunk)."),
    "mxtpu_spec_proposed_tokens_total": (
        "counter", "Draft tokens proposed by the n-gram prompt-lookup "
                   "speculator (excludes the one guaranteed token per "
                   "step)."),
    "mxtpu_spec_accepted_tokens_total": (
        "counter", "Proposed draft tokens accepted by wide-query "
                   "verification (acceptance rate = accepted / "
                   "proposed)."),
    "mxtpu_fleet_replicas": (
        "gauge", "Serving replicas known to the fleet router, by state "
                 "(healthy / draining / dead / left)."),
    "mxtpu_fleet_failovers_total": (
        "counter", "Replicas the fleet router declared dead on "
                   "heartbeat timeout (each failover resubmits every "
                   "journaled in-flight request of the corpse to a "
                   "survivor)."),
    "mxtpu_fleet_resubmits_total": (
        "counter", "Requests resubmitted by the fleet router, by reason "
                   "(failover = original replica declared dead, drain = "
                   "handed off from a draining replica's admission "
                   "queue, rpc = dispatch RPC to a replica failed)."),
    "mxtpu_fleet_drains_total": (
        "counter", "Serving replicas that completed the drain handshake "
                   "and left the router (the rolling-restart path: stop "
                   "admitting, hand off queued work, finish in-slot "
                   "requests, leave)."),
    "mxtpu_fleet_dup_tokens_dropped_total": (
        "counter", "Stale or duplicate token deliveries the request "
                   "journal discarded (a failed-over replica that was "
                   "slow rather than dead keeps streaming under its old "
                   "assignment epoch; clients never see a token "
                   "twice)."),
    "mxtpu_fleet_lost_requests_total": (
        "counter", "Requests the fleet router failed back to the client "
                   "after exhausting MXTPU_FLEET_MAX_RESUBMITS — the "
                   "zero-lost-requests chaos gate asserts this stays "
                   "0."),
    "mxtpu_fleet_queue_depth": (
        "gauge", "Requests in the fleet router's front queue (journaled "
                 "but not yet dispatched to any replica) — the "
                 "autoscaler's backlog signal."),
    "mxtpu_fleet_oldest_queued_seconds": (
        "gauge", "Age of the oldest request still waiting in the fleet "
                 "router's front queue (0 when the queue is empty)."),
    "mxtpu_fleet_total_queue_depth": (
        "gauge", "Fleet-wide queued work: router front queue plus every "
                 "live replica's engine admission queue."),
    "mxtpu_fleet_page_occupancy": (
        "gauge", "Mean KV page-pool occupancy across live (healthy or "
                 "draining) replicas — the fleet-level capacity rollup "
                 "the gateway federates at /metrics."),
    "mxtpu_fleet_replica_health": (
        "gauge", "One-hot replica health matrix: 1 on the replica's "
                 "current state series (healthy / draining / dead / "
                 "left), 0 on the rest, labeled {replica, state}."),
    "mxtpu_fleet_replica_queue_depth": (
        "gauge", "Engine admission-queue depth per replica (federated "
                 "under the replica label at the gateway's /metrics)."),
    "mxtpu_fleet_replica_slots_in_use": (
        "gauge", "Decode slots in use per replica (federated under the "
                 "replica label at the gateway's /metrics)."),
    "mxtpu_fleet_replica_page_occupancy": (
        "gauge", "KV page-pool occupancy per replica (federated under "
                 "the replica label at the gateway's /metrics)."),
    "mxtpu_gateway_requests_total": (
        "counter", "HTTP requests answered by the serving gateway, by "
                   "outcome (ok / error = 4xx or journal failure, "
                   "rejected = 429 backpressure, draining = 503 during "
                   "shutdown, injected = gateway.accept fault)."),
    "mxtpu_gateway_inflight": (
        "gauge", "Generation requests currently open on the serving "
                 "gateway (accepted, not yet finished streaming)."),
    "mxtpu_gateway_access_log_lines_total": (
        "counter", "Lines written to the gateway's structured NDJSON "
                   "access log (MXTPU_GATEWAY_ACCESS_LOG)."),
    "mxtpu_slo_burn_rate": (
        "gauge", "SLO error-budget burn rate (bad_fraction / budget), "
                 "by objective and window (short / long)."),
    "mxtpu_slo_state": (
        "gauge", "SLO state machine position per objective "
                 "(0 = ok, 1 = warning, 2 = breach)."),
    "mxtpu_slo_breaches_total": (
        "counter", "SLO breach transitions (each also logs a "
                   "flight-recorder event and writes one post-mortem "
                   "dump), by objective."),
    "mxtpu_sanitizer_findings_total": (
        "counter", "Deduplicated findings from the runtime sanitizers "
                   "(MXTPU_SANITIZERS), labeled by sanitizer "
                   "(locks/pages) and MXS code; each also logs a "
                   "sanitizer_finding flight-recorder event."),
}

# span() names (tracing regions). Dots namespace by subsystem.
SPAN_NAMES = frozenset({
    "executor.forward",
    "executor.backward",
    "trainer.step",
    "trainer.allreduce_grads",
    "trainer.phase",
    "ps.client.rpc",
    "ps.server.handle",
    "ps.server.merge",
    "ps.server.barrier",
    "embedding.pull",
    "embedding.push",
    "serving.step",
    "serving.prefill",
    "serving.prefill_chunk",
    # per-request lifecycle records (trace-only; emitted straight
    # through distributed.record_span, one lane per request in the
    # trace_merge --requests view)
    "serving.request",
    "serving.request.queued",
    "serving.request.prefill",
    "serving.request.decode",
    # fleet observatory (trace-only): the causal chain of one request
    # across the serving fleet — gateway root, router dispatch, and the
    # failover/resubmit records that explain a mid-stream replica death
    "gateway.request",
    "fleet.dispatch",
    "fleet.failover",
    "fleet.resubmit",
})


def is_registered_metric(name):
    return name in METRIC_NAMES


def is_registered_span(name):
    return name in SPAN_NAMES
