"""Thread-safe metrics primitives: Counter, Gauge, Histogram + registry.

The reference engine attributes time/memory per dispatched op
(ref: src/profiler/profiler.h); in the TPU build the executor is one fused
XLA program, so the host-side hot paths (Trainer.step, kvstore push/pull,
DataLoader, engine.waitall) are where steps and bytes actually go. This
module is the measurement substrate for those paths.

Concurrency model: metrics are written from trainer threads, DataLoader
worker threads, and the engine's heartbeat/daemon threads. Label
resolution (`labels()`) caches the child series in a plain dict, so the
hot path is a dict hit plus a tiny per-child critical section — callers
that care can hold the child object and skip the lookup entirely
(the "lock-free-ish" fast path; under CPython the GIL already serializes
the simple float adds, the lock makes the invariants explicit).
"""
from __future__ import annotations

import bisect

from ..analysis.sanitizers import san_lock

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "DEFAULT_BUCKETS", "BYTES_BUCKETS",
]

# latency-oriented buckets in seconds (Prometheus client defaults, extended
# half a decade down — TPU host hops are often sub-millisecond)
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# payload-size buckets in bytes, 4 KiB .. 1 GiB in powers of 4 — for
# histograms of aggregation/allreduce bucket sizes and similar payloads
BYTES_BUCKETS = tuple(float(4 * 1024 * 4 ** i) for i in range(10))


def _label_key(labels):
    return tuple(sorted(labels.items()))


class _Metric:
    """Base: a named family of label-keyed series."""

    kind = "untyped"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._lock = san_lock("telemetry.metric")
        self._children = {}

    def labels(self, **labels):
        """Get-or-create the child series for this label set (cached)."""
        key = _label_key(labels)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._make_child()
                    self._children[key] = child
        return child

    def series(self):
        """Snapshot: [(labels_dict, child), ...] in stable label order."""
        with self._lock:
            items = sorted(self._children.items())
        return [(dict(key), child) for key, child in items]


class _CounterChild:
    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = san_lock("telemetry.counter_child")
        self.value = 0.0

    def inc(self, amount=1.0):
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self.value += amount


class Counter(_Metric):
    """Monotonically increasing value (ref role: ProfileCounter,
    profiler.h:556 — but registry-backed and exportable)."""

    kind = "counter"
    _make_child = staticmethod(_CounterChild)

    def inc(self, amount=1.0, **labels):
        self.labels(**labels).inc(amount)

    def value(self, **labels):
        return self.labels(**labels).value


class _GaugeChild:
    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = san_lock("telemetry.gauge_child")
        self.value = 0.0

    def set(self, value):
        with self._lock:
            self.value = float(value)

    def set_max(self, value):
        """Watermark update: keep the max ever seen."""
        value = float(value)
        with self._lock:
            if value > self.value:
                self.value = value

    def inc(self, amount=1.0):
        with self._lock:
            self.value += amount

    def dec(self, amount=1.0):
        self.inc(-amount)


class Gauge(_Metric):
    """Point-in-time value that can go both ways (queue depths, bytes in
    use); `set_max` gives watermark semantics for memory peaks."""

    kind = "gauge"
    _make_child = staticmethod(_GaugeChild)

    def set(self, value, **labels):
        self.labels(**labels).set(value)

    def set_max(self, value, **labels):
        self.labels(**labels).set_max(value)

    def inc(self, amount=1.0, **labels):
        self.labels(**labels).inc(amount)

    def dec(self, amount=1.0, **labels):
        self.labels(**labels).dec(amount)

    def value(self, **labels):
        return self.labels(**labels).value


class _HistogramChild:
    __slots__ = ("_lock", "_bounds", "buckets", "count", "sum", "min", "max")

    def __init__(self, bounds):
        self._lock = san_lock("telemetry.hist_child")
        self._bounds = bounds
        self.buckets = [0] * (len(bounds) + 1)  # last slot: +Inf
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, value):
        value = float(value)
        idx = bisect.bisect_left(self._bounds, value)
        with self._lock:
            self.buckets[idx] += 1
            self.count += 1
            self.sum += value
            if self.min is None or value < self.min:
                self.min = value
            if self.max is None or value > self.max:
                self.max = value

    def snapshot(self):
        """Consistent copy: (bounds, bucket counts, count, sum, min, max)."""
        with self._lock:
            return (self._bounds, list(self.buckets), self.count, self.sum,
                    self.min, self.max)


class Histogram(_Metric):
    """Distribution with fixed upper-bound buckets (Prometheus-style
    cumulative exposition happens at export time; storage is per-bucket)."""

    kind = "histogram"

    def __init__(self, name, help="", buckets=DEFAULT_BUCKETS):
        super().__init__(name, help)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")

    def _make_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, value, **labels):
        self.labels(**labels).observe(value)


class MetricsRegistry:
    """Name -> metric family. One process-wide default (`REGISTRY`);
    tests may instantiate their own."""

    def __init__(self):
        self._lock = san_lock("telemetry.registry")
        self._metrics = {}

    def _get_or_create(self, name, kind, factory):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = factory()
                    self._metrics[name] = m
        if m.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as {m.kind}, "
                f"requested {kind}")
        return m

    def counter(self, name, help=""):
        return self._get_or_create(name, "counter",
                                   lambda: Counter(name, help))

    def gauge(self, name, help=""):
        return self._get_or_create(name, "gauge", lambda: Gauge(name, help))

    def histogram(self, name, help="", buckets=DEFAULT_BUCKETS):
        return self._get_or_create(
            name, "histogram", lambda: Histogram(name, help, buckets))

    def get(self, name):
        return self._metrics.get(name)

    def collect(self):
        """Snapshot of all families, name-sorted (stable export order)."""
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def reset(self):
        with self._lock:
            self._metrics.clear()


REGISTRY = MetricsRegistry()
