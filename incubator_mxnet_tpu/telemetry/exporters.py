"""Exporters: JSON dump, Prometheus text exposition, /metrics endpoint.

`to_dict()`/`dump_json()` give a round-trippable JSON view of the whole
registry; `prometheus_text()` renders text exposition format 0.0.4
(the format every Prometheus/VictoriaMetrics/Grafana-agent scraper
speaks); `start_http_server()` serves it from a stdlib daemon thread —
no third-party client library, per the no-new-deps constraint.
"""
from __future__ import annotations

import json
import threading

from .metrics import REGISTRY

__all__ = ["to_dict", "dump_json", "prometheus_text", "start_http_server",
           "register_debug_handler", "unregister_debug_handler",
           "debug_handlers"]

# /debug/* endpoint registry: path -> zero-arg callable returning a
# JSON-serializable snapshot. Served by the telemetry HTTP server only
# when MXTPU_DEBUG_ENDPOINTS is on (introspection snapshots expose
# request ids — not every /metrics scraper should see them). Last
# registration per path wins: a replaced engine takes over its path.
_debug_lock = threading.Lock()
_debug_handlers: dict = {}


def register_debug_handler(path, provider):
    """Expose `provider()` (returning JSON-serializable data) at `path`
    on the telemetry HTTP server, gated by MXTPU_DEBUG_ENDPOINTS."""
    if not path.startswith("/debug/"):
        raise ValueError(f"debug handlers live under /debug/, got {path!r}")
    with _debug_lock:
        _debug_handlers[path] = provider


def unregister_debug_handler(path):
    with _debug_lock:
        _debug_handlers.pop(path, None)


def debug_handlers():
    """Snapshot of the registered /debug/* paths."""
    with _debug_lock:
        return dict(_debug_handlers)


def _fmt(value):
    """Prometheus sample value: integers render bare, floats via repr
    (repr round-trips; exposition format accepts scientific notation)."""
    f = float(value)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(value):
    return (str(value).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _render_labels(labels, extra=None):
    items = list(labels.items())
    if extra:
        items += list(extra.items())
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label(v)}"' for k, v in items)
    return "{" + body + "}"


def to_dict(registry=None):
    """Registry snapshot as plain JSON-serializable data. Histograms carry
    count/sum/min/max plus per-upper-bound bucket counts (non-cumulative;
    the exposition renderer cumulates)."""
    registry = registry or REGISTRY
    metrics = {}
    for metric in registry.collect():
        series = []
        for labels, child in metric.series():
            if metric.kind == "histogram":
                bounds, buckets, count, total, mn, mx = child.snapshot()
                series.append({
                    "labels": labels,
                    "count": count,
                    "sum": total,
                    "min": mn,
                    "max": mx,
                    "buckets": {str(b): n for b, n in zip(bounds, buckets)},
                    "overflow": buckets[-1],  # observations above max bound
                })
            else:
                series.append({"labels": labels, "value": child.value})
        metrics[metric.name] = {
            "type": metric.kind,
            "help": metric.help,
            "series": series,
        }
    return {"version": 1, "metrics": metrics}


def dump_json(path=None, registry=None):
    """Snapshot the registry; when `path` is given also write it as JSON.
    Returns the snapshot dict either way."""
    data = to_dict(registry)
    if path is not None:
        with open(path, "w") as f:
            json.dump(data, f, indent=2, sort_keys=True)
    return data


def _estimate_quantile(bounds, buckets, count, mn, mx, q):
    """Quantile estimate by linear interpolation inside the bucket the
    target rank lands in (non-cumulative bucket counts; observations
    past the last bound resolve to the recorded max). Clamped to the
    child's [min, max] so sparse low buckets can't report a value no
    observation ever had."""
    if not count:
        return None
    target = q * count
    cum = 0.0
    lo = 0.0
    est = None
    for b, n in zip(bounds, buckets):
        if n and cum + n >= target:
            est = lo + (b - lo) * ((target - cum) / n)
            break
        cum += n
        lo = b
    if est is None:  # rank lives in the +Inf overflow bucket
        est = mx
    if mn is not None:
        est = max(est, mn)
    if mx is not None:
        est = min(est, mx)
    return est


# precomputed summary quantiles emitted per histogram child — scrapers
# get p50/p95/p99 without PromQL histogram_quantile math
_SUMMARY_QUANTILES = (("0.5", 0.5), ("0.95", 0.95), ("0.99", 0.99))


def prometheus_text(registry=None):
    """Text exposition format 0.0.4. Histogram buckets are cumulative and
    always include le="+Inf"; each histogram child also carries
    precomputed p50/p95/p99 samples under a `quantile` label (summary
    convention); counters keep whatever name they were registered under
    (instrumented sites use the `_total` convention)."""
    registry = registry or REGISTRY
    lines = []
    for metric in registry.collect():
        lines.append(f"# HELP {metric.name} {metric.help}")
        lines.append(f"# TYPE {metric.name} {metric.kind}")
        for labels, child in metric.series():
            if metric.kind == "histogram":
                bounds, buckets, count, total, mn, mx = child.snapshot()
                cum = 0
                for b, n in zip(bounds, buckets):
                    cum += n
                    lines.append(
                        f"{metric.name}_bucket"
                        f"{_render_labels(labels, {'le': _fmt(b)})} {cum}")
                lines.append(
                    f"{metric.name}_bucket"
                    f"{_render_labels(labels, {'le': '+Inf'})} {count}")
                lines.append(
                    f"{metric.name}_sum{_render_labels(labels)} {_fmt(total)}")
                lines.append(
                    f"{metric.name}_count{_render_labels(labels)} {count}")
                for qlabel, q in _SUMMARY_QUANTILES:
                    est = _estimate_quantile(bounds, buckets, count, mn, mx, q)
                    if est is not None:
                        lines.append(
                            f"{metric.name}"
                            f"{_render_labels(labels, {'quantile': qlabel})}"
                            f" {_fmt(est)}")
            else:
                lines.append(
                    f"{metric.name}{_render_labels(labels)} "
                    f"{_fmt(child.value)}")
    return "\n".join(lines) + "\n"


class _MetricsServer:
    """Stdlib HTTP server answering GET /metrics with the exposition text.
    Daemon-threaded; `close()` for deterministic shutdown in tests."""

    def __init__(self, port, registry=None, host="0.0.0.0"):
        import http.server

        registry = registry or REGISTRY
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _reply(self, body, content_type):
                self.send_response(200)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                from .. import config as _config

                path = self.path.split("?")[0]
                if path in ("/metrics", "/"):
                    self._reply(prometheus_text(outer.registry).encode(),
                                "text/plain; version=0.0.4")
                    return
                provider = debug_handlers().get(path)
                if (provider is not None
                        and _config.get("MXTPU_DEBUG_ENDPOINTS")):
                    try:
                        body = json.dumps(provider(), default=str).encode()
                    except Exception as e:  # snapshot bug: surface, not 404
                        self.send_error(
                            500, f"{type(e).__name__}: {e}")
                        return
                    self._reply(body, "application/json")
                    return
                self.send_error(404)

            def log_message(self, *args):
                pass  # scrapes must not spam the training logs

        self.registry = registry
        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="mxtpu-telemetry-http")
        self._thread.start()

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def start_http_server(port, registry=None, host="0.0.0.0"):
    """Serve Prometheus exposition at http://host:port/metrics (port 0
    picks an ephemeral port; read it back from the returned server)."""
    return _MetricsServer(port, registry, host)
