"""Device/host memory watermarks, sampled on step boundaries.

The reference's storage profiler tracked every allocation through its
pooled allocator (ref: src/profiler/storage_profiler.h); under PJRT the
runtime owns allocation, so the observable surface is
`device.memory_stats()` — populated on TPU/GPU backends, `None` on CPU.
The host process is always sampled (current RSS from /proc/self/statm,
peak from ru_maxrss) under `device="host"` so a memory series exists on
every backend, including the CPU meshes CI runs on.
"""
from __future__ import annotations

import os
import threading

import jax

from .metrics import REGISTRY

__all__ = ["sample_device_memory", "step_boundary"]

BYTES_IN_USE = "mxtpu_device_bytes_in_use"
PEAK_BYTES = "mxtpu_device_peak_bytes_in_use"
STEPS_TOTAL = "mxtpu_trainer_steps_total"

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096

_step_lock = threading.Lock()
_step_count = 0


def _host_bytes():
    """(current_rss, peak_rss) in bytes; (None, None) if unreadable."""
    current = peak = None
    try:
        with open("/proc/self/statm") as f:
            current = int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        pass
    return current, peak


def sample_device_memory(registry=None):
    """Record per-device bytes-in-use gauges and peak watermarks; returns
    the set of device labels sampled."""
    registry = registry or REGISTRY
    in_use = registry.gauge(
        BYTES_IN_USE, "Allocator bytes currently in use, per device "
        "(host RSS under device=\"host\").")
    peak = registry.gauge(
        PEAK_BYTES, "High-watermark of bytes in use, per device.")
    sampled = set()
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue  # CPU backend: no allocator stats
        label = str(d)
        b = stats.get("bytes_in_use")
        if b is not None:
            in_use.set(b, device=label)
            sampled.add(label)
        pk = stats.get("peak_bytes_in_use", b)
        if pk is not None:
            peak.set_max(pk, device=label)
    current, peak_rss = _host_bytes()
    if current is not None:
        in_use.set(current, device="host")
        sampled.add("host")
    if peak_rss is not None:
        peak.set_max(peak_rss, device="host")
    return sampled


def step_boundary(registry=None):
    """Called by Trainer.step (when telemetry is enabled): bump the step
    counter and sample memory every MXNET_TELEMETRY_MEM_INTERVAL steps."""
    global _step_count
    from .. import config as _config

    registry = registry or REGISTRY
    registry.counter(STEPS_TOTAL, "Trainer.step invocations.").inc()
    with _step_lock:
        _step_count += 1
        n = _step_count
    interval = _config.get("MXNET_TELEMETRY_MEM_INTERVAL")
    if interval > 0 and n % interval == 0:
        sample_device_memory(registry)
    ledger_interval = _config.get("MXNET_TELEMETRY_LEDGER_INTERVAL")
    if ledger_interval > 0 and n % ledger_interval == 0:
        from . import ledger as _ledger

        _ledger.step_sample(n)
