"""Tracing spans: nested wall-time regions that feed several sinks at once.

A span records its duration into the metrics registry
(`mxtpu_span_seconds{span=...}`), forwards to
`jax.profiler.TraceAnnotation` when a jax trace is running (so spans line
up with the XLA device timeline in TensorBoard/Perfetto), and accumulates
into the profiler's per-op aggregate table when `aggregate_stats` is on —
unifying with `profiler.dumps()` instead of growing a second table.

When distributed tracing is active (`MXTPU_TRACE_DIR`), every span also
carries Dapper-style identity — `trace_id`/`span_id`/`parent_id` — and is
appended to this process's trace file on exit. A root span adopts the
remote parent shipped by a peer (see `telemetry.distributed`), which is
what links a worker's `trainer.step` to the server-side `merge` it caused.
Completed spans additionally drop a boundary event into the flight
recorder ring, so a post-mortem dump shows what the process was doing.

A span whose body raises keeps its timing but is tagged
`error=<ExcType>` (visible in traces and the `mxtpu_span_seconds` series)
and bumps `mxtpu_span_errors_total{name=...}` — failed and healthy spans
are never conflated.

Nesting is tracked per-thread; `current_span()` exposes the innermost
active span (its `parent` chain gives the full stack).
"""
from __future__ import annotations

import threading
import time

from .. import profiler as _profiler
from . import distributed as _distributed
from . import recorder as _recorder
from .metrics import REGISTRY

__all__ = ["Span", "current_span", "SPAN_HISTOGRAM", "SPAN_ERRORS"]

SPAN_HISTOGRAM = "mxtpu_span_seconds"
_SPAN_HELP = ("Wall time of named host-side spans (executor forward/backward,"
              " trainer step, ...); tags become extra labels.")
SPAN_ERRORS = "mxtpu_span_errors_total"
_ERRORS_HELP = ("Spans whose body raised, by span name (the exception type "
                "is tagged on the span itself).")

_local = threading.local()


def current_span():
    """Innermost active span on this thread, or None."""
    return getattr(_local, "current", None)


class Span:
    """Context manager for one timed region. Re-enterable is NOT supported
    (create a fresh Span per region); re-use across threads is not either —
    both mirror TraceAnnotation's contract.

    `metrics=False` builds a trace-only span: it still gets identity and
    lands in the trace file / flight recorder, but skips the registry and
    profiler sinks — the shape `span()` hands out when distributed tracing
    is on while telemetry proper is off."""

    __slots__ = ("name", "tags", "parent", "trace_id", "span_id",
                 "parent_id", "extra", "_start_ns", "_t0", "_annot",
                 "_metrics")

    def __init__(self, name, tags=None, metrics=True):
        self.name = name
        self.tags = dict(tags or {})
        self.parent = None
        self.trace_id = None
        self.span_id = None
        self.parent_id = None
        self.extra = None
        self._start_ns = None
        self._t0 = None
        self._annot = None
        self._metrics = metrics

    def annotate(self, **kv):
        """Attach key/values to the span's trace record (not metric
        labels — no cardinality cost). Used for e.g. the RPC send/recv
        timestamps that drive clock-skew correction in trace_merge."""
        if self.extra is None:
            self.extra = {}
        self.extra.update(kv)
        return self

    def bump(self, key, amount=1):
        """Increment a numeric annotation (e.g. per-span retry count)."""
        if self.extra is None:
            self.extra = {}
        self.extra[key] = self.extra.get(key, 0) + amount
        return self

    def __enter__(self):
        self.parent = getattr(_local, "current", None)
        _local.current = self
        if _distributed.trace_active():
            self.span_id = _distributed.new_id()
            parent = self.parent
            if parent is not None and parent.span_id is not None:
                self.trace_id = parent.trace_id
                self.parent_id = parent.span_id
            else:
                remote = _distributed.remote_parent()
                if remote is not None:
                    self.trace_id, self.parent_id = remote
                else:
                    self.trace_id = _distributed.new_id()
            self._start_ns = time.time_ns()
        if _profiler._STATE["running"]:
            try:
                self._annot = _profiler.scope(self.name)
                self._annot.__enter__()
            except Exception:
                self._annot = None  # tracing must never break the workload
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        dur = time.perf_counter() - self._t0
        if self._annot is not None:
            try:
                self._annot.__exit__(exc_type, exc_val, exc_tb)
            except Exception:
                pass
            self._annot = None
        _local.current = self.parent
        if exc_type is not None:
            self.tags["error"] = getattr(exc_type, "__name__", str(exc_type))
        if self._metrics:
            labels = {"span": self.name}
            for k, v in self.tags.items():
                labels[str(k)] = str(v)
            REGISTRY.histogram(SPAN_HISTOGRAM, _SPAN_HELP).observe(
                dur, **labels)
            if exc_type is not None:
                REGISTRY.counter(SPAN_ERRORS, _ERRORS_HELP).inc(
                    1, name=self.name)
            if _profiler.aggregate_enabled():
                _profiler.record_duration(self.name, dur)
        if self.span_id is not None:
            record = {
                "name": self.name,
                "tid": self.trace_id,
                "sid": self.span_id,
                "pid": self.parent_id,
                "ts": self._start_ns,
                "dur_ns": int(dur * 1e9),
            }
            if self.tags:
                record["tags"] = {str(k): str(v)
                                  for k, v in self.tags.items()}
            if self.extra:
                record["extra"] = self.extra
            _distributed.record_span(record)
        _recorder.log_event(
            "span_end", name=self.name, dur_ns=int(dur * 1e9),
            **({"error": self.tags["error"]} if exc_type is not None else {}))
        return False


class NoopSpan:
    """Shared do-nothing span for the disabled path: one module-level
    instance, safe to re-enter from any thread."""

    __slots__ = ()
    name = None
    tags = {}
    parent = None
    trace_id = None
    span_id = None
    parent_id = None
    extra = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **kv):
        return self

    def bump(self, key, amount=1):
        return self


NOOP_SPAN = NoopSpan()
