"""Tracing spans: nested wall-time regions that feed three sinks at once.

A span records its duration into the metrics registry
(`mxtpu_span_seconds{span=...}`), forwards to
`jax.profiler.TraceAnnotation` when a jax trace is running (so spans line
up with the XLA device timeline in TensorBoard/Perfetto), and accumulates
into the profiler's per-op aggregate table when `aggregate_stats` is on —
unifying with `profiler.dumps()` instead of growing a second table.

Nesting is tracked per-thread; `current_span()` exposes the innermost
active span (its `parent` chain gives the full stack).
"""
from __future__ import annotations

import threading
import time

from .. import profiler as _profiler
from .metrics import REGISTRY

__all__ = ["Span", "current_span", "SPAN_HISTOGRAM"]

SPAN_HISTOGRAM = "mxtpu_span_seconds"
_SPAN_HELP = ("Wall time of named host-side spans (executor forward/backward,"
              " trainer step, ...); tags become extra labels.")

_local = threading.local()


def current_span():
    """Innermost active span on this thread, or None."""
    return getattr(_local, "current", None)


class Span:
    """Context manager for one timed region. Re-enterable is NOT supported
    (create a fresh Span per region); re-use across threads is not either —
    both mirror TraceAnnotation's contract."""

    __slots__ = ("name", "tags", "parent", "_t0", "_annot")

    def __init__(self, name, tags=None):
        self.name = name
        self.tags = dict(tags or {})
        self.parent = None
        self._t0 = None
        self._annot = None

    def __enter__(self):
        self.parent = getattr(_local, "current", None)
        _local.current = self
        if _profiler._STATE["running"]:
            try:
                self._annot = _profiler.scope(self.name)
                self._annot.__enter__()
            except Exception:
                self._annot = None  # tracing must never break the workload
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        dur = time.perf_counter() - self._t0
        if self._annot is not None:
            try:
                self._annot.__exit__(exc_type, exc_val, exc_tb)
            except Exception:
                pass
            self._annot = None
        _local.current = self.parent
        labels = {"span": self.name}
        for k, v in self.tags.items():
            labels[str(k)] = str(v)
        REGISTRY.histogram(SPAN_HISTOGRAM, _SPAN_HELP).observe(dur, **labels)
        if _profiler.aggregate_enabled():
            _profiler.record_duration(self.name, dur)
        return False


class NoopSpan:
    """Shared do-nothing span for the disabled path: one module-level
    instance, safe to re-enter from any thread."""

    __slots__ = ()
    name = None
    tags = {}
    parent = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = NoopSpan()
