"""SLO burn-rate monitor for the serving engine (SRE multi-window form).

An `Objective` declares a per-request threshold — a latency CEILING
(TTFT, queue wait, end-to-end latency: the sample is bad when it exceeds
the threshold) or a FLOOR (goodput: bad when it dips below). Each
finished request contributes one boolean sample per configured
objective; the monitor keeps the last `window_long` samples and computes

    burn = bad_fraction(window) / error_budget

over the short and the long window. Burn 1.0 means the objective is
spending its budget exactly; burn 10 with a 1% budget means one request
in ten is violating. The state machine is the classic multi-window
guard:

    ok      -> warning   when burn(short) >= warn_burn
    warning -> breach    when burn(short) AND burn(long) >= breach_burn
    breach  -> re-arm    when burn(short) drops back below breach_burn

Windows are counted in SAMPLES, not wall-clock seconds, so the math is
deterministic under test and independent of request rate. No transition
fires before `min_samples` observations (cold-start guard).

A breach transition bumps `mxtpu_slo_breaches_total{objective}`, logs an
`slo_breach` flight-recorder event, and writes exactly ONE post-mortem
dump (`recorder.dump`) carrying the monitor snapshot and the last-N
request timelines supplied by the engine — the artifact a fleet router
pages on. Re-arming and breaching again writes a fresh dump.

Construction is either explicit (tests) or `from_env()`: the serving
engine calls `from_env()` at build time and attaches the monitor only
when at least one `MXTPU_SLO_*` threshold is set, so an unconfigured
engine pays nothing per request.
"""
from __future__ import annotations

import dataclasses
from collections import deque

from .. import config as _config
from . import recorder as _recorder
from .names import METRIC_NAMES

__all__ = ["Objective", "SLOMonitor", "from_env",
           "BURN_RATE", "SLO_STATE", "BREACHES_TOTAL", "STATES"]

BURN_RATE = "mxtpu_slo_burn_rate"
SLO_STATE = "mxtpu_slo_state"
BREACHES_TOTAL = "mxtpu_slo_breaches_total"

STATES = ("ok", "warning", "breach")


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declarative objective. `kind` decides the violation
    direction: "ceiling" flags samples above the threshold (latencies),
    "floor" flags samples below it (goodput)."""
    name: str
    threshold: float
    kind: str = "ceiling"
    budget: float = 0.01

    def __post_init__(self):
        if self.kind not in ("ceiling", "floor"):
            raise ValueError(f"objective kind must be ceiling|floor, "
                             f"got {self.kind!r}")
        if not self.budget > 0:
            raise ValueError(f"error budget must be > 0, got {self.budget}")

    def is_bad(self, value):
        if self.kind == "floor":
            return value < self.threshold
        return value > self.threshold


class _ObjectiveState:
    __slots__ = ("objective", "samples", "state", "breaches", "total")

    def __init__(self, objective, window_long):
        self.objective = objective
        self.samples = deque(maxlen=window_long)  # booleans, newest last
        self.state = "ok"
        self.breaches = 0
        self.total = 0


class SLOMonitor:
    """Burn-rate evaluation over a fixed set of objectives.

    `timelines` is an optional zero-arg callable returning the last-N
    request-timeline dicts to embed in the breach dump; `dump=False`
    keeps the state machine but suppresses post-mortem files (unit
    tests of the burn math)."""

    def __init__(self, objectives, *, window_short=32, window_long=128,
                 min_samples=8, warn_burn=1.0, breach_burn=10.0,
                 timelines=None, dump=True):
        if not objectives:
            raise ValueError("SLOMonitor needs at least one objective")
        if window_short < 1 or window_long < window_short:
            raise ValueError(
                f"need 1 <= window_short <= window_long, got "
                f"{window_short}/{window_long}")
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        self.window_short = int(window_short)
        self.window_long = int(window_long)
        self.min_samples = int(min_samples)
        self.warn_burn = float(warn_burn)
        self.breach_burn = float(breach_burn)
        self._timelines = timelines
        self._dump = dump
        self._obj = {o.name: _ObjectiveState(o, self.window_long)
                     for o in objectives}

    @property
    def objectives(self):
        return [st.objective for st in self._obj.values()]

    def observe(self, name, value):
        """Feed one sample to one objective; runs the state machine and
        publishes the burn gauges. Returns the objective's new state."""
        st = self._obj[name]
        st.samples.append(st.objective.is_bad(float(value)))
        st.total += 1
        return self._evaluate(st)

    def observe_request(self, **samples):
        """Feed one finished request: keyword per objective name; keys
        without a configured objective are ignored, so the engine can
        always pass its full sample set."""
        for name, value in samples.items():
            if name in self._obj and value is not None:
                self.observe(name, value)

    def state(self, name):
        return self._obj[name].state

    def _burns(self, st):
        samples = st.samples
        n_long = len(samples)
        n_short = min(self.window_short, n_long)
        if not n_long:
            return 0.0, 0.0
        budget = st.objective.budget
        recent = list(samples)[-n_short:]
        burn_short = (sum(recent) / n_short) / budget
        burn_long = (sum(samples) / n_long) / budget
        return burn_short, burn_long

    def _evaluate(self, st):
        from . import set_gauge  # late: avoid import cycle at module load

        name = st.objective.name
        burn_short, burn_long = self._burns(st)
        set_gauge(BURN_RATE, burn_short,
                  help=METRIC_NAMES[BURN_RATE][1],
                  objective=name, window="short")
        set_gauge(BURN_RATE, burn_long,
                  help=METRIC_NAMES[BURN_RATE][1],
                  objective=name, window="long")

        prev = st.state
        if st.total >= self.min_samples:
            if (burn_short >= self.breach_burn
                    and burn_long >= self.breach_burn):
                new = "breach"
            elif prev == "breach" and burn_short >= self.breach_burn:
                new = "breach"  # long window decays first: stay latched
            elif burn_short >= self.warn_burn:
                new = "warning"
            else:
                new = "ok"
            if new != prev:
                st.state = new
                self._transition(st, prev, new, burn_short, burn_long)
        set_gauge(SLO_STATE, STATES.index(st.state),
                  help=METRIC_NAMES[SLO_STATE][1], objective=name)
        return st.state

    def _transition(self, st, prev, new, burn_short, burn_long):
        from . import inc  # late import, same cycle as set_gauge

        name = st.objective.name
        _recorder.log_event("slo_transition", objective=name,
                            prev=prev, state=new,
                            burn_short=round(burn_short, 3),
                            burn_long=round(burn_long, 3))
        if new != "breach":
            return
        st.breaches += 1
        inc(BREACHES_TOTAL, help=METRIC_NAMES[BREACHES_TOTAL][1],
            objective=name)
        _recorder.log_event("slo_breach", objective=name,
                            threshold=st.objective.threshold,
                            burn_short=round(burn_short, 3),
                            burn_long=round(burn_long, 3))
        if self._dump:
            timelines = list(self._timelines()) if self._timelines else []
            _recorder.dump(f"slo-breach-{name}", extra={
                "slo": self.snapshot(),
                "request_timelines": timelines,
            })

    def snapshot(self):
        """JSON-ready view: per-objective state, burns, and counters."""
        out = {}
        for name, st in self._obj.items():
            burn_short, burn_long = self._burns(st)
            out[name] = {
                "state": st.state,
                "threshold": st.objective.threshold,
                "kind": st.objective.kind,
                "budget": st.objective.budget,
                "burn_short": burn_short,
                "burn_long": burn_long,
                "samples": st.total,
                "breaches": st.breaches,
            }
        return out


# objective name -> (threshold knob, violation direction); the names
# double as the observe_request() keywords the engine feeds
_ENV_OBJECTIVES = (
    ("ttft", "MXTPU_SLO_TTFT_P99", "ceiling"),
    ("queue_wait", "MXTPU_SLO_QUEUE_WAIT_P99", "ceiling"),
    ("request_latency", "MXTPU_SLO_REQUEST_P99", "ceiling"),
    ("goodput", "MXTPU_SLO_GOODPUT_MIN", "floor"),
)


def from_env(timelines=None):
    """Build the monitor the MXTPU_SLO_* knobs describe, or None when
    no threshold is set (the zero-cost default)."""
    budget = _config.get("MXTPU_SLO_BUDGET")
    objectives = []
    for name, knob, kind in _ENV_OBJECTIVES:
        threshold = _config.get(knob)
        if threshold > 0:
            objectives.append(Objective(name, threshold, kind, budget))
    if not objectives:
        return None
    return SLOMonitor(
        objectives,
        window_short=_config.get("MXTPU_SLO_WINDOW_SHORT"),
        window_long=_config.get("MXTPU_SLO_WINDOW_LONG"),
        min_samples=_config.get("MXTPU_SLO_MIN_SAMPLES"),
        warn_burn=_config.get("MXTPU_SLO_WARN_BURN"),
        breach_burn=_config.get("MXTPU_SLO_BREACH_BURN"),
        timelines=timelines)
