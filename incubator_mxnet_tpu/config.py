"""Central runtime-configuration registry.

The reference documents ~72 `MXNET_*` env knobs in docs/faq/env_var.md and
reads them via dmlc::GetEnv at use sites; this module is the equivalent
tier for the TPU framework: every environment variable the framework reads
is REGISTERED here with its type, default, and documentation, and read
through `config.get(...)`. `config.describe()` regenerates the env-var
reference (the doc-generating reflection the reference gets from
dmlc::Parameter).

Many reference knobs have no TPU analog because XLA subsumes the subsystem
they tuned (thread pools per GPU, memory-pool shapes, bulking windows);
those are listed in `SUBSUMED` with the subsuming mechanism so users
migrating from the reference can find where each knob went.
"""
from __future__ import annotations

import dataclasses
import os

__all__ = ["Knob", "KNOBS", "SUBSUMED", "get", "describe", "register_knob"]


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    default: object
    type: type
    doc: str


KNOBS: dict[str, Knob] = {}


def register_knob(name, default, type_, doc):
    KNOBS[name] = Knob(name, default, type_, doc)
    return KNOBS[name]


def get(name, default=None):
    """Read a registered knob from the environment with its typed default."""
    knob = KNOBS.get(name)
    if knob is None:
        raise KeyError(f"unregistered config knob {name!r}; add it to "
                       "incubator_mxnet_tpu/config.py")
    raw = os.environ.get(name)
    if raw is None:
        return default if default is not None else knob.default
    if knob.type is bool:
        return raw.lower() not in ("0", "false", "off", "")
    return knob.type(raw)


def describe():
    """Render the env-var reference (docs/faq/env_var.md analog)."""
    lines = ["# Environment variables", ""]
    for knob in sorted(KNOBS.values(), key=lambda k: k.name):
        lines.append(f"- `{knob.name}` (default `{knob.default}`, "
                     f"{knob.type.__name__}): {knob.doc}")
    lines += ["", "## Reference knobs subsumed by XLA/JAX", ""]
    for name, how in sorted(SUBSUMED.items()):
        lines.append(f"- `{name}`: {how}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# registry — engine / execution
# ---------------------------------------------------------------------------

register_knob("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice", str,
              "Dependency-engine implementation: ThreadedEnginePerDevice "
              "(async worker pool) or NaiveEngine (serial, for debugging "
              "races — ref: env_var.md:103).")
register_knob("MXNET_CPU_WORKER_NTHREADS", 4, int,
              "Engine worker threads for host-side ops (ref: env_var.md:42).")
register_knob("MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN", 64, int,
              "Max ops bulked into one engine segment (ref: env_var.md:113); "
              "on TPU the fused train step plays this role.")
register_knob("MXTPU_EAGER_JIT", False, bool,
              "Jit-compile eager op dispatches (per-(op, attrs) cache; "
              "XLA then re-specializes per input shape). Recommended for "
              "steady-shape eager loops on TPU; off by default because "
              "shape-diverse workloads pay a compile per new shape.")
register_knob("MXTPU_EAGER_JIT_CACHE_SIZE", 512, int,
              "LRU capacity of the eager-dispatch jit cache (entries; "
              "0 = unbounded). Each entry is one (op, attrs) jitted "
              "callable plus XLA's per-shape executables behind it; "
              "shape-diverse eager workloads otherwise grow the cache "
              "without limit. Read from the environment at insert time "
              "so tests can retune it at runtime; current size is "
              "exported as the mxtpu_eager_jit_cache_size gauge.")

# static analysis
register_knob("MXNET_GRAPH_VALIDATE", "off", str,
              "Opt-in graph validation at Executor bind time: 'off' "
              "(default), 'warn' (run the analysis.validate pass pipeline "
              "over the symbol being bound and log each MXA finding), or "
              "'raise' (additionally raise GraphValidationError on any "
              "error-severity finding). Findings also feed the "
              "mxtpu_graph_validate_findings_total counter when telemetry "
              "is on. See docs/STATIC_ANALYSIS.md.")

register_knob("MXTPU_SANITIZERS", "", str,
              "Comma-separated runtime sanitizers from "
              "analysis/sanitizers.py: 'locks' (san_lock primitives "
              "become instrumented — global lock-order graph with "
              "MXS001 deadlock-cycle reports, MXS002 "
              "blocking-op-under-lock, MXS003 long holds), 'pages' "
              "(shadow refcount/generation checking of every "
              "PageAllocator transition — MXS010 double free, MXS011 "
              "use-after-free, MXS012 COW violation, MXS013 leak at "
              "drain, MXS014 shadow divergence), and 'threads' (gates "
              "the MXL008-MXL010 concurrency lint in tools/sanitize.py "
              "scenarios). Empty (default) = all off: san_lock returns "
              "plain threading primitives, resolved once at lock "
              "creation — no per-acquire indirection. Findings feed "
              "mxtpu_sanitizer_findings_total and sanitizer_finding "
              "flight events. See docs/STATIC_ANALYSIS.md.")

register_knob("MXTPU_SANITIZER_HOLD_MS", 250.0, float,
              "Lock-hold-time threshold in milliseconds for the locks "
              "sanitizer: releasing a sanitized lock held longer than "
              "this emits an MXS003 long-hold finding with the "
              "acquisition site. Only read while MXTPU_SANITIZERS "
              "includes 'locks'.")

# memory traffic (see docs/PERF_ANALYSIS.md §0)
register_knob("MXTPU_FUSED_EPILOGUE", False, bool,
              "Route conv→BN→ReLU(→residual-add) chains through the Pallas "
              "NHWC epilogue kernel (ops/pallas_kernels.py:bn_act_epilogue) "
              "inside traced train steps: one HBM pass applies the BN "
              "affine, the activation, and the residual add to the conv "
              "accumulator instead of leaving the fusion decision to XLA. "
              "Off (default) keeps the XLA path bit-for-bit; off-TPU the "
              "kernel runs in interpret mode only when tests request it.")
register_knob("MXTPU_REMAT_POLICY", "", str,
              "Named jax.checkpoint_policies policy for GluonTrainStep "
              "rematerialization: 'convs' (save convolution AND matmul "
              "results, recompute cheap elementwise — the tier tuned for "
              "the HBM-saturated bf16 conv path), 'dots' (dots_saveable), "
              "'dots_no_batch' (dots_with_no_batch_dims_saveable — "
              "matmuls only; a conv net recomputes every conv under "
              "this), 'offload' (offload dot "
              "results to host memory), 'nothing' (nothing_saveable — "
              "recompute everything, the legacy remat=True behavior), "
              "'everything' (everything_saveable — no remat), or any "
              "exact jax.checkpoint_policies attribute name. A non-empty "
              "policy enables remat even without GluonTrainStep("
              "remat=True); empty (default) preserves the legacy "
              "all-or-nothing jax.checkpoint behavior.")
register_knob("MXTPU_SHARD_POLICY", "", str,
              "ZeRO sharding policy for GluonTrainStep on an explicit "
              "mesh: 'zero1' partitions optimizer state and f32 master "
              "weights over the 'data' axis (largest divisible axis per "
              "tensor, ragged tensors fall back to replication — the "
              "per-tensor decision is recorded and queryable via "
              "GluonTrainStep.shard_placements()), freeing ~(N-1)/N of "
              "optimizer+master HBM per device; 'zero2' additionally "
              "reduce-scatters gradients so the sharded update consumes "
              "only the local grad shard before all-gathering updated "
              "params — one program, no host sync, bit-identical to "
              "replicated. 'replicated' or empty (default) keeps the "
              "legacy placement and leaves compiled programs "
              "structurally identical. On the eager Trainer path the "
              "knob shards newly created optimizer-state buckets next "
              "to mesh-committed parameters. Ignored (with the legacy "
              "placement) when no mesh is attached.")

# optimizer / trainer aggregation
register_knob("MXTPU_STOCHASTIC_ROUNDING", False, bool,
              "Master-free bf16 optimizer updates: for bf16 weights under "
              "multi_precision, skip the f32 master copy and instead "
              "compute the update in f32 from the bf16 weight, then "
              "stochastically round the result back to bf16 (seeded per "
              "(step, param); the unbiased rounding replaces the master's "
              "role of accumulating sub-ulp updates). Cuts the f32 master "
              "read+write (~0.6 GB/step on ResNet-50) from optimizer "
              "traffic. Opt-in: equivalence to the f32-master path is to "
              "tolerance, not bit-exact.")
register_knob("MXNET_OPTIMIZER_AGGREGATION_SIZE", 4096, int,
              "Byte cap (in KB) of one aggregated optimizer-update bucket "
              "on the eager Trainer path: parameters are grouped into "
              "dtype-homogeneous buckets of at most this many KB and each "
              "bucket is updated by ONE jitted multi-tensor program "
              "instead of one dispatch per parameter (ref: the reference's "
              "knob of the same name, which counts tensors — default 4 — "
              "because its cost was kernel launches; here the cost is XLA "
              "program dispatches, so the cap is bytes). 0 disables "
              "aggregation (always per-param dispatch).")
register_knob("MXTPU_ALLREDUCE_BUCKET_KB", 4096, int,
              "Byte cap (in KB) of one gradient-allreduce bucket in "
              "Trainer.allreduce_grads: dense gradients are flattened into "
              "contiguous buckets of at most this many KB and each bucket "
              "crosses the kvstore as ONE pushpull instead of one per "
              "tensor (ref role: MXNET_KVSTORE_BIGARRAY_BOUND, the "
              "reference's comms-granularity knob). Sparse (row_sparse) "
              "gradients and compressed-gradient stores stay on the "
              "per-key path. 0 disables bucketing.")

# data / IO
register_knob("MXTPU_PREFETCH_BUFFER", 2, int,
              "DataIter prefetch depth (ref: prefetcher buffer_size).")
register_knob("MXTPU_DECODE_THREADS", 4, int,
              "JPEG decode/augment worker threads in ImageRecordIter "
              "(ref: preprocess_threads of iter_image_recordio_2.cc).")

# distributed / kvstore
register_knob("MXTPU_COORDINATOR", "", str,
              "host:port of the jax.distributed coordinator (set by "
              "tools/launch.py; ref role: DMLC_PS_ROOT_URI).")
register_knob("MXTPU_NUM_PROCESSES", 1, int,
              "World size for multi-process training (ref: DMLC_NUM_WORKER).")
register_knob("MXTPU_PROCESS_ID", 0, int,
              "This process's rank (ref: ps-lite rank assignment).")
register_knob("MXTPU_ASYNC_PERIOD", 16, int,
              "dist_async: pushes of a key between elastic-averaging mix "
              "points (staleness bound).")
register_knob("MXTPU_ASYNC_ALPHA", 0.5, float,
              "dist_async: mixing rate toward the cross-worker mean at a "
              "mix point.")
register_knob("MXTPU_PS_ADDR", "", str,
              "host:port of the parameter server (default: coordinator "
              "host, coordinator port + 23).")
register_knob("MXTPU_PS_SECRET", "", str,
              "Shared job secret HMAC-authenticating the parameter "
              "server's optimizer blobs (the only pickled payload on the "
              "PS wire). tools/launch.py generates and exports one; set "
              "it identically on every worker for manual launches.")
register_knob("MXTPU_HEARTBEAT_DIR", "", str,
              "Directory for worker heartbeat files (dead-node detection; "
              "default derives from MXTPU_COORDINATOR).")
register_knob("MXTPU_HEARTBEAT_INTERVAL", 2.0, float,
              "Seconds between heartbeat touches.")
register_knob("MXTPU_HEARTBEAT_TRANSPORT", "auto", str,
              "Dead-node heartbeat transport: 'tcp' (rides the PS control "
              "plane on coordinator port + 29; works cross-host), 'file' "
              "(shared-filesystem mtimes), or 'auto' (tcp when a "
              "coordinator is configured, else file).")
register_knob("MXTPU_HEARTBEAT_TIMEOUT", 20.0, float,
              "Heartbeat staleness after which a peer counts as dead "
              "(ref: ps-lite PS_HEARTBEAT_TIMEOUT).")

# resilience / fault tolerance (see docs/FAULT_TOLERANCE.md)
register_knob("MXTPU_RETRY_MAX_ATTEMPTS", 8, int,
              "Max calls (first try + retries) a resilience.RetryPolicy "
              "makes before re-raising (ref role: ps-lite resender "
              "retry bound).")
register_knob("MXTPU_RETRY_BASE_DELAY", 0.05, float,
              "Seconds slept before the first retry; attempt k sleeps "
              "base * 2**k, capped by MXTPU_RETRY_MAX_DELAY.")
register_knob("MXTPU_RETRY_MAX_DELAY", 2.0, float,
              "Upper bound (seconds) on one exponential-backoff sleep.")
register_knob("MXTPU_RETRY_DEADLINE", 120.0, float,
              "Overall wall-clock budget (seconds) across all retries of "
              "one operation; the policy re-raises rather than sleep past "
              "it.")
register_knob("MXTPU_RETRY_JITTER", 0.1, float,
              "Backoff jitter fraction: each sleep is scaled by "
              "1 + U(-j, +j) from a seeded PRNG (deterministic across "
              "runs; 0 disables).")
register_knob("MXTPU_FAULT_SPEC", "", str,
              "Deterministic fault-injection spec, `site:mode@arg` rules "
              "joined by ';' (e.g. 'ps.rpc:drop@0.05;ckpt.write:fail@2'). "
              "Modes: drop (connection), fail (IO error), torn "
              "(corrupt checkpoint), sigterm (deliver SIGTERM to self — "
              "a deterministic preemption); arg is a probability or "
              "1-based call indices. Empty (default) disables injection. "
              "See docs/FAULT_TOLERANCE.md for the grammar and sites.")
register_knob("MXTPU_FAULT_SEED", 0, int,
              "Seed for the fault injector's per-(site, instance) PRNG "
              "streams; same seed + same spec fires the same faults at "
              "the same calls.")
register_knob("MXTPU_PS_CONNECT_TIMEOUT", 30.0, float,
              "Seconds one PSClient connect attempt may take before it "
              "counts as failed and the retry policy redials.")
register_knob("MXTPU_PS_SOCKET_TIMEOUT", 320.0, float,
              "Idle timeout (seconds) on an established PSClient socket; "
              "must exceed the server-side sync/barrier wait so a blocked "
              "quorum RPC is not misread as a dead server.")
register_knob("MXTPU_PS_SYNC_TIMEOUT", 300.0, float,
              "Server-side cap (seconds) on one sync-push merge or "
              "barrier generation wait; heartbeat evictions re-evaluate "
              "the quorum well before this fires.")
register_knob("MXTPU_PS_DEDUP_WINDOW", 128, int,
              "Mutating RPCs remembered per client for exactly-once "
              "replay suppression across reconnects; must exceed the "
              "deepest pipelining a client does (the eager client "
              "pipelines 1).")
register_knob("MXTPU_MAX_WORKERS", 0, int,
              "Elastic world cap for the parameter server: join RPCs may "
              "admit brand-new ranks until num_workers reaches this value "
              "(growth commits at the next barrier boundary). 0 keeps the "
              "world fixed at the configured size; re-admission of "
              "already-known ranks is always allowed.")
register_knob("MXTPU_GUARDRAIL_POLICY", "", str,
              "Divergence guardrail in Trainer.step: when non-empty, every "
              "step runs one fused non-finite check over the gradients "
              "(a single OR-reduce on device, one host sync) BEFORE they "
              "reach the optimizer or the parameter server. 'skip' drops "
              "the poisoned update; 'backoff' additionally halves the AMP "
              "dynamic loss scale (attaching a unit-scale scaler when none "
              "is present, so later steps keep the overflow check); "
              "'rollback' raises GuardrailRollback for the training loop "
              "to restore the last good checkpoint via auto_resume. Empty "
              "(default) disables the check entirely — zero per-step "
              "cost.")
register_knob("MXTPU_CKPT_WALKBACK", 8, int,
              "How many epochs model.latest_valid_checkpoint walks back "
              "over corrupt/missing checkpoints before giving up (each "
              "skipped epoch is logged to the flight recorder). 0 walks "
              "all the way to epoch 0 — unbounded, the pre-knob "
              "behavior.")
register_knob("MXTPU_PS_BUCKET_KB", 1024, int,
              "Byte cap (KiB) of one hierarchical-allreduce bucket on "
              "dist_async_server: list-key pushpulls batch into a single "
              "push_many/pull_many RPC pair per bucket after the "
              "intra-host GSPMD reduction. 0 disables batching (one RPC "
              "pair per key).")
register_knob("MXTPU_EMBEDDING_SHARDS", "", str,
              "Comma-separated host:port list of the embedding-shard PS "
              "fleet (embedding.ShardedEmbeddingService). Row r of every "
              "sharded table lives only on server r % num_shards, so a "
              "table's HBM footprint divides across the fleet and no "
              "worker ever materializes it. Empty (default): the service "
              "must be handed explicit addresses or in-process servers "
              "(tests/bench).")
register_knob("MXTPU_SPARSE_PREFETCH", True, bool,
              "Overlap embedding-row pulls with dense compute: the "
              "sharded embedding service runs pulls and row-sparse grad "
              "pushes on an ordered background thread, so the next "
              "batch's rows stream in behind the current step's dense "
              "forward/backward (the blocking remainder is the "
              "sparse_pull stepstats phase). Off: every pull is a "
              "blocking RPC on the critical path — same math, no "
              "overlap.")

# profiler
register_knob("MXNET_PROFILER_AUTOSTART", False, bool,
              "Start profiling at import (ref: env_var.md:192).")

# distributed tracing / flight recorder (see docs/OBSERVABILITY.md)
register_knob("MXTPU_TRACE_DIR", "", str,
              "Directory for per-process binary-framed trace files "
              "(span records with trace/span/parent ids). Setting it "
              "activates cluster-wide trace export: every completed span "
              "is appended to <dir>/trace-<pid>-<suffix>.mxtrace; merge "
              "the files with tools/trace_merge.py into one "
              "Chrome-trace/Perfetto timeline. Empty (default) disables "
              "trace export.")
register_knob("MXTPU_TRACE_BUFFER_SPANS", 256, int,
              "Completed spans buffered in memory before one framed "
              "write+flush to the trace file (atexit flushes the "
              "remainder). Lower = fresher files after a crash, higher "
              "= fewer write calls on the span exit path.")
register_knob("MXTPU_FLIGHT_RECORDER_EVENTS", 4096, int,
              "Capacity of the always-on flight-recorder ring buffer "
              "(structured events: span boundaries, retries, reconnects, "
              "evictions, checkpoint writes, injected faults). The ring "
              "is a fixed-size in-memory black box costing one list "
              "store per event; 0 disables recording entirely.")
register_knob("MXTPU_FLIGHT_RECORDER_DIR", "", str,
              "Destination directory for post-mortem flight-recorder "
              "dumps (ring contents + metrics snapshot + config knobs as "
              "JSON), written when a worker dies with an uncaught "
              "exception, a retry policy exhausts, or the server evicts "
              "a rank. Empty falls back to MXTPU_TRACE_DIR; when both "
              "are empty no dump files are ever written (the ring still "
              "records).")
register_knob("MXTPU_FLIGHT_RECORDER_MAX_DUMPS", 8, int,
              "Cap on post-mortem dump files one process may write "
              "(guards against dump storms from a retry loop that "
              "exhausts repeatedly).")

# telemetry
register_knob("MXNET_TELEMETRY", False, bool,
              "Master switch for the runtime telemetry layer (metrics "
              "registry, tracing spans, exporters — see "
              "docs/OBSERVABILITY.md). Off by default; while off every "
              "instrumented site short-circuits through no-op stubs.")
register_knob("MXNET_TELEMETRY_PORT", 0, int,
              "When >0 and telemetry is enabled, serve Prometheus text "
              "exposition at http://0.0.0.0:<port>/metrics from a daemon "
              "thread (stdlib http.server; no client library needed).")
register_knob("MXNET_TELEMETRY_MEM_INTERVAL", 1, int,
              "Trainer steps between device-memory watermark samples at "
              "step boundaries (0 disables memory sampling; sampling reads "
              "device.memory_stats() plus host RSS).")
register_knob("MXNET_TELEMETRY_STEPSTATS_WINDOW", 128, int,
              "Rolling-window length (steps) for StepStats per-phase "
              "p50/p99 gauges and the step-anomaly median (performance "
              "observatory, docs/OBSERVABILITY.md).")
register_knob("MXNET_TELEMETRY_ANOMALY_FACTOR", 3.0, float,
              "A step whose wall time exceeds this multiple of the "
              "rolling median step time emits a flight-recorder "
              "step_anomaly event and bumps mxtpu_step_anomalies_total.")
register_knob("MXNET_TELEMETRY_ANOMALY_MIN_STEPS", 8, int,
              "Minimum steps in the StepStats window before anomaly "
              "detection arms (suppresses warmup/compile outliers).")
register_knob("MXNET_TELEMETRY_LEDGER_INTERVAL", 1, int,
              "Trainer steps between HBM-ledger live-set samples at step "
              "boundaries (0 disables ledger sampling and the leak "
              "heuristic; role gauges still track alloc/free).")
register_knob("MXNET_TELEMETRY_LEAK_WINDOW", 8, int,
              "Consecutive monotonically-growing ledger samples before "
              "the leak heuristic fires a memory_leak_suspect event "
              "(0 disables the heuristic).")
register_knob("MXTPU_PERF_GATE_TOLERANCE", 20.0, float,
              "Default per-metric tolerance (percent) for "
              "tools/perf_gate.py when a baseline entry carries no "
              "explicit tolerance_pct band.")

# cold start / persistent compile cache (compile_cache.py)
register_knob("MXTPU_COMPILE_CACHE_DIR", "", str,
              "Directory for the persistent, content-addressed compile "
              "cache. Empty (the default) disables caching; when set, "
              "every jit site compilereg tracks serves serialized XLA "
              "executables from disk on restart instead of recompiling "
              "(crash-consistent writes, sha256-verified loads; corrupt "
              "or version-stale entries are evicted and recompiled). "
              "Read at jit-construction time — set it before building "
              "the model.")
register_knob("MXTPU_COMPILE_CACHE_MAX_MB", 2048.0, float,
              "LRU size cap (megabytes) on the compile-cache directory; "
              "oldest-recency entries are evicted after each write until "
              "the directory fits (the newest entry is never evicted). "
              "0 or negative disables the cap.")
register_knob("MXTPU_COMPILE_CACHE_SALT", "", str,
              "Extra opaque string folded into every compile-cache key. "
              "Bump it to force a cold rebuild of the cache without "
              "deleting the directory (e.g. after an XLA flag change "
              "the key material cannot see).")

# numerics / reproducibility
register_knob("MXTPU_DEFAULT_DTYPE", "float32", str,
              "Default dtype for new NDArrays.")
register_knob("MXTPU_SPARSE_NNZ_BUCKETING", False, bool,
              "Pad sparse (data, indices) buffers along nnz to the next "
              "power-of-2 bucket (floor 16) so XLA sees a few stable "
              "shapes instead of one executable per distinct nnz. Off by "
              "default: padding trades memory/compute for compile-cache "
              "hits, which only pays on TPU with nnz-diverse batches.")

# serving (serving/engine.py — continuous batching over a paged KV cache)
register_knob("MXTPU_PAGE_SIZE", 16, int,
              "Tokens per KV-cache page in the paged decode pool "
              "(serving/pages.py). Smaller pages waste less capacity on "
              "the last partial page per sequence but deepen the "
              "page-table walk in paged_decode_attention; must keep the "
              "page a TPU-friendly block (multiples of 8 recommended).")
register_knob("MXTPU_DECODE_SLOTS", 8, int,
              "Fixed number of decode slots in the continuous-batching "
              "engine — the static batch dimension of every paged decode "
              "step. Requests beyond this wait in the queue; raising it "
              "trades per-step latency for throughput. Static so the "
              "steady-state serving loop never retraces.")
register_knob("MXTPU_SERVING_PAGES", 0, int,
              "Total pages in the serving KV pool (page 0 is the "
              "reserved null page). 0 (default) auto-sizes to "
              "slots x ceil(max_len / page_size) + 1 — every slot can "
              "hold a full-length sequence; set lower to oversubscribe "
              "HBM and let admission backpressure manage the pool.")
register_knob("MXTPU_PREFILL_BUCKETS", "", str,
              "Comma-separated prompt-length buckets for serving "
              "prefill (each bucket is one compiled program; prompts "
              "pad up to the next bucket — the "
              "MXTPU_SPARSE_NNZ_BUCKETING idea applied to sequence "
              "length). Empty (default) uses powers of two from 16 up "
              "to the model's max_len.")
register_knob("MXTPU_PREFIX_CACHE", 0, int,
              "Prefix-cached copy-on-write KV pages in the serving "
              "engine (the vLLM block-sharing design): prompts sharing "
              "a page-aligned token prefix map the cached pages "
              "read-only instead of re-prefilling them. 0 (default) "
              "disables — the engine is byte-identical to the uncached "
              "path; 1 enables with an unbounded cache (bounded only by "
              "pool pressure); >1 enables with an LRU cap of that many "
              "cached pages. Cached pages are only evicted at refcount "
              "0 (no live request mapped).")
register_knob("MXTPU_PREFILL_CHUNK", 0, int,
              "Chunked prefill (Sarathi-style): slice serving prompts "
              "into chunks of this many tokens and interleave one chunk "
              "per engine step with the batched decode, so short "
              "requests' TTFT stops hiding behind long prompts. 0 "
              "(default) disables — prompts prefill in one bucketed "
              "program at admission.")
register_knob("MXTPU_SPEC_NGRAM", 0, int,
              "N-gram length for draft-free prompt-lookup speculative "
              "decoding in the serving engine: the trailing n-gram of a "
              "request's own token history is matched against earlier "
              "history and the continuation proposed. 0 (default) "
              "disables speculation.")
register_knob("MXTPU_SPEC_LOOKAHEAD", 4, int,
              "Tokens proposed per speculative decode step (the wide "
              "verification program processes lookahead+1 query rows "
              "per slot). Only meaningful when MXTPU_SPEC_NGRAM > 0.")

# serving SLOs (telemetry/slo.py) — a threshold of 0 disables that
# objective; when every threshold is 0 the serving engine attaches no
# monitor at all (zero per-request cost)
register_knob("MXTPU_SLO_TTFT_P99", 0.0, float,
              "Serving SLO: time-to-first-token ceiling in seconds. A "
              "finished request whose TTFT exceeds this burns error "
              "budget; 0 disables the objective.")
register_knob("MXTPU_SLO_QUEUE_WAIT_P99", 0.0, float,
              "Serving SLO: queue-wait (submit to slot admission) "
              "ceiling in seconds; 0 disables the objective.")
register_knob("MXTPU_SLO_REQUEST_P99", 0.0, float,
              "Serving SLO: end-to-end request latency ceiling in "
              "seconds; 0 disables the objective.")
register_knob("MXTPU_SLO_GOODPUT_MIN", 0.0, float,
              "Serving SLO: goodput floor in [0, 1] — the fraction of "
              "processed tokens that were neither prefill padding nor "
              "spent on evicted requests. Samples BELOW the floor burn "
              "budget; 0 disables the objective.")
register_knob("MXTPU_SLO_BUDGET", 0.01, float,
              "Error budget for every SLO objective: the fraction of "
              "requests allowed to violate their threshold. Burn rate "
              "= bad_fraction / budget (burn 1.0 spends the budget "
              "exactly).")
register_knob("MXTPU_SLO_WINDOW_SHORT", 32, int,
              "Short burn-rate window in SAMPLES (finished requests). "
              "Count-based, not wall-clock, so burn math is "
              "deterministic under test.")
register_knob("MXTPU_SLO_WINDOW_LONG", 128, int,
              "Long burn-rate window in samples; breach requires BOTH "
              "windows over MXTPU_SLO_BREACH_BURN (the classic "
              "multi-window guard against paging on a blip).")
register_knob("MXTPU_SLO_MIN_SAMPLES", 8, int,
              "Samples an objective must see before the state machine "
              "may leave 'ok' (cold-start guard).")
register_knob("MXTPU_SLO_WARN_BURN", 1.0, float,
              "Short-window burn rate at which an objective enters "
              "'warning'.")
register_knob("MXTPU_SLO_BREACH_BURN", 10.0, float,
              "Burn rate both windows must reach for 'breach' (bumps "
              "mxtpu_slo_breaches_total and writes one post-mortem "
              "dump); the objective re-arms when the short window "
              "drops back below this.")
register_knob("MXTPU_SLO_DUMP_TIMELINES", 32, int,
              "Finished-request timelines the serving engine retains "
              "for the breach post-mortem dump (last N).")
register_knob("MXTPU_DEBUG_ENDPOINTS", False, bool,
              "Serve registered /debug/* JSON endpoints (e.g. "
              "/debug/engine) from the telemetry HTTP server. Off by "
              "default: introspection snapshots expose request ids and "
              "queue contents, which not every /metrics scraper should "
              "see.")

# serving fleet (serving/fleet.py + serving/gateway.py — health-checked
# routing, journaled mid-stream failover, draining rolling restarts)
register_knob("MXTPU_FLEET_HEARTBEAT_TIMEOUT", 10.0, float,
              "Seconds without a scheduler-pump heartbeat before the "
              "fleet router declares a serving replica dead and "
              "resubmits its journaled in-flight requests to the "
              "survivors. Must exceed the replica's worst-case single "
              "step (first-request compiles included) or a merely-slow "
              "replica fails over spuriously — harmless for clients "
              "(the journal dedups the zombie's late tokens) but "
              "wasteful.")
register_knob("MXTPU_FLEET_MAX_RESUBMITS", 3, int,
              "Failover resubmissions a single request may consume "
              "before the router gives up and fails it back to the "
              "client (guards against a poison request that kills "
              "every replica it lands on).")
register_knob("MXTPU_GATEWAY_PORT", 0, int,
              "TCP port for the serving HTTP gateway "
              "(serving/gateway.py). 0 (default) binds an ephemeral "
              "port — read it back from ServingGateway.port.")
register_knob("MXTPU_GATEWAY_QUEUE_LIMIT", 64, int,
              "Per-tenant router queue depth at which the gateway "
              "stops admitting that tenant's requests and answers 429 "
              "with Retry-After (bounded queueing instead of unbounded "
              "latency collapse).")
register_knob("MXTPU_GATEWAY_MAX_OCCUPANCY", 0.95, float,
              "KV page-pool occupancy (on the LEAST loaded healthy "
              "replica) above which the gateway sheds new requests "
              "with 429 — admission control backpressured by the same "
              "PageAllocator that backpressures slot admission.")
register_knob("MXTPU_GATEWAY_RETRY_AFTER", 1.0, float,
              "Retry-After seconds the gateway attaches to 429/503 "
              "responses.")
register_knob("MXTPU_GATEWAY_ACCESS_LOG", "", str,
              "Structured NDJSON access log for the serving gateway: "
              "a file path to append one JSON line per request "
              "(tenant, status, token counts, queue-wait/TTFT/latency, "
              "trace id, serving replica, failover count), '-' for "
              "stderr, empty (default) for off.")

# contrib / compatibility shims
register_knob("MXTPU_USE_TENSORRT", False, bool,
              "TensorRT-compat preference flag (contrib.tensorrt). Purely "
              "advisory on TPU: XLA compiles and fuses every bind already, "
              "so this records the script's intent rather than toggling a "
              "graph pass (ref: MXNET_USE_TENSORRT).")

# model zoo
register_knob("MXTPU_MODELS_ROOT", "", str,
              "Directory for downloaded model-zoo parameter files "
              "(default ~/.mxtpu/models; ref role: MXNET_HOME model "
              "cache).")


# Reference knobs whose role is subsumed by the XLA/JAX substrate: the
# migration map (docs/faq/env_var.md names -> what replaces them here).
SUBSUMED = {
    "MXNET_GPU_WORKER_NTHREADS": "XLA async launch + stream assignment",
    "MXNET_GPU_COPY_NTHREADS": "PJRT transfer manager",
    "MXNET_OMP_MAX_THREADS": "XLA CPU thread pool (--xla_cpu_* flags)",
    "MXNET_GPU_MEM_POOL_SIZE": "PJRT BFC allocator "
                               "(XLA_PYTHON_CLIENT_MEM_FRACTION)",
    "MXNET_GPU_MEM_POOL_TYPE": "PJRT BFC allocator",
    "MXNET_GPU_MEM_POOL_RESERVE": "XLA_PYTHON_CLIENT_PREALLOCATE",
    "MXNET_EXEC_ENABLE_INPLACE": "XLA buffer reuse + donation",
    "MXNET_BACKWARD_DO_MIRROR": "jax.checkpoint / remat policies; the "
                                "policy choice is MXTPU_REMAT_POLICY",
    "MXNET_EXEC_INPLACE_GRAD_SUM_CAP": "XLA fusion of gradient sums",
    "MXNET_KVSTORE_REDUCTION_NTHREADS": "ICI collective all-reduce",
    "MXNET_KVSTORE_BIGARRAY_BOUND": "GSPMD sharding decides partitioning; "
                                    "the comms-granularity role lives on as "
                                    "MXTPU_ALLREDUCE_BUCKET_KB",
    "MXNET_KVSTORE_USETREE": "XLA collective scheduling over ICI topology",
    "MXNET_CUDNN_AUTOTUNE_DEFAULT": "XLA autotuning at compile time",
    "MXNET_SUBGRAPH_BACKEND": "XLA fusion passes",
    "MXNET_MKLDNN_ENABLED": "XLA:CPU oneDNN integration",
    "MXNET_SAFE_ACCUMULATION": "fp32 accumulation in bf16 matmuls "
                               "(preferred_element_type)",
}
