"""Native (C++) runtime components, built lazily with g++.

Where the reference's runtime is C++ (engine, io, storage — SURVEY §2), the
TPU build keeps native code for the pieces XLA does not subsume: the host
data path (recordio) and host-side scheduling. Libraries are compiled on
first use into the package directory and loaded via ctypes; every consumer
has a pure-Python fallback so the framework works without a toolchain.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "src")
_LOCK = threading.Lock()
_LIBS: dict = {}


def _compile(out: str, sources: list[str], extra: list[str],
             shared: bool) -> str | None:
    """Shared compile-if-stale helper for .so libs and tool binaries."""
    srcs = [os.path.join(_SRC, s) for s in sources]
    if os.path.exists(out) and all(
        os.path.getmtime(out) >= os.path.getmtime(s) for s in srcs
    ):
        return out
    cmd = ["g++", "-O2", "-std=c++17", "-pthread"]
    if shared:
        cmd += ["-shared", "-fPIC"]
    cmd += ["-o", out] + srcs + extra
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=180)
        return out
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired, FileNotFoundError) as e:
        # consumers fall back to pure-Python paths — make the degradation
        # visible instead of silent (a missing g++ should not just mean
        # "mysteriously slower")
        import warnings

        detail = ""
        if isinstance(e, subprocess.CalledProcessError) and e.stderr:
            detail = ": " + e.stderr.decode(errors="replace").strip()[-300:]
        warnings.warn(
            f"native build of {os.path.basename(out)} failed "
            f"({type(e).__name__}{detail}); falling back to the pure-Python "
            f"implementation (slower). Install g++ or check src/ sources.",
            RuntimeWarning,
        )
        return None


def _build(name: str, sources: list[str], extra=()) -> str | None:
    return _compile(os.path.join(_HERE, f"lib{name}.so"), sources,
                    list(extra), True)


def build_binary(name: str, sources: list[str], extra_flags=()) -> str | None:
    """Build a tool binary (e.g. the im2rec packer) into the package dir;
    returns its path or None when the toolchain is unavailable."""
    return _compile(os.path.join(_HERE, name), sources, list(extra_flags),
                    False)


def load(name: str, sources: list[str], extra=()):
    """Build+load libname.so; returns ctypes CDLL or None."""
    with _LOCK:
        if name in _LIBS:
            return _LIBS[name]
        path = _build(name, sources, extra)
        lib = None
        if path is not None:
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                lib = None
        _LIBS[name] = lib
        return lib


def recordio_lib():
    lib = load("mxtpu_recordio", ["recordio.cc"])
    if lib is not None and not getattr(lib, "_rio_configured", False):
        lib.rio_open_reader.restype = ctypes.c_void_p
        lib.rio_open_reader.argtypes = [ctypes.c_char_p]
        lib.rio_close_reader.argtypes = [ctypes.c_void_p]
        lib.rio_num_records.restype = ctypes.c_int64
        lib.rio_num_records.argtypes = [ctypes.c_void_p]
        lib.rio_record.restype = ctypes.c_int
        lib.rio_record.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.rio_record_len.restype = ctypes.c_int64
        lib.rio_record_len.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.rio_read_batch.restype = ctypes.c_int
        lib.rio_read_batch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.rio_open_writer.restype = ctypes.c_void_p
        lib.rio_open_writer.argtypes = [ctypes.c_char_p]
        lib.rio_write.restype = ctypes.c_int64
        lib.rio_write.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32]
        lib.rio_close_writer.argtypes = [ctypes.c_void_p]
        lib._rio_configured = True
    return lib


import sysconfig

_TF_INCLUDE = os.path.join(sysconfig.get_paths()["purelib"], "tensorflow",
                           "include")


def predict_lib():
    """C embedding runtime over the PJRT C API (src/predict.cc; header:
    include/mxtpu_predict.h — the c_predict_api.cc replacement)."""
    lib = load("mxtpu_predict", ["predict.cc"],
               extra=[f"-I{_TF_INCLUDE}", "-ldl"])
    if lib is not None and not getattr(lib, "_pred_configured", False):
        lib.MXTpuPredCreate.restype = ctypes.c_int
        lib.MXTpuPredCreate.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_void_p)]
        lib.MXTpuPredLastError.restype = ctypes.c_char_p
        lib.MXTpuPredNumInputs.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int)]
        lib.MXTpuPredInputName.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_char_p)]
        lib.MXTpuPredInputShape.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.c_int)]
        lib.MXTpuPredNumOutputs.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int)]
        lib.MXTpuPredOutputShape.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.c_int)]
        lib.MXTpuPredSetInput.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
            ctypes.c_size_t]
        lib.MXTpuPredForward.argtypes = [ctypes.c_void_p]
        lib.MXTpuPredGetOutput.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p, ctypes.c_size_t]
        lib.MXTpuPredFree.argtypes = [ctypes.c_void_p]
        lib._pred_configured = True
    return lib


def train_lib():
    """C embedding TRAINING runtime over the PJRT C API (src/train.cc;
    header: include/mxtpu.h — the create/train half of the reference's
    c_api.cc, collapsed to one compiled step looped from C)."""
    lib = load("mxtpu_train", ["train.cc"],
               extra=[f"-I{_TF_INCLUDE}", "-ldl"])
    if lib is not None and not getattr(lib, "_train_configured", False):
        lib.MXTpuTrainerCreate.restype = ctypes.c_int
        lib.MXTpuTrainerCreate.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_void_p)]
        lib.MXTpuLastError.restype = ctypes.c_char_p
        for fn in (lib.MXTpuTrainerNumInputs, lib.MXTpuTrainerNumStates):
            fn.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int)]
        for fn in (lib.MXTpuTrainerInputName, lib.MXTpuTrainerStateName):
            fn.argtypes = [ctypes.c_void_p, ctypes.c_int,
                           ctypes.POINTER(ctypes.c_char_p)]
        for fn in (lib.MXTpuTrainerInputShape, lib.MXTpuTrainerStateShape):
            fn.argtypes = [ctypes.c_void_p, ctypes.c_int,
                           ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
                           ctypes.POINTER(ctypes.c_int)]
        lib.MXTpuTrainerSetInput.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
            ctypes.c_size_t]
        lib.MXTpuTrainerSetInputND.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p]
        lib.MXTpuTrainerStep.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float)]
        lib.MXTpuTrainerSetLearningRate.argtypes = [
            ctypes.c_void_p, ctypes.c_float]
        lib.MXTpuTrainerGetLearningRate.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float)]
        lib.MXTpuTrainerGetState.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
            ctypes.c_size_t]
        lib.MXTpuTrainerSetState.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
            ctypes.c_size_t]
        lib.MXTpuTrainerFree.argtypes = [ctypes.c_void_p]
        lib.MXTpuNDCreate.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p)]
        lib.MXTpuNDShape.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.c_int)]
        lib.MXTpuNDDType.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_int)]
        lib.MXTpuNDSize.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_size_t)]
        lib.MXTpuNDData.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_void_p)]
        lib.MXTpuNDCopyTo.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_size_t]
        lib.MXTpuNDCopyFrom.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                        ctypes.c_size_t]
        lib.MXTpuNDFree.argtypes = [ctypes.c_void_p]
        lib._train_configured = True
    return lib


def imperative_lib():
    """Embedded-interpreter imperative op runtime (src/imperative.cc; the
    MXImperativeInvokeEx role — see include/mxtpu_imperative.hpp and the
    generated include/mxtpu_ops.hpp for the C++ user surface)."""
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION") or "3.12"
    lib = load("mxtpu_imperative", ["imperative.cc"],
               extra=[f"-I{inc}", f"-L{libdir}", f"-lpython{ver}",
                      f"-Wl,-rpath,{libdir}"])
    if lib is not None and not getattr(lib, "_imp_configured", False):
        lib.MXTpuImpInit.restype = ctypes.c_int
        lib.MXTpuImpError.restype = ctypes.c_char_p
        lib.MXTpuImpNDCreate.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p)]
        lib.MXTpuImpNDShape.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int)]
        lib.MXTpuImpNDDType.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_int)]
        lib.MXTpuImpNDCopyTo.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                         ctypes.c_size_t]
        lib.MXTpuImpNDFree.argtypes = [ctypes.c_void_p]
        lib.MXTpuImpNDRef.argtypes = [ctypes.c_void_p]
        lib.MXTpuImpInvoke.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p), ctypes.c_int,
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int)]
        lib.MXTpuImpAttachGrad.argtypes = [ctypes.c_void_p]
        lib.MXTpuImpGrad.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_void_p)]
        lib.MXTpuImpRecordBegin.argtypes = [ctypes.c_int]
        lib.MXTpuImpBackward.argtypes = [ctypes.c_void_p]
        lib.MXTpuImpSymBind.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_int,
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p)]
        lib.MXTpuImpExecSetArg.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                           ctypes.c_void_p]
        lib.MXTpuImpExecForward.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
        lib.MXTpuImpExecBackward.argtypes = [ctypes.c_void_p]
        lib.MXTpuImpExecGrad.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.POINTER(ctypes.c_void_p)]
        lib.MXTpuImpExecFree.argtypes = [ctypes.c_void_p]
        lib._imp_configured = True
    return lib


def imgpipe_lib():
    """Native JPEG decode+augment batch pipeline (src/imgpipe.cc; ref:
    iter_image_recordio_2.cc's preprocess-thread parser)."""
    lib = load("mxtpu_imgpipe", ["imgpipe.cc"], extra=["-ljpeg"])
    if lib is not None and not getattr(lib, "_imgpipe_configured", False):
        lib.imgpipe_decode_batch.restype = ctypes.c_int
        lib.imgpipe_decode_batch.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),      # datas
            ctypes.POINTER(ctypes.c_uint32),      # lens
            ctypes.POINTER(ctypes.c_int64),       # indices
            ctypes.c_int,                         # n
            ctypes.POINTER(ctypes.c_float),       # out
            ctypes.c_int, ctypes.c_int,           # target_h, target_w
            ctypes.c_int,                         # resize
            ctypes.c_int, ctypes.c_int,           # rand_crop, rand_mirror
            ctypes.POINTER(ctypes.c_float),       # mean3
            ctypes.POINTER(ctypes.c_float),       # std3
            ctypes.c_float,                       # scale
            ctypes.c_uint64,                      # seed
            ctypes.c_int,                         # nthreads
        ]
        lib._imgpipe_configured = True
    return lib
