"""Optimizers (ref: python/mxnet/optimizer/optimizer.py — 17 registered
optimizers + the Updater state machine used by KVStore).

Each update dispatches to the fused update ops in `ops/optimizer.py`
(ref: src/operator/optimizer_op-inl.h) or inline jnp math; the arrays are
updated by rebinding `_data`, which is the functional analog of the
reference's in-place kWriteInplace updates.
"""
from __future__ import annotations

import math
import pickle

import numpy as np
import jax.numpy as jnp

from . import config
from . import random as _global_random
from .ndarray import register as _ndreg
from .ndarray.ndarray import NDArray
from .ndarray import ones, zeros

__all__ = [
    "Optimizer", "SGD", "NAG", "SGLD", "Signum", "FTML", "DCASGD", "LBSGD",
    "Adam", "AdaGrad", "RMSProp", "AdaDelta", "Ftrl", "Adamax", "Nadam",
    "AdamW", "Test", "Updater", "get_updater", "create", "register",
]

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    return _REGISTRY[name.lower()](**kwargs)


class Optimizer:
    """Base optimizer (ref: optimizer.py class Optimizer)."""

    # True when fused_update reproduces update() step-for-step — the
    # contract the aggregated Trainer path (gluon/trainer.py) relies on.
    # SGLD (traced noise stream) and Nadam (per-parameter m_schedule)
    # deviate deliberately and flip this off below.
    fused_matches_eager = True

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None, **kwargs):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.idx2name = dict(param_idx2name or {})
        self.sym_info = (sym.attr_dict(), sym.list_arguments()) if sym is not None else ()
        self.param_dict = param_dict or {}
        self.aggregate_num = 0

    @staticmethod
    def create_optimizer(name, **kwargs):
        return create(name, **kwargs)

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler is not None else self.lr
        name = self.idx2name.get(index, index)
        if name in self.param_dict:
            lr *= self.param_dict[name].lr_mult
        elif name in self.lr_mult:
            lr *= self.lr_mult[name]
        return lr

    def _get_wd(self, index):
        wd = self.wd
        name = self.idx2name.get(index, index)
        if name in self.param_dict:
            wd *= self.param_dict[name].wd_mult
        elif name in self.wd_mult:
            wd *= self.wd_mult[name]
        return wd

    def _common_attrs(self, index):
        return dict(
            lr=self._get_lr(index),
            wd=self._get_wd(index),
            rescale_grad=self.rescale_grad,
            clip_gradient=self.clip_gradient if self.clip_gradient else -1.0,
        )


def _call(name, arrays, attrs):
    return _ndreg.invoke_by_name(name, arrays, attrs)


def _writeback(targets, results):
    if isinstance(results, NDArray):
        results = [results]
    for t, r in zip(targets, results):
        t._data = r._data


def _is_row_sparse(grad):
    from .ndarray.sparse import RowSparseNDArray

    return isinstance(grad, RowSparseNDArray)


def _cast_state_like(new_state, old_state):
    """Cast an optimizer-state pytree leaf-wise back to its pre-update
    dtypes (None / array / tuple-of-arrays — the shapes create_state
    produces). Keeps jit carries dtype-stable for bf16-cast nets; shared
    by fused.GluonTrainStep and the aggregated Trainer path."""
    if new_state is None or old_state is None:
        return new_state
    if isinstance(new_state, tuple):
        return tuple(
            n if o is None or n is None else n.astype(o.dtype)
            for n, o in zip(new_state, old_state))
    return new_state.astype(old_state.dtype)


def _sparse_grad_prep(opt, grad):
    """Rows + rescaled/clipped per-row gradient block for a lazy update
    (ref: optimizer_op-inl.h SGDUpdateRspImpl lazy_update path: only rows
    present in the row_sparse gradient are touched).

    Duplicate row ids are segment-summed to unique rows first: the state
    paths write with ``.at[rows].set``, which is last-write-wins on
    repeats — without the fold a duplicated row would apply momentum/wd
    once per occurrence and keep only the final racer's state. Framework
    producers (autograd.sparse_embedding, kvstore row-sparse allreduce)
    already emit unique rows, so the host check is the common-case cost.
    """
    idx = np.asarray(grad.indices._data)
    g = grad.data._data * opt.rescale_grad
    if idx.size and np.unique(idx).size != idx.size:
        uniq, inv = np.unique(idx, return_inverse=True)
        g = jnp.zeros((uniq.size,) + g.shape[1:],
                      g.dtype).at[jnp.asarray(inv)].add(g)
        idx = uniq
    rows = jnp.asarray(idx.astype(np.int32))
    if opt.clip_gradient:
        g = jnp.clip(g, -opt.clip_gradient, opt.clip_gradient)
    return rows, g


@register
class SGD(Optimizer):
    """(ref: optimizer.py:511 SGD, with momentum + multi-precision)"""

    def __init__(self, momentum=0.0, lazy_update=True,
                 stochastic_rounding=None, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update
        if stochastic_rounding is None:
            stochastic_rounding = config.get("MXTPU_STOCHASTIC_ROUNDING")
        self.stochastic_rounding = bool(stochastic_rounding)

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return zeros(weight.shape, dtype=str(weight.dtype))
        return None

    def _sr_active(self, weight):
        """Master-free stochastic-rounding path applies to plain SGD on
        bfloat16 weights only: f16's 10-bit mantissa needs loss scaling on
        top, and SGD subclasses (LBSGD) have their own update math that
        does not know the SR contract."""
        return (type(self) is SGD and self.stochastic_rounding
                and str(weight.dtype) == "bfloat16")

    def create_state_multi_precision(self, index, weight):
        """(mom_or_None, fp32 master weight) for low-precision weights when
        multi_precision is set (ref: optimizer.py SGD.create_state_multi_precision
        — momentum is created in the master dtype).

        Under MXTPU_STOCHASTIC_ROUNDING a bf16 weight instead gets the
        master-FREE variant: f32 momentum only, no w32 copy — the update
        computes in f32 and stochastically rounds the new weight back to
        bf16, cutting the optimizer's resident f32 bytes to ~1/2 (momentum
        only) and its HBM traffic per step accordingly."""
        if self._sr_active(weight):
            if self.momentum != 0.0:
                return zeros(weight.shape, dtype="float32")
            return None
        if self.multi_precision and str(weight.dtype) in ("float16", "bfloat16"):
            w32 = NDArray(weight._data.astype(jnp.float32))
            return (self.create_state(index, w32), w32)
        return self.create_state(index, weight)

    def update_multi_precision(self, index, weight, grad, state):
        if not isinstance(state, tuple):
            if self._sr_active(weight):
                self._sr_update(index, weight, grad, state)
                return
            self.update(index, weight, grad, state)
            return
        # mp state from create_state_multi_precision: math on the fp32
        # master, low-precision weight refreshed by cast
        # (ref: optimizer_op.cc mp_sgd_update / mp_sgd_mom_update)
        mom, w32 = state
        self._update_count(index)
        attrs = self._common_attrs(index)
        if _is_row_sparse(grad):
            # the master-copy path has no lazy variant; densify
            grad = grad.todense()
        if mom is not None:
            _writeback([weight, mom, w32], _call(
                "mp_sgd_mom_update", [weight, grad, mom, w32],
                {**attrs, "momentum": self.momentum}))
        else:
            _writeback([weight, w32],
                       _call("mp_sgd_update", [weight, grad, w32], attrs))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        attrs = self._common_attrs(index)
        if _is_row_sparse(grad):
            if not self.lazy_update:
                grad = grad.todense()
            else:
                lr, wd = attrs["lr"], attrs["wd"]
                rows, g = _sparse_grad_prep(self, grad)
                w = weight._data
                g = g + wd * w[rows]
                if state is not None:
                    m = state._data
                    m_rows = self.momentum * m[rows] - lr * g
                    state._data = m.at[rows].set(m_rows)
                    weight._data = w.at[rows].add(m_rows)
                else:
                    weight._data = w.at[rows].add(-lr * g)
                return
        if state is not None:
            _writeback([weight, state], _call("sgd_mom_update", [weight, grad, state],
                                              {**attrs, "momentum": self.momentum}))
        else:
            _writeback([weight], _call("sgd_update", [weight, grad], attrs))

    def _sr_update(self, index, weight, grad, state):
        """Eager master-free bf16 step: same _sgd_sr_math + (seed, t, name)
        key derivation as the fused/aggregated paths, so all three produce
        identical weights for identical schedules (fused_matches_eager
        holds)."""
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        name = self.idx2name.get(index, index)
        if _is_row_sparse(grad):
            grad = grad.todense()  # SR path has no lazy row-sparse variant
        new_w, new_m = _sgd_sr_math(
            self, weight._data, grad._data,
            state._data if state is not None else None, lr, wd, t, name)
        weight._data = new_w
        if state is not None and new_m is not None:
            state._data = new_m


@register
class NAG(Optimizer):
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        return zeros(weight.shape, dtype=str(weight.dtype)) if self.momentum else None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        attrs = self._common_attrs(index)
        if state is not None:
            _writeback([weight, state], _call("nag_mom_update", [weight, grad, state],
                                              {**attrs, "momentum": self.momentum}))
        else:
            _writeback([weight], _call("sgd_update", [weight, grad], attrs))


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (ref: optimizer.py SGLD)."""

    def update(self, index, weight, grad, state):
        import jax

        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad._data * self.rescale_grad
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        key = _global_random.next_key()
        noise = jax.random.normal(key, weight.shape, weight._data.dtype) * math.sqrt(lr)
        weight._data = weight._data - lr / 2 * (g + wd * weight._data) + noise


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        return zeros(weight.shape, dtype=str(weight.dtype)) if self.momentum else None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        attrs = self._common_attrs(index)
        if state is not None:
            _writeback([weight, state], _call("signum_update", [weight, grad, state],
                                              {**attrs, "momentum": self.momentum, "wd_lh": self.wd_lh}))
        else:
            _writeback([weight], _call("signsgd_update", [weight, grad], attrs))


@register
class FTML(Optimizer):
    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        dt = str(weight.dtype)
        return (zeros(weight.shape, dtype=dt), zeros(weight.shape, dtype=dt), zeros(weight.shape, dtype=dt))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        attrs = self._common_attrs(index)
        attrs.pop("clip_gradient")
        d, v, z = state
        _writeback([weight, d, v, z], _call(
            "ftml_update", [weight, grad, d, v, z],
            {**attrs, "beta1": self.beta1, "beta2": self.beta2, "epsilon": self.epsilon,
             "t": t, "clip_grad": self.clip_gradient if self.clip_gradient else -1.0},
        ))


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (ref: optimizer.py DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        mom = zeros(weight.shape, dtype=str(weight.dtype)) if self.momentum else None
        # must COPY: aliasing weight's buffer would make the fused step
        # donate the same buffer twice (params and states are both donated)
        prev = NDArray(jnp.array(weight._data, copy=True))
        return (mom, prev)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad._data * self.rescale_grad
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        mom, prev = state
        comp = g + wd * weight._data + self.lamda * g * g * (weight._data - prev._data)
        if mom is not None:
            mom._data = self.momentum * mom._data - lr * comp
            upd = mom._data
        else:
            upd = -lr * comp
        prev._data = weight._data
        weight._data = weight._data + upd


@register
class LBSGD(SGD):
    """Large-batch SGD with LARS-style layer-wise scaling
    (ref: optimizer.py:782 LBSGD)."""

    def __init__(self, momentum=0.0, warmup_strategy="linear", warmup_epochs=5,
                 batch_scale=1, updates_per_epoch=32, begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(momentum=momentum, **kwargs)
        self.warmup_strategy = warmup_strategy

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad._data * self.rescale_grad
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        # LARS trust ratio
        wnorm = jnp.linalg.norm(weight._data)
        gnorm = jnp.linalg.norm(g)
        ratio = jnp.where(
            (wnorm > 0) & (gnorm > 0), wnorm / (gnorm + wd * wnorm + 1e-9), 1.0
        )
        eff_lr = lr * ratio
        if state is not None:
            state._data = self.momentum * state._data - eff_lr * (g + wd * weight._data)
            weight._data = weight._data + state._data
        else:
            weight._data = weight._data - eff_lr * (g + wd * weight._data)


@register
class Adam(Optimizer):
    """(ref: optimizer.py:1120 Adam) with bias-corrected lr."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        dt = str(weight.dtype)
        return (zeros(weight.shape, dtype=dt), zeros(weight.shape, dtype=dt))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        attrs = self._common_attrs(index)
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        attrs["lr"] = attrs["lr"] * math.sqrt(coef2) / coef1
        mean, var = state
        if _is_row_sparse(grad):
            if not self.lazy_update:
                grad = grad.todense()
            else:
                # lazy Adam (ref: AdamUpdateRspImpl): moments + weight touched
                # only at the gradient's rows
                lr, wd = attrs["lr"], attrs["wd"]
                rows, g = _sparse_grad_prep(self, grad)
                w = weight._data
                g = g + wd * w[rows]
                m_rows = self.beta1 * mean._data[rows] + (1 - self.beta1) * g
                v_rows = (self.beta2 * var._data[rows]
                          + (1 - self.beta2) * jnp.square(g))
                mean._data = mean._data.at[rows].set(m_rows)
                var._data = var._data.at[rows].set(v_rows)
                weight._data = w.at[rows].add(
                    -lr * m_rows / (jnp.sqrt(v_rows) + self.epsilon))
                return
        _writeback([weight, mean, var], _call(
            "adam_update", [weight, grad, mean, var],
            {**attrs, "beta1": self.beta1, "beta2": self.beta2, "epsilon": self.epsilon},
        ))


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, dtype=str(weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if _is_row_sparse(grad):
            # sparse AdaGrad (ref: AdagradUpdateRspImpl): history + weight
            # touched only at the gradient's rows
            rows, g = _sparse_grad_prep(self, grad)
            g = g + wd * weight._data[rows]
            h_rows = state._data[rows] + jnp.square(g)
            state._data = state._data.at[rows].set(h_rows)
            weight._data = weight._data.at[rows].add(
                -lr * g / (jnp.sqrt(h_rows) + self.float_stable_eps))
            return
        g = grad._data * self.rescale_grad
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        g = g + wd * weight._data
        state._data = state._data + jnp.square(g)
        weight._data = weight._data - lr * g / (jnp.sqrt(state._data) + self.float_stable_eps)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9, epsilon=1e-8,
                 centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2, self.epsilon = gamma1, gamma2, epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        dt = str(weight.dtype)
        if self.centered:
            return (zeros(weight.shape, dtype=dt), zeros(weight.shape, dtype=dt), zeros(weight.shape, dtype=dt))
        return zeros(weight.shape, dtype=dt)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        attrs = self._common_attrs(index)
        attrs["clip_weights"] = self.clip_weights if self.clip_weights else -1.0
        if self.centered:
            n, g, delta = state
            _writeback([weight, n, g, delta], _call(
                "rmspropalex_update", [weight, grad, n, g, delta],
                {**attrs, "gamma1": self.gamma1, "gamma2": self.gamma2, "epsilon": self.epsilon},
            ))
        else:
            _writeback([weight, state], _call(
                "rmsprop_update", [weight, grad, state],
                {**attrs, "gamma1": self.gamma1, "epsilon": self.epsilon},
            ))


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.9, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        dt = str(weight.dtype)
        return (zeros(weight.shape, dtype=dt), zeros(weight.shape, dtype=dt))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        g = grad._data * self.rescale_grad
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        acc_g, acc_delta = state
        acc_g._data = self.rho * acc_g._data + (1 - self.rho) * jnp.square(g)
        delta = jnp.sqrt(acc_delta._data + self.epsilon) / jnp.sqrt(acc_g._data + self.epsilon) * g
        acc_delta._data = self.rho * acc_delta._data + (1 - self.rho) * jnp.square(delta)
        weight._data = weight._data - delta - wd * weight._data


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        dt = str(weight.dtype)
        return (zeros(weight.shape, dtype=dt), zeros(weight.shape, dtype=dt))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        attrs = self._common_attrs(index)
        z, n = state
        _writeback([weight, z, n], _call(
            "ftrl_update", [weight, grad, z, n],
            {**attrs, "lamda1": self.lamda1, "beta": self.beta},
        ))


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        dt = str(weight.dtype)
        return (zeros(weight.shape, dtype=dt), zeros(weight.shape, dtype=dt))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index) / (1.0 - self.beta1 ** t)
        wd = self._get_wd(index)
        g = grad._data * self.rescale_grad + wd * weight._data
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        m, u = state
        m._data = self.beta1 * m._data + (1 - self.beta1) * g
        u._data = jnp.maximum(self.beta2 * u._data, jnp.abs(g))
        weight._data = weight._data - lr * m._data / (u._data + 1e-8)


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        dt = str(weight.dtype)
        return (zeros(weight.shape, dtype=dt), zeros(weight.shape, dtype=dt))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad._data * self.rescale_grad + wd * weight._data
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m, v = state
        g_prime = g / (1.0 - self.m_schedule)
        m._data = self.beta1 * m._data + (1.0 - self.beta1) * g
        v._data = self.beta2 * v._data + (1.0 - self.beta2) * jnp.square(g)
        m_prime = m._data / (1.0 - m_schedule_next)
        v_prime = v._data / (1.0 - self.beta2 ** t)
        m_bar = (1.0 - momentum_t) * g_prime + momentum_t_1 * m_prime
        weight._data = weight._data - lr * m_bar / (jnp.sqrt(v_prime) + self.epsilon)


@register
class AdamW(Optimizer):
    """Decoupled weight decay Adam (ref: src/operator/contrib/adamw.cc)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, eta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon, self.eta = beta1, beta2, epsilon, eta

    def create_state(self, index, weight):
        dt = str(weight.dtype)
        return (zeros(weight.shape, dtype=dt), zeros(weight.shape, dtype=dt))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        attrs = self._common_attrs(index)
        mean, var = state
        _writeback([weight, mean, var], _call(
            "adamw_update", [weight, grad, mean, var],
            {**attrs, "beta1": self.beta1, "beta2": self.beta2,
             "epsilon": self.epsilon, "eta": self.eta},
        ))


@register
class Test(Optimizer):
    def create_state(self, index, weight):
        return zeros(weight.shape, dtype=str(weight.dtype))

    def update(self, index, weight, grad, state):
        weight._data = weight._data - self.rescale_grad * grad._data


class Updater:
    """State machine applying an optimizer per key
    (ref: optimizer.py:1621 Updater — used by KVStore as the updater fn)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}
        self.aggregate_updates = optimizer.aggregate_num > 0

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad, self.states[index])

    def get_states(self, dump_optimizer=False):
        def _np(state):
            if state is None:
                return None
            if isinstance(state, (list, tuple)):
                return tuple(_np(s) for s in state)
            return state.asnumpy()

        states = {k: _np(v) for k, v in self.states.items()}
        return pickle.dumps((states, self.optimizer) if dump_optimizer else states)

    def set_states(self, states_blob):
        states = pickle.loads(states_blob)
        if isinstance(states, tuple) and len(states) == 2:
            states, self.optimizer = states

        def _nd(state):
            if state is None:
                return None
            if isinstance(state, (list, tuple)):
                return tuple(_nd(s) for s in state)
            return NDArray(state)

        self.states = {k: _nd(v) for k, v in states.items()}
        self.states_synced = {k: True for k in self.states}


def get_updater(optimizer):
    return Updater(optimizer)


# ---------------------------------------------------------------------------
# Pure fused-update hooks used by fused.GluonTrainStep (traced inside jit;
# everything here is jnp math on raw arrays).
# ---------------------------------------------------------------------------


def _stochastic_round_bf16(x32, key):
    """Round f32 to bf16 with probability proportional to the distance to
    each neighboring bf16 value, so the rounding error is zero-mean and
    small updates (below bf16's ~2^-8 relative resolution) accumulate in
    expectation instead of being silently dropped by round-to-nearest.

    Bit trick: bf16 is the top 16 bits of f32, so adding a uniform 16-bit
    integer to the f32 bit pattern and truncating the low half rounds up
    with exactly the right probability; values already representable in
    bf16 (low bits zero) are never changed. Non-finite inputs pass through
    untouched — the integer walk would corrupt inf/nan payloads."""
    import jax
    from jax import lax

    bits = jax.random.bits(key, x32.shape, jnp.uint32) & jnp.uint32(0xFFFF)
    u = lax.bitcast_convert_type(x32, jnp.uint32)
    r = (u + bits) & jnp.uint32(0xFFFF0000)
    out = lax.bitcast_convert_type(r, jnp.float32)
    out = jnp.where(jnp.isfinite(x32), out, x32)
    return out.astype(jnp.bfloat16)


def _sr_key(opt, t, name):
    """Deterministic per-(step, param) PRNG key — the SGLD fused-noise
    idiom, shared verbatim by the eager, aggregated, and fused SR paths so
    their rounding draws (and therefore their weights) agree exactly."""
    import binascii

    import jax

    key = jax.random.PRNGKey(getattr(opt, "fused_seed", 0))
    key = jax.random.fold_in(key, jnp.asarray(t, jnp.int32))
    key = jax.random.fold_in(key, binascii.crc32(str(name).encode()) & 0x7FFFFFFF)
    return key


def _sgd_sr_math(opt, weight, grad, state, lr, wd, t, name):
    """Master-free bf16 SGD step (MXTPU_STOCHASTIC_ROUNDING): all math in
    f32 (momentum IS f32 — create_state_multi_precision / the fused-state
    hook allocate it that way), new weight stochastically rounded back to
    bf16. Versus the (mom, w32-master) mp state this halves the resident
    f32 bytes and removes the master read+write from every step's HBM
    traffic; the unbiased rounding is what keeps convergence within
    tolerance of the f32-master baseline."""
    w32 = weight.astype(jnp.float32)
    g = grad.astype(jnp.float32) * opt.rescale_grad
    if opt.clip_gradient:
        g = jnp.clip(g, -opt.clip_gradient, opt.clip_gradient)
    g = g + wd * w32
    if opt.momentum != 0.0 and state is not None:
        new_mom = opt.momentum * state - lr * g
        new_w32 = w32 + new_mom
    else:
        new_mom = state
        new_w32 = w32 - lr * g
    return _stochastic_round_bf16(new_w32, _sr_key(opt, t, name)), new_mom


def _sgd_fused(self, name, weight, grad, state, lr, t=None):
    if isinstance(state, tuple):
        # multi-precision state (mom_or_None, fp32 master) from
        # create_state_multi_precision — route through the mp ops
        from .ops import optimizer as _oo

        lr, wd = _mults(self, name, lr)
        clip = self.clip_gradient if self.clip_gradient else -1.0
        mom, w32 = state
        if mom is not None:
            w2, m2, w322 = _oo.mp_sgd_mom_update(
                weight, grad, mom, w32, lr=lr, momentum=self.momentum,
                wd=wd, rescale_grad=self.rescale_grad, clip_gradient=clip)
            return w2, (m2, w322)
        w2, w322 = _oo.mp_sgd_update(
            weight, grad, w32, lr=lr, wd=wd,
            rescale_grad=self.rescale_grad, clip_gradient=clip)
        return w2, (None, w322)
    if self._sr_active(weight):
        lr, wd = _mults(self, name, lr)
        return _sgd_sr_math(self, weight, grad, state, lr, wd,
                            _t_or_eager(self, t), name)
    g = grad * self.rescale_grad
    if self.clip_gradient:
        g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
    lr, wd = _mults(self, name, lr)
    g = g + wd * weight
    if self.momentum != 0.0 and state is not None:
        new_mom = self.momentum * state - lr * g
        return weight + new_mom, new_mom
    return weight - lr * g, None


SGD.fused_update = _sgd_fused
# (LBSGD gets its own LARS-aware fused hook below)


def _sgd_create_fused_state(self, index, weight):
    """Fused-path state: f32 momentum when stochastic rounding is active
    on a bf16 weight (the scanned carry keeps the accumulator in full
    precision; _cast_state_like then preserves f32 across steps). With
    multi_precision on a low-precision weight, the (mom, f32 master)
    tuple — fused_update already routes tuples through the mp ops, and
    under MXTPU_SHARD_POLICY the master rides the state tree into the
    ZeRO placement (1/N of the f32 bytes per device). Otherwise
    identical to create_state."""
    if self._sr_active(weight):
        if self.momentum != 0.0:
            return zeros(weight.shape, dtype="float32")
        return None
    if self.multi_precision and str(weight.dtype) in ("float16", "bfloat16"):
        return self.create_state_multi_precision(index, weight)
    return self.create_state(index, weight)


SGD.create_fused_state = _sgd_create_fused_state


def _nag_fused(self, name, weight, grad, state, lr, t=None):
    g = grad * self.rescale_grad
    if self.clip_gradient:
        g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
    lr, wd = _mults(self, name, lr)
    g = g + wd * weight
    if self.momentum != 0.0 and state is not None:
        new_mom = self.momentum * state + g
        return weight - lr * (g + self.momentum * new_mom), new_mom
    return weight - lr * g, None


NAG.fused_update = _nag_fused


def _adam_fused(self, name, weight, grad, state, lr, t=None):
    g = grad * self.rescale_grad
    if self.clip_gradient:
        g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
    lr, wd = _mults(self, name, lr)
    g = g + wd * weight
    mean, var = state
    # t is a traced per-step input when driven by GluonTrainStep (so K
    # scanned steps each see their own update count); fall back to the
    # eager counter otherwise
    if t is None:
        t = float(self.num_update)
    t = jnp.maximum(jnp.asarray(t, jnp.float32), 1.0)
    new_mean = self.beta1 * mean + (1 - self.beta1) * g
    new_var = self.beta2 * var + (1 - self.beta2) * jnp.square(g)
    # -expm1(t*log(beta)) == 1 - beta**t without the fp32 catastrophic
    # cancellation at small t (beta2=0.999, t=1: naive form loses ~4 digits)
    coef1 = _one_minus_pow(self.beta1, t)
    coef2 = _one_minus_pow(self.beta2, t)
    lr_t = lr * jnp.sqrt(coef2) / coef1
    return (
        weight - lr_t * new_mean / (jnp.sqrt(new_var) + self.epsilon),
        (new_mean, new_var),
    )


Adam.fused_update = _adam_fused


def _one_minus_pow(beta, t):
    """1 - beta**t for traced t, cancellation-free (beta is a Python float;
    its log is taken in double precision before entering the trace)."""
    if beta <= 0.0:
        return jnp.ones_like(t)
    return -jnp.expm1(t * math.log(beta))


def _mults(self, name, lr):
    """Per-parameter lr/wd with name-keyed multipliers (the fused-path
    analog of _get_lr/_get_wd, which are index-keyed on the eager path;
    like them, a param_dict entry takes EXCLUSIVE priority over the
    set_lr_mult/set_wd_mult dicts)."""
    if name in self.param_dict:
        lr = lr * self.param_dict[name].lr_mult
        wd = self.wd * self.param_dict[name].wd_mult
    else:
        lr = lr * self.lr_mult.get(name, 1.0)
        wd = self.wd * self.wd_mult.get(name, 1.0)
    return lr, wd


def _t_or_eager(self, t):
    """Per-step update count: traced input under GluonTrainStep (each of K
    scanned steps sees its own t), eager counter otherwise."""
    if t is None:
        t = float(max(self.num_update, 1))
    return jnp.maximum(jnp.asarray(t, jnp.float32), 1.0)


def _signum_fused(self, name, weight, grad, state, lr, t=None):
    from .ops import optimizer as _oo

    lr, wd = _mults(self, name, lr)
    clip = self.clip_gradient if self.clip_gradient else -1.0
    if state is not None:
        w, m = _oo.signum_update(weight, grad, state, lr=lr, momentum=self.momentum,
                                 wd=wd, rescale_grad=self.rescale_grad,
                                 clip_gradient=clip, wd_lh=self.wd_lh)
        return w, m
    return _oo.signsgd_update(weight, grad, lr=lr, wd=wd,
                              rescale_grad=self.rescale_grad,
                              clip_gradient=clip), None


Signum.fused_update = _signum_fused


def _ftml_fused(self, name, weight, grad, state, lr, t=None):
    from .ops import optimizer as _oo

    lr, wd = _mults(self, name, lr)
    d, v, z = state
    w, d2, v2, z2 = _oo.ftml_update(
        weight, grad, d, v, z, lr=lr, beta1=self.beta1, beta2=self.beta2,
        epsilon=self.epsilon, wd=wd, rescale_grad=self.rescale_grad,
        clip_grad=self.clip_gradient if self.clip_gradient else -1.0,
        t=_t_or_eager(self, t))
    return w, (d2, v2, z2)


FTML.fused_update = _ftml_fused


def _dcasgd_fused(self, name, weight, grad, state, lr, t=None):
    lr, wd = _mults(self, name, lr)
    g = grad * self.rescale_grad
    if self.clip_gradient:
        g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
    mom, prev = state
    comp = g + wd * weight + self.lamda * g * g * (weight - prev)
    if mom is not None:
        new_mom = self.momentum * mom - lr * comp
        return weight + new_mom, (new_mom, weight)
    return weight - lr * comp, (None, weight)


DCASGD.fused_update = _dcasgd_fused


def _lbsgd_fused(self, name, weight, grad, state, lr, t=None):
    """LARS trust-ratio SGD — matches LBSGD.update (NOT plain SGD)."""
    lr, wd = _mults(self, name, lr)
    g = grad * self.rescale_grad
    if self.clip_gradient:
        g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
    wnorm = jnp.linalg.norm(weight)
    gnorm = jnp.linalg.norm(g)
    ratio = jnp.where((wnorm > 0) & (gnorm > 0),
                      wnorm / (gnorm + wd * wnorm + 1e-9), 1.0)
    eff_lr = lr * ratio
    if state is not None:
        new_mom = self.momentum * state - eff_lr * (g + wd * weight)
        return weight + new_mom, new_mom
    return weight - eff_lr * (g + wd * weight), None


LBSGD.fused_update = _lbsgd_fused


def _adagrad_fused(self, name, weight, grad, state, lr, t=None):
    lr, wd = _mults(self, name, lr)
    g = grad * self.rescale_grad
    if self.clip_gradient:
        g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
    g = g + wd * weight
    h = state + jnp.square(g)
    return weight - lr * g / (jnp.sqrt(h) + self.float_stable_eps), h


AdaGrad.fused_update = _adagrad_fused


def _rmsprop_fused(self, name, weight, grad, state, lr, t=None):
    from .ops import optimizer as _oo

    lr, wd = _mults(self, name, lr)
    clip = self.clip_gradient if self.clip_gradient else -1.0
    cw = self.clip_weights if self.clip_weights else -1.0
    if self.centered:
        n, g, delta = state
        w, n2, g2, d2 = _oo.rmspropalex_update(
            weight, grad, n, g, delta, lr=lr, gamma1=self.gamma1,
            gamma2=self.gamma2, epsilon=self.epsilon, wd=wd,
            rescale_grad=self.rescale_grad, clip_gradient=clip,
            clip_weights=cw)
        return w, (n2, g2, d2)
    w, n2 = _oo.rmsprop_update(
        weight, grad, state, lr=lr, gamma1=self.gamma1, epsilon=self.epsilon,
        wd=wd, rescale_grad=self.rescale_grad, clip_gradient=clip,
        clip_weights=cw)
    return w, n2


RMSProp.fused_update = _rmsprop_fused


def _adadelta_fused(self, name, weight, grad, state, lr, t=None):
    _, wd = _mults(self, name, lr)  # AdaDelta ignores lr (as in update())
    g = grad * self.rescale_grad
    if self.clip_gradient:
        g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
    acc_g, acc_delta = state
    acc_g2 = self.rho * acc_g + (1 - self.rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + self.epsilon) / jnp.sqrt(acc_g2 + self.epsilon) * g
    acc_delta2 = self.rho * acc_delta + (1 - self.rho) * jnp.square(delta)
    return weight - delta - wd * weight, (acc_g2, acc_delta2)


AdaDelta.fused_update = _adadelta_fused


def _ftrl_fused(self, name, weight, grad, state, lr, t=None):
    from .ops import optimizer as _oo

    lr, wd = _mults(self, name, lr)
    z, n = state
    w, z2, n2 = _oo.ftrl_update(
        weight, grad, z, n, lr=lr, lamda1=self.lamda1, beta=self.beta, wd=wd,
        rescale_grad=self.rescale_grad,
        clip_gradient=self.clip_gradient if self.clip_gradient else -1.0)
    return w, (z2, n2)


Ftrl.fused_update = _ftrl_fused


def _adamax_fused(self, name, weight, grad, state, lr, t=None):
    lr, wd = _mults(self, name, lr)
    t = _t_or_eager(self, t)
    lr = lr / _one_minus_pow(self.beta1, t)
    g = grad * self.rescale_grad + wd * weight
    if self.clip_gradient:
        g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
    m, u = state
    m2 = self.beta1 * m + (1 - self.beta1) * g
    u2 = jnp.maximum(self.beta2 * u, jnp.abs(g))
    return weight - lr * m2 / (u2 + 1e-8), (m2, u2)


Adamax.fused_update = _adamax_fused


def _nadam_create_fused_state(self, index, weight):
    """(m, v, m_schedule): the eager path keeps m_schedule as a shared
    Python float mutated once per update() CALL (an MXNet quirk: N params
    advance it N times per step); the traced path cannot mutate Python
    state, so it carries a PER-PARAMETER m_schedule — the textbook Nadam
    schedule — as a scalar in the state tuple."""
    dt = str(weight.dtype)
    return (zeros(weight.shape, dtype=dt), zeros(weight.shape, dtype=dt),
            ones((), dtype=dt))


def _nadam_fused(self, name, weight, grad, state, lr, t=None):
    lr, wd = _mults(self, name, lr)
    t = _t_or_eager(self, t)
    g = grad * self.rescale_grad + wd * weight
    if self.clip_gradient:
        g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
    m, v, m_sched = state
    momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
    momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
    m_sched2 = m_sched * momentum_t
    m_sched_next = m_sched2 * momentum_t_1
    g_prime = g / (1.0 - m_sched2)
    m2 = self.beta1 * m + (1.0 - self.beta1) * g
    v2 = self.beta2 * v + (1.0 - self.beta2) * jnp.square(g)
    m_prime = m2 / (1.0 - m_sched_next)
    v_prime = v2 / _one_minus_pow(self.beta2, t)
    m_bar = (1.0 - momentum_t) * g_prime + momentum_t_1 * m_prime
    w2 = weight - lr * m_bar / (jnp.sqrt(v_prime) + self.epsilon)
    return w2, (m2, v2, m_sched2)


Nadam.create_fused_state = _nadam_create_fused_state
Nadam.fused_update = _nadam_fused
# per-parameter m_schedule vs the eager path's shared Python float advanced
# N times per step: trajectories differ by design, so the aggregated
# Trainer path must not treat fused as an eager drop-in
Nadam.fused_matches_eager = False


def _adamw_fused(self, name, weight, grad, state, lr, t=None):
    from .ops import optimizer as _oo

    lr, wd = _mults(self, name, lr)
    mean, var = state
    w, m2, v2 = _oo.adamw_update(
        weight, grad, mean, var, lr=lr, beta1=self.beta1, beta2=self.beta2,
        epsilon=self.epsilon, wd=wd, eta=self.eta,
        rescale_grad=self.rescale_grad,
        clip_gradient=self.clip_gradient if self.clip_gradient else -1.0)
    return w, (m2, v2)


AdamW.fused_update = _adamw_fused


def _sgld_fused(self, name, weight, grad, state, lr, t=None):
    """SGLD inside the trace: the Langevin noise key is derived
    deterministically from (seed attr, step t, param name) via fold_in —
    the eager path draws from the global RNG stream instead."""
    import binascii

    import jax

    lr, wd = _mults(self, name, lr)
    g = grad * self.rescale_grad
    if self.clip_gradient:
        g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
    t = _t_or_eager(self, t)
    key = jax.random.PRNGKey(getattr(self, "fused_seed", 0))
    key = jax.random.fold_in(key, jnp.asarray(t, jnp.int32))
    key = jax.random.fold_in(key, binascii.crc32(name.encode()) & 0x7FFFFFFF)
    noise = jax.random.normal(key, weight.shape, weight.dtype) * jnp.sqrt(lr)
    return weight - lr / 2 * (g + wd * weight) + noise, None


SGLD.fused_update = _sgld_fused
# deterministic fold_in noise vs the eager global RNG stream: same
# distribution, different draws — excluded from eager-equivalent aggregation
SGLD.fused_matches_eager = False


def _test_fused(self, name, weight, grad, state, lr, t=None):
    return weight - self.rescale_grad * grad, state


Test.fused_update = _test_fused


def _generic_fused(self, name, weight, grad, state, lr, t=None):
    """Base-class fallback for CUSTOM optimizers without a dedicated
    fused_update: runs the eager update() on NDArray views inside the jit
    trace, routing the traced per-step lr through self.lr for the duration
    of the trace.

    Caveat (documented in fused.GluonTrainStep): anything update() reads
    from Python state — self._index_update_count (time-dependent bias
    correction), host RNG draws — is baked in at TRACE time and frozen
    thereafter. Time-dependent or stochastic custom optimizers should
    implement fused_update; every built-in optimizer already has an exact
    one."""

    def _wrap(s):
        if s is None:
            return None
        if isinstance(s, (tuple, list)):
            return tuple(_wrap(e) for e in s)
        return NDArray(s)

    def _unwrap(s):
        if s is None:
            return None
        if isinstance(s, (tuple, list)):
            return tuple(_unwrap(e) for e in s)
        return s._data

    w, g, st = NDArray(weight), NDArray(grad), _wrap(state)
    old_lr, old_sched = self.lr, self.lr_scheduler
    self.lr, self.lr_scheduler = lr, None
    try:
        self.update(name, w, g, st)
    finally:
        self.lr, self.lr_scheduler = old_lr, old_sched
    return w._data, _unwrap(st)


Optimizer.fused_update = _generic_fused
