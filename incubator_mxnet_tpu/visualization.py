"""Network visualization (ref: python/mxnet/visualization.py)."""
from __future__ import annotations

import json

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=(0.44, 0.64, 0.74, 1.0)):
    """(ref: visualization.py print_summary)"""
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    if shape is not None:
        _, out_shapes, _ = symbol.get_internals().infer_shape(**shape)
        shape_dict = dict(zip(symbol.get_internals().list_outputs(), out_shapes))
    else:
        shape_dict = {}

    positions = [int(line_length * p) for p in positions]
    to_display = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(fields, positions):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[: positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(to_display, positions)
    print("=" * line_length)
    total_params = 0
    for node in nodes:
        op = node["op"]
        name = node["name"]
        if op == "null":
            continue
        out_name = f"{name}_output"
        out_shape = shape_dict.get(out_name, "")
        pre = [nodes[item[0]]["name"] for item in node["inputs"]]
        print_row([f"{name} ({op})", out_shape, 0, ",".join(pre[:2])], positions)
        total_params += 0
    print("=" * line_length)


def plot_network(symbol, title="plot", save_format="pdf", shape=None, dtype=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz plot; returns a Digraph when graphviz is installed."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError("plot_network requires graphviz") from e
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    dot = Digraph(name=title)
    for i, node in enumerate(nodes):
        op = node["op"]
        name = node["name"]
        if op == "null":
            if hide_weights and (name.endswith("weight") or name.endswith("bias") or
                                 name.endswith("gamma") or name.endswith("beta") or
                                 "moving" in name):
                continue
            dot.node(name=name, label=name, shape="oval")
        else:
            dot.node(name=name, label=f"{op}\n{name}", shape="box")
        for item in node["inputs"]:
            src = nodes[item[0]]["name"]
            dot.edge(tail_name=src, head_name=name)
    return dot
