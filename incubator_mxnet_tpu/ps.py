"""True parameter-server backend for `dist_async`
(ref: src/kvstore/kvstore_dist_server.h — KVStoreDistServer: async path
applies updates the moment a push arrives (:348-358), sync path aggregates
num_workers contributions before one update (:346); workers ship the
optimizer to the server via CommandType::kController, and serve
row_sparse pulls row-by-row (:499)).

TPU-native stance: the DEFAULT multi-host story here is serverless —
GSPMD all-reduce over ICI/DCN (`dist_sync`) and bounded-staleness elastic
averaging (`dist_async`), because collectives are what the interconnect
fabric is built for. But the reference's `dist_async` has a distinct
semantic — a SERVER applies each worker's update to the authoritative
weights the instant it arrives, so workers never wait on each other and
never average trajectories. That semantic matters for reproducing async-SGD
papers/workloads, so it exists here as an opt-in control-plane service:
weights live on host at rank 0 (device compute stays jitted on workers),
pushes/pulls ride a length-prefixed TCP protocol exactly like ps-lite rode
zmq. Enable with kvstore type 'dist_async_server'.
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading

import numpy as np

__all__ = ["ParameterServer", "PSClient", "default_server_addr"]

_LEN = struct.Struct(">Q")


def _send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n))


def default_server_addr():
    """Server address derived from the launcher's coordinator: same host,
    coordinator port + 23 (the launcher reserves adjacent ports)."""
    from . import config as _config

    addr = _config.get("MXTPU_PS_ADDR")
    if addr:
        host, port = addr.rsplit(":", 1)
        return host, int(port)
    coord = _config.get("MXTPU_COORDINATOR")
    if ":" in coord:
        host, port = coord.rsplit(":", 1)
        return host, int(port) + 23
    return "127.0.0.1", 9923


class ParameterServer:
    """Authoritative weight store + server-side optimizer
    (ref: KVStoreDistServer, kvstore_dist_server.h:200).

    One handler thread per worker connection; per-key locks make the async
    apply atomic per key while pushes to different keys proceed in
    parallel (the reference got this from ps-lite's per-key request
    serialization).
    """

    def __init__(self, num_workers, host="0.0.0.0", port=9923):
        self.num_workers = num_workers
        self._store = {}           # key -> np.ndarray (authoritative)
        self._locks = {}           # key -> threading.Lock
        self._locks_guard = threading.Lock()
        self._updater = None
        self._compressor = None
        # sync-mode aggregation (ref: DataHandleDefault sync path :346)
        self._merge = {}           # key -> (buf, count)
        self._sync_cv = threading.Condition()
        self._versions = {}        # key -> applied-update count
        # barrier bookkeeping (ref: ps-lite Postoffice::Barrier)
        self._barrier_cv = threading.Condition()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(num_workers + 2)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._threads = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="mxtpu-ps-accept")
        self._accept_thread.start()

    # --- plumbing ---------------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            if self._stop.is_set():
                conn.close()
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True, name="mxtpu-ps-worker")
            t.start()
            self._threads.append(t)

    def _key_lock(self, key):
        with self._locks_guard:
            return self._locks.setdefault(key, threading.Lock())

    def _serve(self, conn):
        try:
            while True:
                msg = _recv_msg(conn)
                cmd = msg[0]
                if cmd == "stop":
                    _send_msg(conn, ("ok",))
                    self.shutdown()
                    return
                _send_msg(conn, self._dispatch(cmd, msg[1:]))
        except (ConnectionError, OSError, EOFError):
            pass
        finally:
            conn.close()

    def _dispatch(self, cmd, args):
        try:
            return getattr(self, "_cmd_" + cmd)(*args)
        except Exception as e:  # ship the failure to the worker
            return ("err", f"{type(e).__name__}: {e}")

    # --- commands ---------------------------------------------------------
    def _cmd_init(self, key, value):
        """First writer wins (rank 0 inits; ref: kvstore_dist.h Init)."""
        with self._key_lock(key):
            if key not in self._store:
                self._store[key] = np.array(value, copy=True)
                self._versions[key] = 0
        return ("ok",)

    def _cmd_set_optimizer(self, blob):
        """(ref: CommandType::kController — the worker ships the pickled
        optimizer, the server builds its updater from it)."""
        from . import optimizer as _opt

        self._updater = _opt.get_updater(pickle.loads(blob))
        return ("ok",)

    def _cmd_get_optimizer_states(self, dump_optimizer):
        if self._updater is None:
            raise RuntimeError("no optimizer set on the server")
        return ("val", self._updater.get_states(dump_optimizer))

    def _cmd_set_optimizer_states(self, blob):
        if self._updater is None:
            raise RuntimeError("no optimizer set on the server")
        self._updater.set_states(blob)
        return ("ok",)

    def _cmd_set_optimizer_attrs(self, attrs):
        """Live optimizer mutation (lr schedules, rescale_grad) without
        rebuilding the updater — state survives."""
        if self._updater is None:
            raise RuntimeError("no optimizer set on the server")
        opt = self._updater.optimizer
        for name, value in attrs.items():
            if not hasattr(opt, name):
                raise AttributeError(f"optimizer has no attribute {name!r}")
            setattr(opt, name, value)
        return ("ok",)

    def _cmd_set_compression(self, params):
        from .kvstore import _make_compressor

        self._compressor = _make_compressor(dict(params))
        return ("ok",)

    def _apply(self, key, grad):
        from .ndarray.ndarray import NDArray

        stored = self._store[key]
        if self._updater is not None:
            w = NDArray(stored)
            # pass the key through untouched — string keys carry the
            # idx2name/lr_mult/wd_mult identity the optimizer looks up
            self._updater(key, NDArray(grad), w)
            self._store[key] = np.asarray(w.asnumpy())
        else:
            self._store[key] = stored + grad
        self._versions[key] += 1

    def _cmd_push(self, key, grad, sync):
        grad = np.asarray(grad)
        if not sync:
            # async: apply instantly, nobody waits (ref: :348-358)
            with self._key_lock(key):
                self._apply(key, grad)
            return ("ok",)
        # sync: aggregate num_workers contributions, apply once, release
        # everyone at the new version (ref: :346 merge buffer path)
        with self._sync_cv:
            buf, count = self._merge.get(key, (None, 0))
            buf = grad if buf is None else buf + grad
            count += 1
            if count == self.num_workers:
                with self._key_lock(key):
                    self._apply(key, buf)
                self._merge[key] = (None, 0)
                self._sync_cv.notify_all()
            else:
                self._merge[key] = (buf, count)
                target = self._versions[key] + 1
                ok = self._sync_cv.wait_for(
                    lambda: self._versions[key] >= target, timeout=300)
                if not ok:
                    # a peer died mid-rendezvous: drop the stale buffer so a
                    # retry cannot double-count, and surface the failure
                    self._merge[key] = (None, 0)
                    raise TimeoutError(
                        f"sync push on {key!r} waited 300s for "
                        f"{self.num_workers} contributions")
        return ("ok",)

    def _cmd_push_rows(self, key, indices, rows):
        """Sparse push: apply only the occupied rows, through the
        optimizer's sparse/lazy path (ref: DataHandleRowSparse :499)."""
        from .ndarray.ndarray import NDArray
        from .ndarray.sparse import RowSparseNDArray

        indices = np.asarray(indices, np.int64)
        rows = np.asarray(rows)
        with self._key_lock(key):
            stored = self._store[key]
            if self._updater is not None:
                rsp = RowSparseNDArray(NDArray(rows), NDArray(indices),
                                       stored.shape)
                w = NDArray(stored)
                self._updater(key, rsp, w)
                self._store[key] = np.asarray(w.asnumpy())
            else:
                upd = stored.copy()
                np.add.at(upd, indices, rows)
                self._store[key] = upd
            self._versions[key] += 1
        return ("ok",)

    def _cmd_push_compressed(self, key, payload, shape):
        """Decode the worker's packed 2-bit payload server-side
        (ref: DataHandleCompressed kvstore_dist_server.h:394)."""
        if self._compressor is None:
            raise RuntimeError("server has no compressor configured")
        grad = np.asarray(self._compressor.decode(payload, tuple(shape)))
        with self._key_lock(key):
            self._apply(key, grad)
        return ("ok",)

    def _cmd_pull(self, key):
        with self._key_lock(key):
            return ("val", np.array(self._store[key], copy=True))

    def _cmd_pull_rows(self, key, row_ids):
        """Serve only the requested rows (ref: DataHandleRowSparse :499)."""
        rows = np.asarray(row_ids, dtype=np.int64)
        with self._key_lock(key):
            return ("val", np.array(self._store[key][rows], copy=True))

    def _cmd_barrier(self):
        with self._barrier_cv:
            gen = self._barrier_gen
            self._barrier_count += 1
            if self._barrier_count == self.num_workers:
                self._barrier_count = 0
                self._barrier_gen += 1
                self._barrier_cv.notify_all()
            else:
                ok = self._barrier_cv.wait_for(
                    lambda: self._barrier_gen > gen, timeout=300)
                if not ok:
                    self._barrier_count -= 1
                    raise TimeoutError(
                        f"barrier waited 300s with only "
                        f"{self._barrier_count + 1}/{self.num_workers} "
                        "workers present")
        return ("ok",)

    def _cmd_keys(self):
        return ("val", sorted(self._store, key=str))

    def shutdown(self):
        self._stop.set()
        # shutdown() (not just close()) wakes a thread blocked in accept();
        # close() alone leaves it blocked on a stale fd which the NEXT
        # server's listener can reuse — the old loop would then steal the
        # new server's connections
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=10)


class PSClient:
    """Worker-side connection (ref: kvstore_dist.h push/pull over ps-lite).

    Thread-safe: one socket, request/response framing under a lock.
    """

    def __init__(self, host, port, retries=60):
        import time

        self._lock = threading.Lock()
        last = None
        for _ in range(retries):
            try:
                self._sock = socket.create_connection((host, port), timeout=30)
                break
            except OSError as e:  # server may not be up yet
                last = e
                time.sleep(0.5)
        else:
            raise ConnectionError(
                f"parameter server at {host}:{port} unreachable: {last}")
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # outlive the server's own 300s rendezvous waits, which raise a
        # proper error instead of this socket timing out first
        self._sock.settimeout(320)

    def _rpc(self, *msg):
        with self._lock:
            _send_msg(self._sock, msg)
            resp = _recv_msg(self._sock)
        if resp[0] == "err":
            raise RuntimeError(f"parameter server: {resp[1]}")
        return resp[1] if len(resp) > 1 else None

    def init(self, key, value):
        return self._rpc("init", key, np.asarray(value))

    def push(self, key, grad, sync=False):
        return self._rpc("push", key, np.asarray(grad), bool(sync))

    def push_compressed(self, key, payload, shape):
        return self._rpc("push_compressed", key, np.asarray(payload),
                         tuple(shape))

    def push_rows(self, key, indices, rows):
        return self._rpc("push_rows", key, np.asarray(indices),
                         np.asarray(rows))

    def set_optimizer_attrs(self, attrs):
        return self._rpc("set_optimizer_attrs", dict(attrs))

    def set_compression(self, params):
        return self._rpc("set_compression", dict(params))

    def get_optimizer_states(self, dump_optimizer=False):
        return self._rpc("get_optimizer_states", bool(dump_optimizer))

    def set_optimizer_states(self, blob):
        return self._rpc("set_optimizer_states", blob)

    def pull(self, key):
        return self._rpc("pull", key)

    def pull_rows(self, key, row_ids):
        return self._rpc("pull_rows", key, np.asarray(row_ids))

    def set_optimizer(self, optimizer):
        return self._rpc("set_optimizer",
                         pickle.dumps(optimizer,
                                      protocol=pickle.HIGHEST_PROTOCOL))

    def barrier(self):
        return self._rpc("barrier")

    def keys(self):
        return self._rpc("keys")

    def stop_server(self):
        try:
            self._rpc("stop")
        except (RuntimeError, ConnectionError, OSError):
            pass

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
