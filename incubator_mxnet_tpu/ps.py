"""True parameter-server backend for `dist_async`
(ref: src/kvstore/kvstore_dist_server.h — KVStoreDistServer: async path
applies updates the moment a push arrives (:348-358), sync path aggregates
num_workers contributions before one update (:346); workers ship the
optimizer to the server via CommandType::kController, and serve
row_sparse pulls row-by-row (:499)).

TPU-native stance: the DEFAULT multi-host story here is serverless —
GSPMD all-reduce over ICI/DCN (`dist_sync`) and bounded-staleness elastic
averaging (`dist_async`), because collectives are what the interconnect
fabric is built for. But the reference's `dist_async` has a distinct
semantic — a SERVER applies each worker's update to the authoritative
weights the instant it arrives, so workers never wait on each other and
never average trajectories. That semantic matters for reproducing async-SGD
papers/workloads, so it exists here as an opt-in control-plane service:
weights live on host at rank 0 (device compute stays jitted on workers),
pushes/pulls ride a length-prefixed TCP protocol exactly like ps-lite rode
zmq. Enable with kvstore type 'dist_async_server'.
"""
from __future__ import annotations

import collections
import hashlib
import hmac
import itertools
import logging
import os
import pickle
import secrets
import socket
import struct
import threading
import time
import zlib

import numpy as np

from .analysis.sanitizers import san_condition, san_lock

logger = logging.getLogger(__name__)

__all__ = ["ParameterServer", "PSClient", "default_server_addr",
           "StaleEpochError", "JoinRejectedError"]

_RECONNECT_METRIC = "mxtpu_ps_reconnects_total"
_RECONNECT_HELP = ("PSClient transparent reconnects after a mid-frame "
                   "socket error, by cause.")
_DEDUP_METRIC = "mxtpu_ps_dedup_hits_total"
_DEDUP_HELP = ("Retried mutating RPCs the ParameterServer suppressed via "
               "the per-client dedup window, by command.")
_EVICT_METRIC = "mxtpu_ps_evictions_total"
_EVICT_HELP = ("Workers evicted from the barrier/sync quorum after "
               "heartbeat staleness (dist graceful degradation).")
_JOIN_METRIC = "mxtpu_ps_joins_total"
_JOIN_HELP = ("Join RPCs the ParameterServer accepted, by outcome "
              "(registered / readmitted / pending).")
_READMIT_METRIC = "mxtpu_ps_readmissions_total"
_READMIT_HELP = ("Evicted ranks re-admitted to the quorum, via a fresh "
                 "heartbeat or a join RPC (elastic membership).")
_STALE_METRIC = "mxtpu_ps_stale_epoch_rejections_total"
_STALE_HELP = ("Sync contributions rejected for carrying a stale "
               "membership epoch, by command.")
_LEAVE_METRIC = "mxtpu_ps_leaves_total"
_LEAVE_HELP = ("Ranks that left the sync quorum via the graceful-leave "
               "RPC (preemption drain) — the quorum shrinks immediately, "
               "without waiting for a heartbeat timeout.")
_EPOCH_METRIC = "mxtpu_ps_membership_epoch"
_EPOCH_HELP = ("Current membership epoch of the ParameterServer; bumps on "
               "every membership change (readmission, rank takeover, "
               "world growth).")

# wire/socket errors after which a frame exchange cannot be trusted; the
# client closes and redials rather than reuse the poisoned socket
_WIRE_ERRORS = (OSError, EOFError, struct.error)


class StaleEpochError(RuntimeError):
    """A sync push/barrier carried a membership epoch older than the
    server's: the sender missed a membership change (join, readmission,
    takeover) and must refresh via PSClient.membership() before it may
    contribute again. Raised instead of silently merging the stale
    contribution, which would skew the synchronous gradient math."""


class JoinRejectedError(RuntimeError):
    """The server cannot admit this rank right now (the elastic world is
    at its MXTPU_MAX_WORKERS cap); the joiner backs off under its
    RetryPolicy and retries."""


# server-side errors cross the wire as "ClassName: message"; these names
# re-raise as their class on the client so callers can catch the protocol
# condition rather than parse a RuntimeError string
_ERR_CLASSES = {"StaleEpochError": StaleEpochError,
                "JoinRejectedError": JoinRejectedError}

# commands that ride the control plane every couple of seconds (the
# heartbeat thread) — never spanned/traced, they would drown the timeline
_UNTRACED_COMMANDS = frozenset({"heartbeat", "num_dead"})

_LEN = struct.Struct(">Q")
_U32 = struct.Struct(">I")
_F64 = struct.Struct(">d")

# ---------------------------------------------------------------------------
# Wire codec. The data plane (keys, tensors, shapes, attr dicts) crosses the
# socket in a closed tag-length-value format — NEVER pickle, so a host that
# can reach the port cannot execute code by connecting (the reference's
# ps-lite likewise shipped raw tensor bytes). The ONE pickle on the wire is
# the optimizer blob (ref: CommandType::kController ships a serialized
# optimizer); it travels as opaque bytes and is HMAC-authenticated with the
# job secret before either side unpickles it.
# ---------------------------------------------------------------------------


def _enc(obj, out):
    if obj is None:
        out.append(b"N")
    elif obj is True:
        out.append(b"T")
    elif obj is False:
        out.append(b"F")
    elif isinstance(obj, (int, np.integer)):
        b = str(int(obj)).encode("ascii")
        out.append(b"I" + _U32.pack(len(b)) + b)
    elif isinstance(obj, (float, np.floating)):
        out.append(b"f" + _F64.pack(float(obj)))
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out.append(b"S" + _U32.pack(len(b)) + b)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        b = bytes(obj)
        out.append(b"B" + _U32.pack(len(b)) + b)
    elif isinstance(obj, np.ndarray):
        if obj.dtype.hasobject:
            raise TypeError("object arrays cannot cross the PS wire")
        a = np.ascontiguousarray(obj)
        # dtype travels by NAME ('float32', 'bfloat16', ...) — .str would
        # collapse extension dtypes like ml_dtypes.bfloat16 to raw-void
        # '<V2' and silently corrupt them on decode
        if a.dtype.kind == "V" and a.dtype.name.startswith("void"):
            raise TypeError(f"dtype {a.dtype} cannot cross the PS wire")
        dt = a.dtype.name.encode("ascii")
        out.append(b"A" + _U32.pack(len(dt)) + dt + _U32.pack(a.ndim))
        for d in a.shape:
            out.append(_LEN.pack(d))
        raw = a.tobytes()
        out.append(_LEN.pack(len(raw)) + raw)
    elif isinstance(obj, (list, tuple)):
        out.append(b"L" + _U32.pack(len(obj)))
        for item in obj:
            _enc(item, out)
    elif isinstance(obj, dict):
        out.append(b"D" + _U32.pack(len(obj)))
        for k, v in obj.items():
            _enc(k, out)
            _enc(v, out)
    else:
        raise TypeError(
            f"{type(obj).__name__} cannot cross the PS wire; allowed: "
            "None/bool/int/float/str/bytes/ndarray/list/dict")


def _dec(buf, pos):
    tag = buf[pos:pos + 1]
    pos += 1
    if tag == b"N":
        return None, pos
    if tag == b"T":
        return True, pos
    if tag == b"F":
        return False, pos
    if tag == b"I":
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        return int(buf[pos:pos + n]), pos + n
    if tag == b"f":
        (v,) = _F64.unpack_from(buf, pos)
        return v, pos + 8
    if tag == b"S":
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        return buf[pos:pos + n].decode("utf-8"), pos + n
    if tag == b"B":
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        return bytes(buf[pos:pos + n]), pos + n
    if tag == b"A":
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        dtype = _dtype_by_name(buf[pos:pos + n].decode("ascii"))
        pos += n
        if dtype.hasobject:
            raise ValueError("object arrays cannot cross the PS wire")
        (ndim,) = _U32.unpack_from(buf, pos)
        pos += 4
        shape = []
        for _ in range(ndim):
            (d,) = _LEN.unpack_from(buf, pos)
            shape.append(d)
            pos += 8
        (nbytes,) = _LEN.unpack_from(buf, pos)
        pos += 8
        arr = np.frombuffer(buf[pos:pos + nbytes], dtype=dtype)
        return arr.reshape(shape).copy(), pos + nbytes
    if tag == b"L":
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        items = []
        for _ in range(n):
            item, pos = _dec(buf, pos)
            items.append(item)
        return tuple(items), pos
    if tag == b"D":
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        d = {}
        for _ in range(n):
            k, pos = _dec(buf, pos)
            v, pos = _dec(buf, pos)
            d[k] = v
        return d, pos
    raise ValueError(f"bad PS wire tag {tag!r}")


# low-precision accelerator dtypes numpy can't resolve by name
_EXT_DTYPES = ("bfloat16", "float8_e4m3fn", "float8_e5m2", "float8_e4m3",
               "float8_e4m3fnuz", "float8_e5m2fnuz", "int4", "uint4")


def _dtype_by_name(name):
    try:
        return np.dtype(name)
    except TypeError:
        if name in _EXT_DTYPES:
            import ml_dtypes

            return np.dtype(getattr(ml_dtypes, name))
        raise ValueError(f"unknown dtype {name!r} on the PS wire")


def _send_msg(sock, obj):
    out = []
    _enc(obj, out)
    payload = b"".join(out)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    obj, _ = _dec(_recv_exact(sock, n), 0)
    return obj


# Fallback secret when MXTPU_PS_SECRET is unset: random per process, so a
# same-process server+client pair (unit tests, single-host trainer) works
# out of the box while cross-process use without the launcher fails loudly.
_PROCESS_SECRET = secrets.token_bytes(32)


def _ps_secret():
    from . import config as _config

    s = _config.get("MXTPU_PS_SECRET")
    return s.encode("utf-8") if s else _PROCESS_SECRET


def _sign_blob(blob):
    return hmac.new(_ps_secret(), blob, hashlib.sha256).digest() + blob


def _verify_blob(signed):
    mac, blob = signed[:32], signed[32:]
    if not hmac.compare_digest(
            mac, hmac.new(_ps_secret(), blob, hashlib.sha256).digest()):
        raise PermissionError(
            "optimizer blob failed HMAC authentication; set "
            "MXTPU_PS_SECRET to the same value on every worker "
            "(tools/launch.py exports one automatically)")
    return blob


def default_server_addr():
    """Server address derived from the launcher's coordinator: same host,
    coordinator port + 23 (the launcher reserves adjacent ports)."""
    from . import config as _config

    addr = _config.get("MXTPU_PS_ADDR")
    if addr:
        host, port = addr.rsplit(":", 1)
        return host, int(port)
    coord = _config.get("MXTPU_COORDINATOR")
    if ":" in coord:
        host, port = coord.rsplit(":", 1)
        return host, int(port) + 23
    return "127.0.0.1", 9923


class ParameterServer:
    """Authoritative weight store + server-side optimizer
    (ref: KVStoreDistServer, kvstore_dist_server.h:200).

    One handler thread per worker connection; per-key locks make the async
    apply atomic per key while pushes to different keys proceed in
    parallel (the reference got this from ps-lite's per-key request
    serialization).
    """

    def __init__(self, num_workers, host=None, port=9923):
        if host is None:
            # default to the coordinator interface, NOT 0.0.0.0 — the
            # server should only be reachable over the interface the job
            # actually uses (an unauthenticated data plane on all
            # interfaces is a needless exposure)
            host = default_server_addr()[0]
        self.host = host
        self.num_workers = num_workers
        self._store = {}           # key -> np.ndarray (authoritative)
        self._locks = {}           # key -> threading.Lock
        self._locks_guard = san_lock("ps.locks_guard")
        self._updater = None
        self._compressor = None
        # sync-mode aggregation (ref: DataHandleDefault sync path :346)
        self._merge = {}           # key -> (buf, count)
        self._sync_cv = san_condition("ps.sync_cv")
        self._versions = {}        # key -> applied-update count
        # barrier bookkeeping (ref: ps-lite Postoffice::Barrier)
        self._barrier_cv = san_condition("ps.barrier_cv")
        self._barrier_count = 0
        self._barrier_gen = 0
        # worker heartbeats (ref: ps-lite Heartbeat/GetDeadNodes) — rides
        # the same TCP control plane, so dead-node detection works
        # cross-host with no shared filesystem
        self._beats = {}
        self._beats_lock = san_lock("ps.beats")
        self._start_time = time.time()
        from . import config as _config

        # rendezvous waits and replay suppression (docs/FAULT_TOLERANCE.md)
        self._sync_timeout = _config.get("MXTPU_PS_SYNC_TIMEOUT")
        self._dedup_window = max(1, _config.get("MXTPU_PS_DEDUP_WINDOW"))
        self._evict_timeout = _config.get("MXTPU_HEARTBEAT_TIMEOUT")
        self._dedup = {}           # client_id -> OrderedDict(seq -> entry)
        self._dedup_lock = san_lock("ps.dedup")
        # ranks seen via heartbeat then gone stale: they shrink the
        # barrier/sync quorum instead of hanging every survivor until the
        # rendezvous timeout; a fresh beat re-admits them
        self._evicted = set()
        # ranks that left via the graceful-leave RPC: unlike staleness
        # evictions, a stray late beat from the dying process must NOT
        # re-admit them — only an explicit join() does
        self._departed = set()
        # elastic membership (docs/FAULT_TOLERANCE.md — Elastic
        # membership): a monotonically-increasing epoch versions the rank
        # set; sync contributions carry it and stale ones are fenced.
        # Growth joins park in _pending_ranks until a barrier boundary so
        # no in-flight merge generation changes its expected world.
        self._epoch = 0
        self._owners = {}          # rank -> owning client_id
        self._pending_ranks = set()
        self._max_workers = _config.get("MXTPU_MAX_WORKERS")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._sock.bind((host, port))
        except OSError as e:
            import errno
            import warnings

            if e.errno != errno.EADDRNOTAVAIL:
                raise
            # the advertised address is not a local interface (NAT'd
            # external IP, docker-mapped name): fall back to all
            # interfaces so the job still comes up — loudly, since this
            # widens the listener beyond the coordinator interface
            warnings.warn(
                f"parameter server cannot bind {host!r} (not a local "
                "interface); listening on all interfaces instead")
            self._sock.bind(("0.0.0.0", port))
            self.host = "127.0.0.1"  # local clients reach it via loopback
        # backlog sized for the elastic cap, not just the starting world:
        # a mass rejoin may dial more sockets than num_workers
        self._sock.listen(max(num_workers, self._max_workers) + 2)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._threads = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="mxtpu-ps-accept")
        self._accept_thread.start()

    # --- plumbing ---------------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            if self._stop.is_set():
                conn.close()
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True, name="mxtpu-ps-worker")
            t.start()
            self._threads.append(t)

    def _key_lock(self, key):
        with self._locks_guard:
            return self._locks.setdefault(key, san_lock("ps.key"))

    def _serve(self, conn):
        try:
            while True:
                msg = _recv_msg(conn)
                ctx = None
                if msg[0] == "trc":
                    # tracing wrapper: ("trc", {tid, sid}, inner_frame) —
                    # the sender's span becomes this request's parent
                    info = msg[1]
                    if isinstance(info, dict) and info.get("sid"):
                        ctx = (info.get("tid"), info.get("sid"))
                    msg = msg[2]
                cmd = msg[0]
                if cmd == "stop":
                    _send_msg(conn, ("ok",))
                    self.shutdown()
                    return
                if cmd == "mut":
                    # reliable envelope: ("mut", client_id, seq, cmd, *args)
                    resp = self._handle_mut(msg[1], int(msg[2]), msg[3],
                                            msg[4:], ctx)
                else:
                    resp = self._dispatch(cmd, msg[1:], ctx)
                _send_msg(conn, resp)
        except (ConnectionError, OSError, EOFError, ValueError,
                struct.error):
            pass  # malformed frame or peer gone: drop the connection
        finally:
            conn.close()

    def _dispatch(self, cmd, args, ctx=None):
        try:
            if cmd in _UNTRACED_COMMANDS:
                return getattr(self, "_cmd_" + cmd)(*args)
            from . import telemetry as _telemetry
            from .telemetry import distributed as _distributed

            # the child span opens HERE, not in _serve: _handle_mut routes
            # only the owning frame of each (client, seq) through dispatch,
            # so a retried (deduped) mutation yields exactly one server span
            with _distributed.remote_context(ctx, lane="server"):
                with _telemetry.span("ps.server.handle", command=cmd):
                    return getattr(self, "_cmd_" + cmd)(*args)
        except Exception as e:  # ship the failure to the worker
            return ("err", f"{type(e).__name__}: {e}")

    def _handle_mut(self, client_id, seq, cmd, args, ctx=None):
        """Exactly-once apply for mutating RPCs: each (client_id, seq) is
        executed by the first frame that carries it; a retransmit (same
        client redialing after a mid-frame drop) waits for the original's
        result instead of re-executing — even when the original is still
        blocked in a sync/barrier rendezvous on the dead connection.
        The window is keyed by CLIENT, not connection, so it survives
        reconnects (ref: ps-lite Resender's seq-based dedup)."""
        with self._dedup_lock:
            window = self._dedup.setdefault(client_id,
                                            collections.OrderedDict())
            entry = window.get(seq)
            owner = entry is None
            if owner:
                entry = {"done": threading.Event(), "resp": None}
                window[seq] = entry
                while len(window) > self._dedup_window:
                    oldest = next(iter(window))
                    if not window[oldest]["done"].is_set():
                        break  # never evict an in-flight original
                    window.pop(oldest)
        if owner:
            resp = self._dispatch(cmd, args, ctx)
            entry["resp"] = resp
            entry["done"].set()
            return resp
        from . import telemetry as _telemetry

        _telemetry.inc(_DEDUP_METRIC, 1, help=_DEDUP_HELP, command=cmd)
        _telemetry.log_event("ps_dedup_hit", command=cmd, seq=seq,
                             client=client_id)
        logger.debug("ps: duplicate %s seq=%d from %s suppressed",
                     cmd, seq, client_id)
        # generous slack over the longest a legitimate original can run
        # (a full sync/barrier rendezvous wait)
        if not entry["done"].wait(timeout=self._sync_timeout + 60):
            return ("err", "TimeoutError: duplicate of an in-flight "
                           f"{cmd} seq={seq} never completed")
        return entry["resp"]

    def _quorum(self):
        """Workers a rendezvous must wait for: the configured world minus
        heartbeat-evicted ranks. Eviction needs the rank to have beaten at
        least once (a never-seen rank may still be starting up); a fresh
        beat re-admits. Only meaningful while heartbeats ride this server
        (tcp transport) — without beats the quorum is the full world."""
        now = time.time()
        newly = []
        readmitted = []
        with self._beats_lock:
            for rank, last in self._beats.items():
                if now - last > self._evict_timeout:
                    if rank not in self._evicted:
                        self._evicted.add(rank)
                        newly.append(rank)
                elif rank in self._evicted:
                    # the quorum grows back: a fresh beat re-admits
                    self._evicted.discard(rank)
                    readmitted.append(rank)
            quorum = max(1, self.num_workers - len(self._evicted))
        for rank in readmitted:
            self._note_readmission(rank, "heartbeat", quorum)
        if newly:
            from . import telemetry as _telemetry
            from .telemetry import recorder as _recorder

            for rank in newly:
                logger.warning(
                    "ps: worker %d heartbeat stale >%.1fs; evicting from "
                    "the rendezvous quorum (now %d/%d)", rank,
                    self._evict_timeout, quorum, self.num_workers)
                _telemetry.inc(_EVICT_METRIC, 1, help=_EVICT_HELP)
                _telemetry.log_event(
                    "ps_eviction", rank=rank, quorum=quorum,
                    world=self.num_workers,
                    stale_s=round(self._evict_timeout, 3))
            # a rank just fell out of the job: preserve the black box
            _recorder.dump("eviction")
        return quorum

    # --- elastic membership ----------------------------------------------
    def _note_readmission(self, rank, via, quorum=None):
        from . import telemetry as _telemetry

        if quorum is None:
            quorum = max(1, self.num_workers - len(self._evicted))
        logger.info("ps: rank %d re-admitted to the quorum via %s "
                    "(now %d/%d)", rank, via, quorum, self.num_workers)
        _telemetry.inc(_READMIT_METRIC, 1, help=_READMIT_HELP, via=via)
        _telemetry.log_event("ps_readmission", rank=int(rank), via=via,
                             quorum=quorum, world=self.num_workers,
                             epoch=self._epoch)

    def _publish_epoch(self, reason):
        from . import telemetry as _telemetry

        _telemetry.set_gauge(_EPOCH_METRIC, self._epoch, help=_EPOCH_HELP)
        _telemetry.log_event("ps_membership_epoch", epoch=self._epoch,
                             reason=reason, world=self.num_workers)

    def _check_epoch(self, epoch, command):
        """Fence a sync contribution against the membership epoch it was
        computed under. `None` (a client that never joined) is always
        accepted — the protocol is opt-in, so pre-elastic clients keep
        working. The check runs at ENTRY, before the contribution touches
        any merge buffer, so a rejection leaves the rendezvous untouched
        and the gradient math bit-exact."""
        if epoch is None or int(epoch) == self._epoch:
            return
        from . import telemetry as _telemetry

        _telemetry.inc(_STALE_METRIC, 1, help=_STALE_HELP, command=command)
        _telemetry.log_event("ps_stale_epoch", command=command,
                             got=int(epoch), want=self._epoch)
        raise StaleEpochError(
            f"{command} carried membership epoch {int(epoch)} but the "
            f"server is at {self._epoch}; the rank set changed — refresh "
            "via membership() and re-contribute")

    def _admit_pending(self):
        """Commit parked growth joins at a barrier boundary: the new ranks
        only count toward generations that START after this one, so no
        in-flight merge ever waits on a contribution that was not part of
        its world — which is what keeps elastic growth bit-exact."""
        with self._beats_lock:
            if not self._pending_ranks:
                return
            admitted = sorted(self._pending_ranks)
            self._pending_ranks.clear()
            self.num_workers = max(self.num_workers, admitted[-1] + 1)
            self._epoch += 1
        from . import telemetry as _telemetry

        for rank in admitted:
            _telemetry.log_event("ps_admission", rank=rank,
                                 world=self.num_workers, epoch=self._epoch)
            logger.info("ps: rank %d admitted at the epoch boundary "
                        "(world now %d, membership epoch %d)", rank,
                        self.num_workers, self._epoch)
        self._publish_epoch("admit")

    # --- commands ---------------------------------------------------------
    def _cmd_join(self, rank, client_id):
        """Versioned membership join (ref: ps-lite dynamic node groups —
        AddNode reassigned ids at the scheduler; here the server IS the
        scheduler). Confirms or assigns a rank and returns the current
        epoch + key directory. Three outcomes: an evicted rank re-admits
        immediately (the quorum grows back NOW — survivors are already
        rendezvousing without it); a brand-new rank parks in
        _pending_ranks until the next barrier boundary; a live rank's
        takeover by a new client_id fences the old incarnation. Every
        membership change bumps the epoch so stale contributions are
        rejected rather than merged."""
        from . import telemetry as _telemetry

        rank = int(rank)
        with self._beats_lock:
            world = self.num_workers
            cap = self._max_workers if self._max_workers > 0 else world
            if rank < 0:
                # no preference: reuse the lowest dead rank, else grow
                evicted = sorted(self._evicted)
                rank = evicted[0] if evicted else world
            readmitted = rank in self._evicted
            takeover = (not readmitted
                        and self._owners.get(rank, client_id) != client_id)
            pending = rank in self._pending_ranks
            if rank >= world and not pending:
                if rank >= cap:
                    raise JoinRejectedError(
                        f"rank {rank} exceeds the elastic world cap "
                        f"({world} configured, MXTPU_MAX_WORKERS={cap}); "
                        "retry after an eviction or raise the cap")
                self._pending_ranks.add(rank)
                pending = True
            self._owners[rank] = client_id
            self._evicted.discard(rank)
            self._departed.discard(rank)  # an explicit rejoin is real
            if rank in self._beats:
                # re-arm staleness from the join, not the pre-death beat
                self._beats[rank] = time.time()
            if readmitted or takeover:
                self._epoch += 1
            epoch = self._epoch
        # a grown-back quorum may complete a parked rendezvous
        with self._barrier_cv:
            self._barrier_cv.notify_all()
        with self._sync_cv:
            self._sync_cv.notify_all()
        outcome = ("readmitted" if readmitted
                   else "pending" if pending else "registered")
        _telemetry.inc(_JOIN_METRIC, 1, help=_JOIN_HELP, outcome=outcome)
        _telemetry.log_event("ps_join", rank=rank, outcome=outcome,
                             epoch=epoch, world=self.num_workers,
                             client=str(client_id))
        if readmitted:
            self._note_readmission(rank, "join")
        if readmitted or takeover:
            self._publish_epoch("join")
        logger.info("ps: rank %d joined (%s) at membership epoch %d",
                    rank, outcome, epoch)
        return ("val", {"epoch": epoch, "rank": rank, "pending": pending,
                        "readmitted": readmitted,
                        "num_workers": self.num_workers,
                        "keys": sorted(self._store, key=str)})

    def _cmd_leave(self, rank):
        """Graceful departure (the preemption drain's farewell): the rank
        is marked evicted NOW, so survivors' rendezvous quorum shrinks
        without waiting out a heartbeat timeout. The leaver's beat record
        is dropped too — unlike a staleness eviction, a stray late beat
        from the dying process must not re-admit it. Symmetric with
        eviction, a leave does NOT bump the membership epoch (the world
        only shrank; survivors' in-flight contributions stay valid), and
        a later join() of the same rank re-admits it through the normal
        versioned path."""
        from . import telemetry as _telemetry
        from .telemetry import recorder as _recorder

        rank = int(rank)
        with self._beats_lock:
            already = rank in self._evicted
            self._beats.pop(rank, None)
            self._owners.pop(rank, None)
            if rank < self.num_workers:
                self._evicted.add(rank)
                self._departed.add(rank)
            quorum = max(1, self.num_workers - len(self._evicted))
        # a shrunk quorum may complete a parked rendezvous
        with self._barrier_cv:
            self._barrier_cv.notify_all()
        with self._sync_cv:
            self._sync_cv.notify_all()
        if not already:
            logger.info("ps: rank %d left the quorum gracefully "
                        "(now %d/%d)", rank, quorum, self.num_workers)
            _telemetry.inc(_LEAVE_METRIC, 1, help=_LEAVE_HELP)
            _telemetry.log_event("ps_leave", rank=rank, quorum=quorum,
                                 world=self.num_workers, epoch=self._epoch)
            # a planned departure still closes a chapter: keep the black
            # box, same as an unplanned eviction does
            _recorder.dump("leave")
        return ("ok", quorum)

    def _cmd_membership(self):
        """Read-only membership snapshot — the recovery RPC after a
        StaleEpochError."""
        return ("val", {"epoch": self._epoch,
                        "num_workers": self.num_workers,
                        "quorum": self._quorum(),
                        "pending": sorted(self._pending_ranks)})

    def _cmd_state_manifest(self):
        """Key directory with per-tensor sha256 in the sharded_checkpoint
        manifest shape — the joiner's state-transfer contract: it pulls
        each key and verifies the bytes against this manifest, so a
        server applying concurrent updates surfaces as a clean mismatch
        (and a refetch) instead of silent skew."""
        from .contrib import sharded_checkpoint as _sc

        files = {}
        for key in sorted(self._store, key=str):
            with self._key_lock(key):
                arr = self._store[key]
                entry = _sc.manifest_entry(arr.tobytes())
                entry["dtype"] = arr.dtype.name
                entry["shape"] = list(int(d) for d in arr.shape)
                files[str(key)] = entry
        return ("val", {"version": 1, "epoch": self._epoch,
                        "files": files})

    def _cmd_init(self, key, value):
        """First writer wins (rank 0 inits; ref: kvstore_dist.h Init)."""
        with self._key_lock(key):
            if key not in self._store:
                self._store[key] = np.array(value, copy=True)
                self._versions[key] = 0
        return ("ok",)

    def _cmd_set_optimizer(self, blob):
        """(ref: CommandType::kController — the worker ships the pickled
        optimizer, the server builds its updater from it). The blob is
        unpickled ONLY after HMAC authentication against the job secret."""
        from . import optimizer as _opt

        self._updater = _opt.get_updater(pickle.loads(_verify_blob(blob)))
        return ("ok",)

    def _cmd_get_optimizer_states(self, dump_optimizer):
        if self._updater is None:
            raise RuntimeError("no optimizer set on the server")
        return ("val", _sign_blob(self._updater.get_states(dump_optimizer)))

    def _cmd_set_optimizer_states(self, blob):
        if self._updater is None:
            raise RuntimeError("no optimizer set on the server")
        self._updater.set_states(_verify_blob(blob))
        return ("ok",)

    def _cmd_set_optimizer_attrs(self, attrs):
        """Live optimizer mutation (lr schedules, rescale_grad) without
        rebuilding the updater — state survives."""
        if self._updater is None:
            raise RuntimeError("no optimizer set on the server")
        opt = self._updater.optimizer
        for name, value in attrs.items():
            if not hasattr(opt, name):
                raise AttributeError(f"optimizer has no attribute {name!r}")
            setattr(opt, name, value)
        return ("ok",)

    def _cmd_set_compression(self, params):
        from .kvstore import _make_compressor

        self._compressor = _make_compressor(dict(params))
        return ("ok",)

    def _apply(self, key, grad):
        from .ndarray.ndarray import NDArray

        stored = self._store[key]
        if self._updater is not None:
            w = NDArray(stored)
            # pass the key through untouched — string keys carry the
            # idx2name/lr_mult/wd_mult identity the optimizer looks up
            self._updater(key, NDArray(grad), w)
            self._store[key] = np.asarray(w.asnumpy())
        else:
            self._store[key] = stored + grad
        self._versions[key] += 1

    def _cmd_push(self, key, grad, sync, epoch=None):
        from . import telemetry as _telemetry

        grad = np.asarray(grad)
        if not sync:
            # async: apply instantly, nobody waits (ref: :348-358)
            with _telemetry.span("ps.server.merge", sync="0"):
                with self._key_lock(key):
                    self._apply(key, grad)
            return ("ok",)
        self._check_epoch(epoch, "push")
        # sync: aggregate one contribution per live worker, apply once,
        # release everyone at the new version (ref: :346 merge buffer
        # path). Waits run in short slices so a heartbeat eviction
        # mid-generation shrinks the quorum and releases the survivors
        # instead of hanging them until the rendezvous timeout.
        with _telemetry.span("ps.server.merge", sync="1"), self._sync_cv:
            buf, count = self._merge.get(key, (None, 0))
            buf = grad if buf is None else buf + grad
            count += 1
            self._merge[key] = (buf, count)
            target = self._versions[key] + 1
            deadline = time.monotonic() + self._sync_timeout
            while self._versions[key] < target:
                pend, npend = self._merge.get(key, (None, 0))
                if pend is not None and npend >= self._quorum():
                    with self._key_lock(key):
                        self._apply(key, pend)
                    self._merge[key] = (None, 0)
                    self._sync_cv.notify_all()
                    break
                if time.monotonic() > deadline:
                    # drop the stale buffer so a retry cannot double-count,
                    # and surface the failure
                    self._merge[key] = (None, 0)
                    raise TimeoutError(
                        f"sync push on {key!r} waited "
                        f"{self._sync_timeout:.0f}s with {npend}/"
                        f"{self._quorum()} contributions")
                self._sync_cv.wait(timeout=1.0)
        return ("ok",)

    def _cmd_push_many(self, keys, grads, sync, epoch=None):
        """One RPC, many keys — the inter-host half of the hierarchical
        allreduce (the worker already reduced intra-host over the GSPMD
        mesh, so exactly one contribution per key per host arrives here).
        Sync mode rendezvouses the whole bucket as ONE unit under a
        synthetic bucket key: a single merge wait per bucket instead of
        one per key, which is also the single choke point where
        membership changes take effect between generations. Per-key
        optimizer math is unchanged (each key still applies through
        _apply under its own lock), so results stay bit-identical to the
        flat per-key path."""
        from . import telemetry as _telemetry

        keys = tuple(keys)
        grads = [np.asarray(g) for g in grads]
        if len(keys) != len(grads):
            raise ValueError(f"push_many got {len(keys)} keys but "
                             f"{len(grads)} gradients")
        if not sync:
            with _telemetry.span("ps.server.merge", sync="0",
                                 bucket=str(len(keys))):
                for key, grad in zip(keys, grads):
                    with self._key_lock(key):
                        self._apply(key, grad)
            return ("ok",)
        self._check_epoch(epoch, "push_many")
        bkey = ("__bucket__",) + keys
        with _telemetry.span("ps.server.merge", sync="1",
                             bucket=str(len(keys))), self._sync_cv:
            buf, count = self._merge.get(bkey, (None, 0))
            buf = (list(grads) if buf is None
                   else [b + g for b, g in zip(buf, grads)])
            count += 1
            self._merge[bkey] = (buf, count)
            target = self._versions.setdefault(bkey, 0) + 1
            deadline = time.monotonic() + self._sync_timeout
            while self._versions[bkey] < target:
                pend, npend = self._merge.get(bkey, (None, 0))
                if pend is not None and npend >= self._quorum():
                    for key, grad in zip(keys, pend):
                        with self._key_lock(key):
                            self._apply(key, grad)
                    self._merge[bkey] = (None, 0)
                    self._versions[bkey] = target
                    self._sync_cv.notify_all()
                    break
                if time.monotonic() > deadline:
                    self._merge[bkey] = (None, 0)
                    raise TimeoutError(
                        f"sync push_many on {len(keys)} keys waited "
                        f"{self._sync_timeout:.0f}s with {npend}/"
                        f"{self._quorum()} contributions")
                self._sync_cv.wait(timeout=1.0)
        return ("ok",)

    def _cmd_pull_many(self, keys):
        out = []
        for key in keys:
            with self._key_lock(key):
                out.append(np.array(self._store[key], copy=True))
        return ("val", out)

    def _apply_rows(self, key, indices, rows):
        """Apply one key's row-sparse grad through the optimizer's
        sparse/lazy path — only the touched rows of the stored tensor
        move (ref: DataHandleRowSparse :499). Caller holds the key lock."""
        from .ndarray.ndarray import NDArray
        from .ndarray.sparse import RowSparseNDArray

        stored = self._store[key]
        if self._updater is not None:
            rsp = RowSparseNDArray(NDArray(rows), NDArray(indices),
                                   stored.shape)
            w = NDArray(stored)
            self._updater(key, rsp, w)
            self._store[key] = np.asarray(w.asnumpy())
        else:
            upd = stored.copy()
            np.add.at(upd, indices, rows)
            self._store[key] = upd
        self._versions[key] += 1

    def _cmd_push_rows(self, key, indices, rows, epoch=None):
        """Sparse push: apply only the occupied rows, through the
        optimizer's sparse/lazy path (ref: DataHandleRowSparse :499)."""
        self._check_epoch(epoch, "push_rows")
        indices = np.asarray(indices, np.int64)
        rows = np.asarray(rows)
        with self._key_lock(key):
            self._apply_rows(key, indices, rows)
        return ("ok",)

    # --- sharded embedding tables ------------------------------------------
    # One server of an embedding-shard fleet stores ONLY its local rows of
    # each table (global row r lives on server r % num_shards as local row
    # r // num_shards; the client owns the mapping). The commands below are
    # the shard-fleet data plane: a deterministic server-side init (so no
    # worker ever materializes even a shard), and multi-key row pull/push
    # so one RPC per SERVER carries every table's rows for a step —
    # mirroring push_many's one-RPC-per-bucket hierarchy. State transfer
    # (chaos replacement) rides the existing state_manifest/pull contract
    # unchanged, because a shard is just a dense tensor under its key.

    def _cmd_init_rows(self, key, num_rows, width, dtype, spec):
        """Declare this server's shard of an embedding table: materialize
        `num_rows` local rows SERVER-SIDE from a deterministic init spec
        (first writer wins, like init). spec is ("zeros",) or
        ("uniform", scale, seed, shard, num_shards): local row i is drawn
        from a counter-based stream keyed by (seed, global row id), so a
        row's initial value depends only on its global id — stable across
        fleet layouts and never shipped over the wire."""
        num_rows, width = int(num_rows), int(width)
        with self._key_lock(key):
            if key in self._store:
                return ("ok",)
            dt = _dtype_by_name(str(dtype))
            kind = spec[0]
            if kind == "zeros":
                block = np.zeros((num_rows, width), dt)
            elif kind == "uniform":
                scale, seed, shard, num_shards = (
                    float(spec[1]), int(spec[2]), int(spec[3]),
                    int(spec[4]))
                global_ids = shard + num_shards * np.arange(num_rows)
                seeds = np.empty((num_rows, 2), np.uint64)
                seeds[:, 0] = np.uint64(seed)
                seeds[:, 1] = global_ids.astype(np.uint64)
                block = np.empty((num_rows, width), dt)
                for i in range(num_rows):
                    rng = np.random.Philox(key=seeds[i])
                    block[i] = np.random.Generator(rng).uniform(
                        -scale, scale, width).astype(dt)
            else:
                raise ValueError(f"unknown embedding init spec {kind!r}")
            self._store[key] = block
            self._versions[key] = 0
        return ("ok",)

    def _cmd_pull_rows_multi(self, keys, ids_list):
        """Serve the requested rows of MANY keys in one response — the
        per-server half of the deduped/bucketed embedding pull (one RPC
        per server per step instead of one per key)."""
        out = []
        for key, ids in zip(keys, ids_list):
            ids = np.asarray(ids, np.int64)
            with self._key_lock(key):
                out.append(np.array(self._store[key][ids], copy=True))
        return ("val", out)

    def _cmd_push_rows_multi(self, keys, ids_list, rows_list, epoch=None):
        """Apply many keys' row-sparse grads in one mutating RPC, each
        through the lazy sparse optimizer path. Rides the dedup envelope
        (exactly-once across client retries) and the membership-epoch
        fence, like push_many."""
        self._check_epoch(epoch, "push_rows_multi")
        if not (len(keys) == len(ids_list) == len(rows_list)):
            raise ValueError(
                f"push_rows_multi got {len(keys)} keys, {len(ids_list)} "
                f"id vectors, {len(rows_list)} row blocks")
        for key, ids, rows in zip(keys, ids_list, rows_list):
            ids = np.asarray(ids, np.int64)
            rows = np.asarray(rows)
            with self._key_lock(key):
                self._apply_rows(key, ids, rows)
        return ("ok",)

    def _cmd_push_compressed(self, key, payload, shape):
        """Decode the worker's packed 2-bit payload server-side
        (ref: DataHandleCompressed kvstore_dist_server.h:394)."""
        if self._compressor is None:
            raise RuntimeError("server has no compressor configured")
        grad = np.asarray(self._compressor.decode(payload, tuple(shape)))
        with self._key_lock(key):
            self._apply(key, grad)
        return ("ok",)

    def _cmd_pull(self, key):
        with self._key_lock(key):
            return ("val", np.array(self._store[key], copy=True))

    def _cmd_pull_rows(self, key, row_ids):
        """Serve only the requested rows (ref: DataHandleRowSparse :499)."""
        rows = np.asarray(row_ids, dtype=np.int64)
        with self._key_lock(key):
            return ("val", np.array(self._store[key][rows], copy=True))

    def _cmd_barrier(self, epoch=None):
        from . import telemetry as _telemetry

        self._check_epoch(epoch, "barrier")
        # generation-counted rendezvous (ref: ps-lite Postoffice::Barrier).
        # Short wait slices re-evaluate the quorum so heartbeat evictions
        # release the survivors; whichever waiter first observes
        # count >= quorum opens the generation. A retransmitted barrier
        # never double-counts: it rides the dedup window in _handle_mut.
        # Barriers are the epoch boundaries of elastic membership: parked
        # growth joins commit when a generation opens, and every waiter
        # returns the (possibly new) epoch so joined clients stay current.
        with _telemetry.span("ps.server.barrier"), self._barrier_cv:
            gen = self._barrier_gen
            self._barrier_count += 1
            deadline = time.monotonic() + self._sync_timeout
            while self._barrier_gen == gen:
                if self._barrier_count >= self._quorum():
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    self._admit_pending()
                    self._barrier_cv.notify_all()
                    break
                if time.monotonic() > deadline:
                    self._barrier_count -= 1
                    raise TimeoutError(
                        f"barrier waited {self._sync_timeout:.0f}s with "
                        f"only {self._barrier_count + 1}/{self._quorum()} "
                        "workers present")
                self._barrier_cv.wait(timeout=1.0)
        return ("ok", self._epoch)

    def _cmd_heartbeat(self, rank):
        rank = int(rank)
        with self._beats_lock:
            if rank in self._departed:
                # a straggler beat from a rank that already said goodbye:
                # it is draining, not back — only join() readmits it
                return ("ok",)
            self._beats[rank] = time.time()
            readmitted = rank in self._evicted
            self._evicted.discard(rank)  # a live beat re-admits
        if readmitted:
            self._note_readmission(rank, "heartbeat")
        with self._barrier_cv:
            self._barrier_cv.notify_all()  # quorum may have changed
        with self._sync_cv:
            self._sync_cv.notify_all()
        return ("ok",)

    def _cmd_num_dead(self, requester, timeout, grace_elapsed):
        """Ranks whose heartbeat is stale (or never arrived), excluding the
        requester — the KVStore::get_num_dead_node analog served over TCP.
        `grace_elapsed` tells whether the REQUESTER's own startup grace has
        passed (mirrors the file transport, where never-seen peers count
        as dead only relative to the observer's start, so late-joining
        workers are not reported dead by early starters)."""
        now = time.time()
        dead = 0
        with self._beats_lock:
            for r in range(self.num_workers):
                if r == int(requester):
                    continue
                last = self._beats.get(r)
                if last is None:
                    if grace_elapsed and now - self._start_time > timeout:
                        dead += 1
                elif now - last > timeout:
                    dead += 1
        return ("val", dead)

    def _cmd_keys(self):
        return ("val", sorted(self._store, key=str))

    def serve_forever(self):
        """Block this thread until a worker sends the stop command or
        shutdown() is called — the dedicated-server-process entry
        (kvstore_server.KVStoreServer.run)."""
        self._stop.wait()
        self._accept_thread.join(timeout=10)

    def shutdown(self):
        self._stop.set()
        # shutdown() (not just close()) wakes a thread blocked in accept();
        # close() alone leaves it blocked on a stale fd which the NEXT
        # server's listener can reuse — the old loop would then steal the
        # new server's connections
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=10)


# per-process client-id disambiguator: the server's dedup window is keyed
# by (client_id, seq), so the id must be unique per CLIENT OBJECT and
# stable across that object's reconnects
_CLIENT_IDS = itertools.count()


class PSClient:
    """Worker-side connection (ref: kvstore_dist.h push/pull over ps-lite).

    Thread-safe: one socket, request/response framing under a lock.

    Resilient: a mid-frame socket error (or injected drop) closes the
    socket and transparently redials + resends under a RetryPolicy —
    never reuses a socket whose framing may be poisoned. Every mutating
    RPC carries this client's monotonic sequence id in a ("mut", ...)
    envelope so the server applies a retransmit exactly once; reads
    (pull/keys/heartbeat/...) are idempotent and resend bare.
    """

    def __init__(self, host, port, retries=60, instance=None):
        from . import config as _config
        from .resilience import RetryPolicy

        self._host, self._port = host, int(port)
        self._lock = san_lock("ps.client")
        self._sock = None
        self._seq = 0
        self._client_id = (f"{socket.gethostname()}:{os.getpid()}:"
                           f"{next(_CLIENT_IDS)}")
        # stable tag for the fault injector's per-client streams: the
        # worker rank by default, so a seeded chaos schedule replays
        # per-worker regardless of thread interleaving
        self._instance = (instance if instance is not None
                          else f"w{_config.get('MXTPU_PROCESS_ID')}")
        self._connect_timeout = _config.get("MXTPU_PS_CONNECT_TIMEOUT")
        # the socket timeout outlives the server's rendezvous waits, which
        # raise a proper error instead of this socket timing out first
        self._socket_timeout = _config.get("MXTPU_PS_SOCKET_TIMEOUT")
        # distinct backoff jitter per client: every worker redialing after
        # the same network blip must NOT share one seed, or the whole
        # fleet sleeps and retries in lockstep and the mass rejoin
        # thundering-herds the server. The heartbeat sender's reconnect
        # rides these policies too, so beats desynchronize the same way.
        self._policy_seed = zlib.crc32(
            f"{self._instance}:{self._client_id}".encode("utf-8"))
        # first connect keeps the caller-visible `retries` contract (the
        # server may simply not be up yet) on the knob-driven schedule
        self._connect_policy = RetryPolicy.from_knobs(
            max_attempts=max(1, int(retries)), seed=self._policy_seed)
        self._rpc_policy = RetryPolicy.from_knobs(seed=self._policy_seed)
        # membership epoch last observed (None until join/membership —
        # epoch-less clients are always accepted, see _check_epoch)
        self._epoch = None
        self._rank = None
        with self._lock:
            self._reconnect_locked(first=True)

    # --- connection management -------------------------------------------
    def _close_locked(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _dial_once(self, _attempt):
        from .resilience import fault as _fault

        _fault.injector().raise_for("ps.connect", self._instance)
        sock = socket.create_connection(
            (self._host, self._port), timeout=self._connect_timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(self._socket_timeout)
        return sock

    def _reconnect_locked(self, first=False, cause="redial"):
        self._close_locked()

        def _log(attempt, exc, remaining):
            logger.debug(
                "PSClient redial %s:%d attempt %d failed (%s: %s); "
                "%.1fs of deadline remaining", self._host, self._port,
                attempt + 1, type(exc).__name__, exc, remaining)

        try:
            self._sock = self._connect_policy.call(
                self._dial_once, OSError, site="ps.connect", on_retry=_log)
        except OSError as e:
            raise ConnectionError(
                f"parameter server at {self._host}:{self._port} "
                f"unreachable: {e}") from e
        if not first:
            from . import telemetry as _telemetry

            _telemetry.inc(_RECONNECT_METRIC, 1, help=_RECONNECT_HELP,
                           cause=cause)
            _telemetry.log_event("ps_reconnect", cause=cause,
                                 addr=f"{self._host}:{self._port}")
            logger.debug("PSClient reconnected to %s:%d (%s)",
                         self._host, self._port, cause)

    # --- framing ----------------------------------------------------------
    def _rpc_attempt(self, frame):
        from .resilience import fault as _fault
        from .telemetry.spans import current_span

        inj = _fault.injector()
        # attach the current trace context so the server's child span joins
        # this trace; the ("trc", ...) wrapper only exists when a span is
        # live, so the untraced wire format is byte-identical to before
        sp = current_span()
        traced = sp is not None and sp.span_id is not None
        if traced:
            frame = ("trc", {"tid": sp.trace_id, "sid": sp.span_id}, frame)
        with self._lock:
            if self._sock is None:
                self._reconnect_locked(cause="redial")
            try:
                inj.raise_for("ps.rpc", self._instance)
                if traced:
                    # send/recv wall clocks of the SUCCESSFUL attempt
                    # (annotate overwrites across retries) — paired with
                    # the server span's start/end by trace_merge for
                    # NTP-style clock-skew correction
                    sp.annotate(send_ns=time.time_ns())
                _send_msg(self._sock, frame)
                # separate post-send site: a drop HERE leaves the request
                # applied server-side, which is exactly what the dedup
                # window must absorb on the retransmit
                inj.raise_for("ps.rpc.recv", self._instance)
                resp = _recv_msg(self._sock)
                if traced:
                    sp.annotate(recv_ns=time.time_ns())
                return resp
            except _WIRE_ERRORS as e:
                self._close_locked()  # poisoned mid-frame: next try redials
                self._last_cause = type(e).__name__
                if sp is not None:
                    sp.bump("retries")
                raise

    def _call(self, frame, site):
        from . import telemetry as _telemetry

        command = site.rpartition(".")[2]
        if command in _UNTRACED_COMMANDS:
            return self._call_inner(frame, site)
        with _telemetry.span("ps.client.rpc", command=command):
            return self._call_inner(frame, site)

    def _call_inner(self, frame, site):
        resp = self._rpc_policy.call(
            lambda _a: self._rpc_attempt(frame), _WIRE_ERRORS, site=site)
        if resp[0] == "err":
            name = str(resp[1]).split(":", 1)[0]
            cls = _ERR_CLASSES.get(name, RuntimeError)
            raise cls(f"parameter server: {resp[1]}")
        return resp[1] if len(resp) > 1 else None

    def _rpc(self, *msg):
        """Idempotent RPC: resent bare across reconnects."""
        return self._call(tuple(msg), site="ps." + msg[0])

    def _mut_rpc(self, cmd, *args):
        """Mutating RPC: one sequence id for ALL resends of this call, so
        the server's dedup window applies it exactly once."""
        with self._lock:
            self._seq += 1
            frame = ("mut", self._client_id, self._seq, cmd) + args
        return self._call(frame, site="ps." + cmd)

    # --- API --------------------------------------------------------------
    def init(self, key, value):
        return self._mut_rpc("init", key, np.asarray(value))

    def push(self, key, grad, sync=False):
        return self._mut_rpc("push", key, np.asarray(grad), bool(sync),
                             self._epoch)

    def push_many(self, keys, grads, sync=False):
        """One mutating RPC carrying a whole bucket of gradients — the
        client half of the hierarchical allreduce."""
        return self._mut_rpc("push_many", tuple(keys),
                             tuple(np.asarray(g) for g in grads),
                             bool(sync), self._epoch)

    def pull_many(self, keys):
        return list(self._rpc("pull_many", tuple(keys)))

    def push_compressed(self, key, payload, shape):
        return self._mut_rpc("push_compressed", key, np.asarray(payload),
                             tuple(shape))

    def push_rows(self, key, indices, rows):
        return self._mut_rpc("push_rows", key, np.asarray(indices),
                             np.asarray(rows), self._epoch)

    # --- sharded embedding tables ------------------------------------------
    def init_rows(self, key, num_rows, width, dtype, spec):
        """Create this server's shard of an embedding table from a
        deterministic init spec (server-side materialization)."""
        return self._mut_rpc("init_rows", key, int(num_rows), int(width),
                             str(dtype), tuple(spec))

    def pull_rows_multi(self, keys, ids_list):
        """One RPC, many keys: fetch each key's requested rows."""
        return list(self._rpc("pull_rows_multi", tuple(keys),
                              [np.asarray(i, np.int64) for i in ids_list]))

    def push_rows_multi(self, keys, ids_list, rows_list):
        """One mutating RPC applying many keys' row-sparse grads through
        the server's lazy sparse optimizer path (epoch-fenced, deduped)."""
        return self._mut_rpc("push_rows_multi", tuple(keys),
                             [np.asarray(i, np.int64) for i in ids_list],
                             [np.asarray(r) for r in rows_list],
                             self._epoch)

    def set_optimizer_attrs(self, attrs):
        return self._mut_rpc("set_optimizer_attrs", dict(attrs))

    def set_compression(self, params):
        return self._mut_rpc("set_compression", dict(params))

    def get_optimizer_states(self, dump_optimizer=False):
        return _verify_blob(
            self._rpc("get_optimizer_states", bool(dump_optimizer)))

    def set_optimizer_states(self, blob):
        return self._mut_rpc("set_optimizer_states", _sign_blob(blob))

    def pull(self, key):
        return self._rpc("pull", key)

    def pull_rows(self, key, row_ids):
        return self._rpc("pull_rows", key, np.asarray(row_ids))

    def set_optimizer(self, optimizer):
        return self._mut_rpc("set_optimizer",
                             _sign_blob(pickle.dumps(
                                 optimizer,
                                 protocol=pickle.HIGHEST_PROTOCOL)))

    def barrier(self):
        epoch = self._mut_rpc("barrier", self._epoch)
        if self._epoch is not None and epoch is not None:
            # boundaries publish the (possibly bumped) membership epoch
            self._epoch = int(epoch)
        return epoch

    def heartbeat(self, rank):
        return self._rpc("heartbeat", int(rank))

    # --- elastic membership ----------------------------------------------
    @property
    def epoch(self):
        """Membership epoch last observed (None before join)."""
        return self._epoch

    @property
    def rank(self):
        """Rank the server assigned at join (None before join)."""
        return self._rank

    def join(self, rank=-1, wait=True, policy=None):
        """Join (or rejoin) the membership: returns the server's verdict
        {epoch, rank, pending, readmitted, num_workers, keys}. rank=-1
        lets the server pick (lowest evicted rank, else world growth). A
        world-full rejection backs off and retries under `policy` — the
        rejoin backoff — and with wait=True a growth join also polls
        until the next barrier boundary commits the admission."""
        from .resilience import RetryPolicy

        if policy is None:
            policy = RetryPolicy.from_knobs(seed=self._policy_seed)
        rank = -1 if rank is None else int(rank)
        info = policy.call(
            lambda _a: self._mut_rpc("join", rank, self._client_id),
            JoinRejectedError, site="ps.join")
        self._epoch = int(info["epoch"])
        self._rank = int(info["rank"])
        if wait and info["pending"]:
            self.wait_admitted(policy=policy)
        return info

    def membership(self):
        """Refresh {epoch, num_workers, quorum, pending} from the server
        — the recovery step after a StaleEpochError."""
        info = self._rpc("membership")
        self._epoch = int(info["epoch"])
        return info

    def leave(self, rank=None):
        """Graceful departure (preemption drain): tell the server this
        rank is gone so the survivors' quorum shrinks NOW instead of
        after a heartbeat timeout. Defaults to the rank join() assigned.
        Returns the post-leave quorum; rejoin later via join()."""
        r = self._rank if rank is None else int(rank)
        if r is None:
            raise RuntimeError("leave() before join(): no rank to retire "
                               "(pass rank= explicitly)")
        return self._mut_rpc("leave", int(r))

    def wait_admitted(self, policy=None):
        """Backoff-poll until this rank is inside the world (its parked
        growth join was committed by a barrier boundary)."""
        from .resilience import RetryPolicy

        if self._rank is None:
            raise RuntimeError("wait_admitted before join()")
        if policy is None:
            policy = RetryPolicy.from_knobs(seed=self._policy_seed)
        info = self.membership()
        if self._rank < int(info["num_workers"]):
            return info
        for delay in policy.delays():
            time.sleep(delay)
            info = self.membership()
            if self._rank < int(info["num_workers"]):
                return info
        raise TimeoutError(
            f"rank {self._rank} was never admitted (world stuck at "
            f"{info['num_workers']}); admissions commit at a barrier "
            "boundary — is any live worker reaching one?")

    def state_manifest(self):
        return self._rpc("state_manifest")

    def bootstrap(self, keys=None):
        """State transfer on admit: pull every key in the server's
        directory and verify the bytes against its sharded_checkpoint-
        format manifest. A mismatch (the server applied a push between
        manifest and pull) refetches the manifest once; returns
        {key: np.ndarray}."""
        from . import telemetry as _telemetry
        from .contrib import sharded_checkpoint as _sc

        if keys is None:
            keys = self.keys()
        manifest = self.state_manifest()
        out = {}
        for key in keys:
            for _attempt in range(2):
                entry = manifest["files"].get(str(key))
                val = np.asarray(self.pull(key))
                if entry is None or _sc.verify_wire_entry(
                        entry, val.tobytes()):
                    break
                manifest = self.state_manifest()
            else:
                raise RuntimeError(
                    f"bootstrap of key {key!r} never matched the server's "
                    "state manifest — the server is applying concurrent "
                    "updates; join at an epoch boundary instead")
            out[key] = val
        _telemetry.log_event("ps_bootstrap", keys=len(out),
                             epoch=self._epoch)
        return out

    def num_dead(self, rank, timeout, grace_elapsed=True):
        return self._rpc("num_dead", int(rank), float(timeout),
                         bool(grace_elapsed))

    def keys(self):
        return self._rpc("keys")

    def stop_server(self):
        # deliberately NOT retried: at teardown a dead server is success,
        # and a retry loop here would stall interpreter exit
        try:
            with self._lock:
                if self._sock is None:
                    self._sock = self._dial_once(0)
                _send_msg(self._sock, ("stop",))
                _recv_msg(self._sock)
        except (RuntimeError, ConnectionError, EOFError, OSError,
                struct.error):
            pass

    def close(self):
        with self._lock:
            self._close_locked()
