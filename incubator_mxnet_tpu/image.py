"""Image IO + augmentation (ref: python/mxnet/image/image.py and the C++
augmenter chain src/io/image_aug_default.cc:46-283).

Host-side decode/augment with OpenCV (like the reference's opencv path);
tensors convert to NDArray at batch boundaries for async H2D transfer.
"""
from __future__ import annotations

import os
import random as pyrandom

import numpy as np

from .ndarray.ndarray import NDArray
from .ndarray import array as nd_array
from .io import DataIter, DataBatch, DataDesc
from . import recordio

__all__ = [
    "imdecode", "imread", "imresize", "scale_down", "resize_short", "fixed_crop",
    "random_crop", "center_crop", "color_normalize", "random_size_crop",
    "Augmenter", "SequentialAug", "RandomOrderAug", "ResizeAug", "ForceResizeAug",
    "RandomCropAug", "RandomSizedCropAug", "CenterCropAug", "BrightnessJitterAug",
    "ContrastJitterAug", "SaturationJitterAug", "HueJitterAug", "ColorJitterAug",
    "LightingAug", "ColorNormalizeAug", "RandomGrayAug", "HorizontalFlipAug",
    "CastAug", "CreateAugmenter", "ImageIter",
]


def imdecode(buf, flag=1, to_rgb=True, **kwargs):
    import cv2

    img = cv2.imdecode(np.frombuffer(buf, dtype=np.uint8), flag)
    if img is None:
        raise ValueError("cannot decode image")
    if to_rgb and img.ndim == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return nd_array(img)


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=1):
    import cv2

    a = src.asnumpy() if isinstance(src, NDArray) else src
    return nd_array(cv2.resize(a, (w, h), interpolation=interp))


def _as_np(src):
    return src.asnumpy() if isinstance(src, NDArray) else src


def scale_down(src_size, size):
    """Shrink a requested crop (w, h) until it fits inside src_size,
    preserving its aspect ratio: both edges scale by the one factor
    min(1, sw/w, sh/h) (behavioral ref: image.py scale_down)."""
    sw, sh = src_size
    w, h = size
    shrink = min(1.0, sw / float(w), sh / float(h))
    return int(w * shrink), int(h * shrink)


def resize_short(src, size, interp=2):
    """Resize so the SHORTER edge becomes `size`; the longer edge keeps
    the aspect ratio (floor division, as users of the reference expect)."""
    import cv2

    a = _as_np(src)
    h, w = a.shape[:2]
    long_edge = size * max(h, w) // min(h, w)
    new_w, new_h = (size, long_edge) if w <= h else (long_edge, size)
    return nd_array(cv2.resize(a, (new_w, new_h), interpolation=interp))


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """Take the w x h window at (x0, y0); resize to `size` if asked."""
    window = _as_np(src)[y0:y0 + h, x0:x0 + w]
    if size is None or tuple(size) == (w, h):
        return nd_array(window)
    import cv2

    return nd_array(cv2.resize(window, size, interpolation=interp))


def _place_crop(a, size, interp, x0, y0, cw, ch):
    return fixed_crop(a, x0, y0, cw, ch, size, interp), (x0, y0, cw, ch)


def random_crop(src, size, interp=2):
    a = _as_np(src)
    h, w = a.shape[:2]
    cw, ch = scale_down((w, h), size)
    return _place_crop(a, size, interp,
                       pyrandom.randint(0, w - cw),
                       pyrandom.randint(0, h - ch), cw, ch)


def center_crop(src, size, interp=2):
    a = _as_np(src)
    h, w = a.shape[:2]
    cw, ch = scale_down((w, h), size)
    return _place_crop(a, size, interp, (w - cw) // 2, (h - ch) // 2, cw, ch)


def random_size_crop(src, size, area, ratio, interp=2, **kwargs):
    """Inception-style crop: draw an area fraction and a log-uniform aspect
    ratio, retry up to 10 times for a window that fits, else center-crop."""
    a = _as_np(src)
    h, w = a.shape[:2]
    lo, hi = (area, 1.0) if isinstance(area, (int, float)) else area
    for _ in range(10):
        pixels = pyrandom.uniform(lo, hi) * (h * w)
        aspect = np.exp(pyrandom.uniform(np.log(ratio[0]), np.log(ratio[1])))
        cw = int(round(np.sqrt(pixels * aspect)))
        ch = int(round(np.sqrt(pixels / aspect)))
        if cw > w or ch > h:
            continue
        return _place_crop(a, size, interp,
                           pyrandom.randint(0, w - cw),
                           pyrandom.randint(0, h - ch), cw, ch)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    a = src.asnumpy().astype(np.float32) if isinstance(src, NDArray) else np.asarray(src, np.float32)
    mean = mean.asnumpy() if isinstance(mean, NDArray) else np.asarray(mean)
    a = a - mean
    if std is not None:
        std = std.asnumpy() if isinstance(std, NDArray) else np.asarray(std)
        a = a / std
    return nd_array(a)


class Augmenter:
    """(ref: image.py Augmenter base)"""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for aug in self.ts:
            src = aug(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2, **kwargs):
        super().__init__(size=size)
        self.size, self.area, self.ratio, self.interp = size, area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return nd_array(src.asnumpy() * alpha)


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast
        self.coef = np.array([[[0.299, 0.587, 0.114]]])

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        a = src.asnumpy().astype(np.float32)
        gray = (a * self.coef).sum() * (3.0 / a.size)
        return nd_array(a * alpha + gray * (1 - alpha))


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation
        self.coef = np.array([[[0.299, 0.587, 0.114]]])

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        a = src.asnumpy().astype(np.float32)
        gray = (a * self.coef).sum(axis=2, keepdims=True)
        return nd_array(a * alpha + gray * (1 - alpha))


class HueJitterAug(Augmenter):
    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = np.array([[0.299, 0.587, 0.114], [0.596, -0.274, -0.321],
                              [0.211, -0.523, 0.311]])
        self.ityiq = np.array([[1.0, 0.956, 0.621], [1.0, -0.272, -0.647],
                               [1.0, -1.107, 1.705]])

    def __call__(self, src):
        alpha = pyrandom.uniform(-self.hue, self.hue)
        u, w = np.cos(alpha * np.pi), np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]])
        t = np.dot(np.dot(self.ityiq, bt), self.tyiq).T
        return nd_array(np.dot(src.asnumpy().astype(np.float32), t))


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA lighting noise (ref: image_aug_default.cc pca noise)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval)
        self.eigvec = np.asarray(eigvec)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = np.dot(self.eigvec * alpha, self.eigval)
        return nd_array(src.asnumpy().astype(np.float32) + rgb)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = np.asarray(mean) if mean is not None else None
        self.std = np.asarray(std) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p
        self.mat = np.array([[0.21, 0.21, 0.21], [0.72, 0.72, 0.72], [0.07, 0.07, 0.07]])

    def __call__(self, src):
        if pyrandom.random() < self.p:
            src = nd_array(np.dot(src.asnumpy().astype(np.float32), self.mat))
        return src


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            src = nd_array(src.asnumpy()[:, ::-1].copy())
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return nd_array(src.asnumpy().astype(self.typ))


# ImageNet channel statistics and PCA lighting basis (data constants shared
# with the reference's defaults)
_IMAGENET_MEAN = np.array([123.68, 116.28, 103.53])
_IMAGENET_STD = np.array([58.395, 57.12, 57.375])
_IMAGENET_PCA_EIGVAL = np.array([55.46, 4.794, 1.148])
_IMAGENET_PCA_EIGVEC = np.array([[-0.5675, 0.7192, 0.4009],
                                 [-0.5808, -0.0045, -0.814],
                                 [-0.5836, -0.6948, 0.4203]])


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0, rand_gray=0,
                    inter_method=2):
    """(ref: image.py CreateAugmenter mirroring image_aug_default.cc
    defaults). Pipeline order: resize -> crop -> flip -> cast -> color
    jitter -> hue -> PCA lighting -> grayscale -> normalize."""
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        crop = RandomSizedCropAug(crop_size, (0.08, 1.0),
                                  (3.0 / 4.0, 4.0 / 3.0), inter_method)
    else:
        crop_cls = RandomCropAug if rand_crop else CenterCropAug
        crop = crop_cls(crop_size, inter_method)
    mean = _IMAGENET_MEAN.copy() if mean is True else mean
    std = _IMAGENET_STD.copy() if std is True else std
    # (enabled?, augmenter) stages in pipeline order
    stages = [
        (resize > 0, lambda: ResizeAug(resize, inter_method)),
        (True, lambda: crop),
        (rand_mirror, lambda: HorizontalFlipAug(0.5)),
        (True, CastAug),
        (brightness or contrast or saturation,
         lambda: ColorJitterAug(brightness, contrast, saturation)),
        (hue, lambda: HueJitterAug(hue)),
        (pca_noise > 0,
         lambda: LightingAug(pca_noise, _IMAGENET_PCA_EIGVAL.copy(),
                             _IMAGENET_PCA_EIGVEC.copy())),
        (rand_gray > 0, lambda: RandomGrayAug(rand_gray)),
        (mean is not None or std is not None,
         lambda: ColorNormalizeAug(mean, std)),
    ]
    return [make() for on, make in stages if on]


class ImageIter(DataIter):
    """Python image iterator over .rec shards or image lists
    (ref: python/mxnet/image/image.py ImageIter; C++ twin:
    src/io/iter_image_recordio_2.cc). Supports dist sharding via
    part_index/num_parts like the reference."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="", path_imgidx=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="softmax_label",
                 dtype="float32", last_batch_handle="pad", **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype

        self.imgrec = None
        self.imglist = None
        self.seq = None
        if path_imgrec:
            idx_path = path_imgidx or (os.path.splitext(path_imgrec)[0] + ".idx")
            if os.path.isfile(idx_path):
                self.imgrec = recordio.MXIndexedRecordIO(idx_path, path_imgrec, "r")
                self.seq = list(self.imgrec.keys)
            else:
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
        elif path_imglist or imglist is not None:
            if path_imglist:
                imglist = []
                with open(path_imglist) as fin:
                    for line in fin:
                        parts = line.strip().split("\t")
                        imglist.append((np.array([float(x) for x in parts[1:-1]]), parts[-1]))
            self.imglist = [
                (np.asarray(lbl, dtype=np.float32), os.path.join(path_root, fname))
                for lbl, fname in imglist
            ]
            self.seq = list(range(len(self.imglist)))
        else:
            raise ValueError("need path_imgrec, path_imglist, or imglist")

        if self.seq is not None and num_parts > 1:
            # distributed sharding (ref: iter_image_recordio_2.cc part_index)
            n = len(self.seq) // num_parts
            self.seq = self.seq[part_index * n : (part_index + 1) * n]

        self.auglist = aug_list if aug_list is not None else CreateAugmenter(data_shape, **{
            k: v for k, v in kwargs.items()
            if k in ("resize", "rand_crop", "rand_resize", "rand_mirror", "mean",
                     "std", "brightness", "contrast", "saturation", "hue",
                     "pca_noise", "rand_gray", "inter_method")
        })
        self.cur = 0
        self._cache = None
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name, (self.batch_size,) + self.data_shape, np.float32)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shape, np.float32)]

    def reset(self):
        if self.shuffle and self.seq is not None:
            pyrandom.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()
        self.cur = 0

    def _record_at(self, idx):
        """(label, encoded bytes) for one source position."""
        if self.imgrec is not None:
            rec = recordio.unpack(self.imgrec.read_idx(idx))
            return rec[0].label, rec[1]
        label, fname = self.imglist[idx]
        with open(fname, "rb") as f:
            return label, f.read()

    def next_sample(self):
        if self.seq is None:
            # non-indexed .rec: pure sequential read
            s = self.imgrec.read()
            if s is None:
                raise StopIteration
            header, img = recordio.unpack(s)
            return header.label, img
        if self.cur >= len(self.seq):
            raise StopIteration
        self.cur += 1
        return self._record_at(self.seq[self.cur - 1])

    def next(self):
        batch_data = np.zeros((self.batch_size,) + self.data_shape, dtype=np.float32)
        shape = (self.batch_size,) if self.label_width == 1 else (self.batch_size, self.label_width)
        batch_label = np.zeros(shape, dtype=np.float32)
        i = 0
        pad = 0
        try:
            while i < self.batch_size:
                label, s = self.next_sample()
                img = imdecode(s).asnumpy()
                for aug in self.auglist:
                    img = aug(nd_array(img) if not isinstance(img, NDArray) else img)
                    img = img.asnumpy() if isinstance(img, NDArray) else img
                batch_data[i] = np.transpose(img, (2, 0, 1))
                batch_label[i] = label if np.isscalar(label) or self.label_width == 1 else label[: self.label_width]
                i += 1
        except StopIteration:
            if i == 0:
                raise
            pad = self.batch_size - i
        return DataBatch(
            data=[nd_array(batch_data)], label=[nd_array(batch_label)], pad=pad,
        )


# ---------------------------------------------------------------------------
# Detection augmenters + ImageDetIter (ref: python/mxnet/image/detection.py;
# C++ twin src/io/iter_image_det_recordio.cc). Labels are (N, 5+) arrays of
# [cls, x1, y1, x2, y2] with normalized corner coords; invalid rows cls=-1.
# ---------------------------------------------------------------------------


class DetAugmenter:
    """Augmenter that transforms (image, label) jointly."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap an image-only Augmenter for detection pipelines."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.__class__.__name__)
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Apply one randomly selected augmenter (or none, with skip_prob)
    (ref: detection.py DetRandomSelectAug)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if not self.aug_list or pyrandom.random() < self.skip_prob:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            a = src.asnumpy() if isinstance(src, NDArray) else src
            src = nd_array(np.ascontiguousarray(a[:, ::-1]))
            label = label.copy()
            valid = label[:, 0] >= 0
            x1 = label[valid, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x1
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping enough object coverage
    (ref: detection.py DetRandomCropAug)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def _coverage(self, box, crop):
        iw = max(0.0, min(box[2], crop[2]) - max(box[0], crop[0]))
        ih = max(0.0, min(box[3], crop[3]) - max(box[1], crop[1]))
        area = (box[2] - box[0]) * (box[3] - box[1])
        return iw * ih / area if area > 0 else 0.0

    def __call__(self, src, label):
        a = src.asnumpy() if isinstance(src, NDArray) else src
        h, w = a.shape[:2]
        valid = label[:, 0] >= 0
        boxes = label[valid, 1:5]
        for _ in range(self.max_attempts):
            ar = pyrandom.uniform(*self.aspect_ratio_range)
            area = pyrandom.uniform(*self.area_range)
            cw = min(1.0, np.sqrt(area * ar))
            ch = min(1.0, np.sqrt(area / ar))
            cx = pyrandom.uniform(0, 1 - cw)
            cy = pyrandom.uniform(0, 1 - ch)
            crop = (cx, cy, cx + cw, cy + ch)
            if len(boxes) and max(
                    (self._coverage(b, crop) for b in boxes), default=0.0
            ) < self.min_object_covered:
                continue
            x0, y0 = int(cx * w), int(cy * h)
            x1, y1 = max(x0 + 1, int((cx + cw) * w)), max(y0 + 1, int((cy + ch) * h))
            out = np.ascontiguousarray(a[y0:y1, x0:x1])
            new_label = label.copy()
            for i in np.where(valid)[0]:
                cov = self._coverage(label[i, 1:5], crop)
                if cov < self.min_eject_coverage:
                    new_label[i, 0] = -1.0  # ejected
                    continue
                bx = label[i, 1:5]
                nb = [
                    (max(bx[0], crop[0]) - crop[0]) / cw,
                    (max(bx[1], crop[1]) - crop[1]) / ch,
                    (min(bx[2], crop[2]) - crop[0]) / cw,
                    (min(bx[3], crop[3]) - crop[1]) / ch,
                ]
                new_label[i, 1:5] = nb
            return nd_array(out), new_label
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Random expand/pad (ref: detection.py DetRandomPadAug)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33), area_range=(1.0, 3.0),
                 max_attempts=50, pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        a = src.asnumpy() if isinstance(src, NDArray) else src
        h, w = a.shape[:2]
        scale = pyrandom.uniform(*self.area_range)
        if scale <= 1.0:
            return src, label
        ar = pyrandom.uniform(*self.aspect_ratio_range)
        nw = int(w * np.sqrt(scale * ar))
        nh = int(h * np.sqrt(scale / ar))
        nw, nh = max(nw, w), max(nh, h)
        ox = pyrandom.randint(0, nw - w)
        oy = pyrandom.randint(0, nh - h)
        canvas = np.empty((nh, nw, a.shape[2]), a.dtype)
        canvas[:] = np.asarray(self.pad_val, a.dtype)
        canvas[oy:oy + h, ox:ox + w] = a
        new_label = label.copy()
        valid = new_label[:, 0] >= 0
        new_label[valid, 1] = (label[valid, 1] * w + ox) / nw
        new_label[valid, 2] = (label[valid, 2] * h + oy) / nh
        new_label[valid, 3] = (label[valid, 3] * w + ox) / nw
        new_label[valid, 4] = (label[valid, 4] * h + oy) / nh
        return nd_array(canvas), new_label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_mirror=False, mean=None, std=None, brightness=0,
                       contrast=0, saturation=0, hue=0, pca_noise=0,
                       rand_gray=0, inter_method=2,
                       min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Detection augmenter factory (ref: detection.py CreateDetAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        # rand_crop is the per-image application probability (ref semantics)
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])),
                                min_eject_coverage, max_attempts)
        auglist.append(DetRandomSelectAug([crop], skip_prob=1.0 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (1.0, max(1.0, area_range[1])),
                              max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad], skip_prob=1.0 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetBorrowAug(ForceResizeAug((data_shape[2], data_shape[1]),
                                               inter_method)))
    for jitter, cls in ((brightness, BrightnessJitterAug),
                       (contrast, ContrastJitterAug),
                       (saturation, SaturationJitterAug),
                       (hue, HueJitterAug)):
        if jitter > 0:
            auglist.append(DetBorrowAug(cls(jitter)))
    if pca_noise > 0:
        auglist.append(DetBorrowAug(LightingAug(
            pca_noise, _IMAGENET_PCA_EIGVAL.copy(),
            _IMAGENET_PCA_EIGVEC.copy())))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(
            mean if mean is not None else np.zeros(3, np.float32),
            std if std is not None else np.ones(3, np.float32))))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator: images + (max_objects, 5) padded box labels
    (ref: python/mxnet/image/detection.py ImageDetIter; C++ twin
    src/io/iter_image_det_recordio.cc)."""

    def __init__(self, batch_size, data_shape, label_width=-1, aug_list=None,
                 **kwargs):
        det_kwargs = {k: kwargs.pop(k) for k in (
            "rand_crop", "rand_pad", "min_object_covered", "aspect_ratio_range",
            "area_range", "min_eject_coverage", "max_attempts", "pad_val",
        ) if k in kwargs}
        img_aug_kwargs = {k: kwargs.pop(k) for k in (
            "resize", "rand_mirror", "mean", "std", "brightness", "contrast",
            "saturation", "pca_noise", "rand_gray", "inter_method",
        ) if k in kwargs}
        super().__init__(batch_size, data_shape, label_width=1,
                         aug_list=[], **kwargs)
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **det_kwargs,
                                          **img_aug_kwargs)
        self.det_auglist = aug_list
        # label_width > 0 (flat label slots, the reference's escape hatch)
        # skips the full-dataset label scan — essential for large shards
        self.max_objects = (label_width // 5 if label_width > 0
                            else self._scan_max_objects())

    def _scan_max_objects(self):
        mx_obj = 1
        if self.imglist is not None:
            for lbl, _ in self.imglist:
                mx_obj = max(mx_obj, len(np.asarray(lbl).reshape(-1, 5)))
        elif self.seq is not None:
            for idx in self.seq:
                s = self.imgrec.read_idx(idx)
                header, _ = recordio.unpack(s)
                lbl = np.asarray(header.label).reshape(-1)
                if lbl.size >= 5:
                    mx_obj = max(mx_obj, lbl.size // 5)
        else:  # sequential .rec without .idx: full pass, then rewind
            while True:
                s = self.imgrec.read()
                if s is None:
                    break
                header, _ = recordio.unpack(s)
                lbl = np.asarray(header.label).reshape(-1)
                if lbl.size >= 5:
                    mx_obj = max(mx_obj, lbl.size // 5)
            self.imgrec.reset()
        return mx_obj

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size, self.max_objects, 5), np.float32)]

    def next(self):
        c, h, w = self.data_shape
        batch_data = np.zeros((self.batch_size, c, h, w), np.float32)
        batch_label = -np.ones((self.batch_size, self.max_objects, 5), np.float32)
        i = 0
        pad = 0
        try:
            while i < self.batch_size:
                label, s = self.next_sample()
                img = imdecode(s)
                lbl = np.asarray(label, np.float32).reshape(-1, 5)
                for aug in self.det_auglist:
                    img, lbl = aug(img, lbl)
                a = img.asnumpy() if isinstance(img, NDArray) else img
                batch_data[i] = np.transpose(a.astype(np.float32), (2, 0, 1))
                n = min(len(lbl), self.max_objects)
                batch_label[i, :n] = lbl[:n]
                i += 1
        except StopIteration:
            if i == 0:
                raise
            pad = self.batch_size - i
        return DataBatch(data=[nd_array(batch_data)],
                         label=[nd_array(batch_label)], pad=pad)


__all__ += [
    "DetAugmenter", "DetBorrowAug", "DetRandomSelectAug", "DetHorizontalFlipAug",
    "DetRandomCropAug", "DetRandomPadAug", "CreateDetAugmenter", "ImageDetIter",
]
