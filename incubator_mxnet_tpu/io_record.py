"""C++-backed record iterators: ImageRecordIter, MNISTIter, LibSVMIter.

TPU-native analog of the reference's registered C++ data iterators
(ref: SURVEY §2 N19 — src/io/iter_image_recordio_2.cc ImageRecordIter2,
src/io/iter_mnist.cc, src/io/iter_libsvm.cc). Architecture mirrors the
reference's parser->batcher->prefetcher pipeline:

- shard read: the native mmap/thread-pool RecordIO engine
  (src/recordio.cc via recordio.NativeRecordReader), with
  part_index/num_parts distributed sharding;
- decode+augment: a `preprocess_threads`-wide thread pool (JPEG decode is
  the CPU hot spot, exactly as in the reference's OpenCV path);
- batching+prefetch: a background thread keeps `prefetch_buffer` ready
  batches in a bounded queue (ref: iter_prefetcher.h PrefetcherIter), so
  host decode overlaps device compute.
"""
from __future__ import annotations

import gzip
import os
import queue
import random as pyrandom
import struct
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from .io import DataBatch, DataDesc, DataIter
from .ndarray import array as nd_array
from . import recordio

__all__ = ["ImageRecordIter", "MNISTIter", "LibSVMIter"]


class _PrefetchMixin:
    """Background-thread batch prefetcher (ref: iter_prefetcher.h:47)."""

    def _start_prefetch(self, depth):
        self._q = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._producer_exc = None
        self._exhausted = False

        def put(item):
            # bounded put that aborts when the iterator is reset/closed, so
            # an abandoned iterator's producer thread can exit instead of
            # blocking forever on a full queue (and pinning self against GC)
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        def run():
            try:
                while not self._stop.is_set():
                    try:
                        b = self._produce()
                    except StopIteration:
                        put(None)
                        return
                    if not put(b):
                        return
            except BaseException as e:  # surfaced on next()
                # single writer (this thread), single reader (the consumer
                # after it drains the None sentinel below) — the sentinel
                # put() orders the write, so no lock is needed
                self._producer_exc = e  # mxlint: disable=MXL008
                put(None)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="mxtpu-record-prefetch")
        self._thread.start()

    def _stop_prefetch(self):
        """Returns True when the producer thread has fully exited."""
        t = getattr(self, "_thread", None)
        if t is None:
            return True
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        t.join(timeout=5)
        if t.is_alive():  # producer wedged (e.g. slow decode) — keep ref
            return False
        self._thread = None
        return True

    def close(self):
        self._stop_prefetch()

    def __del__(self):
        try:
            self._stop_prefetch()
        except Exception:
            pass

    def next(self):
        if self._exhausted:  # keep raising after the end, like the reference
            raise StopIteration
        b = self._q.get()
        if b is None:
            self._exhausted = True
            if self._producer_exc is not None:
                raise self._producer_exc
            raise StopIteration
        return b


class _PyRandomAccessRec:
    """Thread-safe random-access fallback over a .rec file (no .idx needed).

    One header-only scan builds the offset table, then every read is a
    single `os.pread` — positionless, so the decode thread pool can read
    concurrently without locks (the C++ engine does the same via mmap).
    """

    def __init__(self, uri, idx_path=None):
        from .recordio import _MAGIC, _decode_lrec
        import struct

        self._fd = os.open(uri, os.O_RDONLY)
        self._offsets = []  # (payload_offset, length)
        if idx_path and os.path.isfile(idx_path):
            # honor a user-supplied .idx (subset / custom order): each line
            # is "key\tbyte_offset" of a record start
            starts = []
            with open(idx_path) as f:
                for line in f:
                    parts = line.split()
                    if len(parts) >= 2:
                        starts.append(int(parts[1]))
            for pos in starts:
                head = os.pread(self._fd, 8, pos)
                magic, lrec = struct.unpack("<II", head)
                if magic != _MAGIC:
                    raise IOError(f"bad idx offset {pos} for {uri}")
                _, length = _decode_lrec(lrec)
                self._offsets.append((pos + 8, length))
        else:
            pos = 0
            size = os.fstat(self._fd).st_size
            while pos + 8 <= size:
                head = os.pread(self._fd, 8, pos)
                magic, lrec = struct.unpack("<II", head)
                if magic != _MAGIC:
                    raise IOError(f"invalid record magic {magic:#x} in {uri}")
                _, length = _decode_lrec(lrec)
                self._offsets.append((pos + 8, length))
                pos += 8 + length + (4 - length % 4) % 4
        if not self._offsets:
            raise IOError(f"no records found in {uri}")

    def __len__(self):
        return len(self._offsets)

    def read(self, i):
        off, length = self._offsets[i]
        return os.pread(self._fd, length, off)

    def close(self):
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class ImageRecordIter(_PrefetchMixin, DataIter):
    """Threaded image-record iterator
    (ref: src/io/iter_image_recordio_2.cc:766 `ImageRecordIter` registration;
    Python surface: mx.io.ImageRecordIter). Parameters mirror the
    reference's dmlc::Parameter structs (ImageRecParserParam /
    ImageRecordParam / BatchParam / ImageNormalizeParam).
    """

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 preprocess_threads=None, prefetch_buffer=None,
                 rand_crop=False, rand_mirror=False, resize=0,
                 mean_img=None, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=1.0, std_g=1.0, std_b=1.0, scale=1.0,
                 max_random_scale=1.0, min_random_scale=1.0,
                 max_rotate_angle=0, max_aspect_ratio=0.0, max_shear_ratio=0.0,
                 random_h=0, random_s=0, random_l=0, fill_value=127,
                 inter_method=1, data_name="data", label_name="softmax_label",
                 round_batch=True, seed=0, dtype="float32", **kwargs):
        super().__init__(batch_size)
        from . import image as _image
        from . import config as _config

        if preprocess_threads is None:
            preprocess_threads = _config.get("MXTPU_DECODE_THREADS")
        if prefetch_buffer is None:
            prefetch_buffer = _config.get("MXTPU_PREFETCH_BUFFER")
        self.data_shape = tuple(int(x) for x in data_shape)
        self.label_width = int(label_width)
        self.data_name, self.label_name = data_name, label_name
        self.dtype = dtype
        self.round_batch = round_batch
        self.shuffle = shuffle
        self._rng = pyrandom.Random(seed)

        # --- shard reader: native engine first, pread-based Python fallback
        #     (both are positionless -> safe under the decode thread pool) ---
        if path_imgidx:
            # explicit .idx subsets/reorders the shard; pread fallback
            # handles it natively
            self._reader = _PyRandomAccessRec(path_imgrec, path_imgidx)
        else:
            try:
                self._reader = recordio.NativeRecordReader(path_imgrec)
            except (RuntimeError, IOError):
                self._reader = _PyRandomAccessRec(path_imgrec)
        n = len(self._reader)
        self._read = self._reader.read

        self._seq = list(range(n))
        if num_parts > 1:  # distributed sharding (ref: part_index/num_parts)
            per = n // num_parts
            self._seq = self._seq[part_index * per:(part_index + 1) * per]

        # --- augmenter chain from the reference's default-augmenter params
        #     (ref: src/io/image_aug_default.cc:46-283) ---
        mean = std = None
        if mean_r or mean_g or mean_b:
            mean = np.array([mean_r, mean_g, mean_b], np.float32)
        if (std_r, std_g, std_b) != (1.0, 1.0, 1.0):
            std = np.array([std_r, std_g, std_b], np.float32)
        self._auglist = _image.CreateAugmenter(
            self.data_shape, resize=resize, rand_crop=rand_crop,
            rand_mirror=rand_mirror, mean=mean, std=std,
            brightness=random_l / 255.0 if random_l else 0,
            saturation=random_s / 255.0 if random_s else 0,
            hue=random_h / 180.0 if random_h else 0,
            inter_method=inter_method)
        self._scale = float(scale)

        self._pool = ThreadPoolExecutor(max_workers=max(1, preprocess_threads))
        self._prefetch_depth = int(prefetch_buffer)
        self._cursor = 0

        # --- native decode+augment fast path (src/imgpipe.cc; ref:
        #     iter_image_recordio_2.cc) when the augmentation config is in
        #     the subset it implements: resize / random|center crop /
        #     mirror / mean/std / scale. Anything richer (HSL jitter,
        #     rotation, aspect) keeps the Python augmenter chain. ---
        self._native = None
        simple_augs = (not (random_h or random_s or random_l)
                       and max_rotate_angle == 0 and max_aspect_ratio == 0.0
                       and max_shear_ratio == 0.0 and max_random_scale == 1.0
                       and min_random_scale == 1.0 and mean_img is None
                       and self.data_shape[0] == 3 and dtype == "float32"
                       and inter_method == 1)  # native resize is bilinear
        if simple_augs:
            from . import _native as _nat

            lib = _nat.imgpipe_lib()
            if lib is not None:
                import ctypes as _ct

                mean = np.asarray([mean_r, mean_g, mean_b], np.float32)
                std = np.asarray([std_r, std_g, std_b], np.float32)
                self._native = dict(
                    lib=lib, ct=_ct,
                    mean=mean, std=std,
                    resize=int(resize), rand_crop=int(bool(rand_crop)),
                    rand_mirror=int(bool(rand_mirror)),
                    threads=max(1, preprocess_threads), seed=int(seed))
        if self._native is not None:
            # decide native-vs-python DETERMINISTICALLY for homogeneous
            # shards: peek at record 0's payload magic. Without this the
            # runtime fallback (non-JPEG seen mid-batch) races the
            # prefetch thread, so observers could not rely on engagement
            # state; heterogeneous shards still fall back at runtime.
            try:
                rr = recordio.MXRecordIO(path_imgrec, "r")
                s = rr.read()
                rr.close()
                if s:
                    _, img0 = recordio.unpack(s)
                    if not (len(img0) >= 2 and img0[0] == 0xFF
                            and img0[1] == 0xD8):
                        self._native = None
            except Exception:
                pass  # unreadable first record: the runtime path decides
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name, (self.batch_size,) + self.data_shape,
                         np.dtype(self.dtype))]

    @property
    def provide_label(self):
        shape = ((self.batch_size,) if self.label_width == 1
                 else (self.batch_size, self.label_width))
        return [DataDesc(self.label_name, shape, np.float32)]

    def _decode_one(self, rec_index):
        from . import image as _image

        header, img_bytes = recordio.unpack(self._read(rec_index))
        img = _image.imdecode(img_bytes)
        for aug in self._auglist:
            img = aug(img)
        a = img.asnumpy() if hasattr(img, "asnumpy") else np.asarray(img)
        a = np.transpose(a.astype(np.float32), (2, 0, 1)) * self._scale
        label = np.asarray(header.label, np.float32)
        return a.astype(self.dtype, copy=False), label

    def _produce_native(self, take, pad):
        """Batch decode+augment entirely in C++ (GIL-free thread pool)."""
        nat = self._native
        ct = nat["ct"]
        n = len(take)
        if hasattr(self._reader, "read_batch"):
            blobs = self._reader.read_batch(take)
        else:
            blobs = [self._read(i) for i in take]
        raws, labels = [], []
        for blob in blobs:
            header, img_bytes = recordio.unpack(blob)
            if not img_bytes.startswith(b"\xff\xd8"):
                # non-JPEG payload (e.g. PNG-packed shard): the native
                # decoder only handles JPEG — permanently fall back to the
                # cv2-based Python chain, which decodes any format
                self._native = None
                return None
            raws.append(img_bytes)
            labels.append(np.asarray(header.label, np.float32))
        keep = [ct.c_char_p(r) for r in raws]  # keep buffers alive
        datas = (ct.c_void_p * n)(*[ct.cast(k, ct.c_void_p) for k in keep])
        lens = (ct.c_uint32 * n)(*[len(r) for r in raws])
        idxs = (ct.c_int64 * n)(*take)
        out = np.empty((n, 3) + self.data_shape[1:], np.float32)
        # per-epoch seed shift: fresh augmentation stream each epoch, same
        # stream for a given (seed, epoch) — matching the Python chain's
        # fresh-per-epoch randomness while keeping runs reproducible
        seed = (nat["seed"] + 0x9E3779B1 * self._epoch) & 0xFFFFFFFFFFFFFFFF
        rc = nat["lib"].imgpipe_decode_batch(
            datas, lens, idxs, n,
            out.ctypes.data_as(ct.POINTER(ct.c_float)),
            self.data_shape[1], self.data_shape[2], nat["resize"],
            nat["rand_crop"], nat["rand_mirror"],
            nat["mean"].ctypes.data_as(ct.POINTER(ct.c_float)),
            nat["std"].ctypes.data_as(ct.POINTER(ct.c_float)),
            self._scale, seed, nat["threads"])
        if rc != 0:
            raise IOError(f"corrupt record at batch position {rc - 1} "
                          f"(record {take[rc - 1]})")
        return DataBatch(data=[nd_array(out)],
                         label=[nd_array(self._assemble_labels(labels))],
                         pad=pad)

    def _assemble_labels(self, labels):
        if self.label_width == 1:
            return np.array([float(np.atleast_1d(l)[0]) for l in labels],
                            np.float32)
        return np.stack([np.resize(l, self.label_width) for l in labels])

    def _produce(self):
        if self._cursor >= len(self._seq):
            raise StopIteration
        take = self._seq[self._cursor:self._cursor + self.batch_size]
        self._cursor += len(take)
        pad = self.batch_size - len(take)
        if pad and not self.round_batch:
            raise StopIteration
        if pad:  # wrap-around padding like the reference's round_batch
            take = take + self._seq[:pad]
        if self._native is not None:
            batch = self._produce_native(take, pad)
            if batch is not None:
                return batch
            # fell back (non-JPEG shard): continue on the Python chain
        samples = list(self._pool.map(self._decode_one, take))
        data = np.stack([s[0] for s in samples])
        label = self._assemble_labels([s[1] for s in samples])
        return DataBatch(data=[nd_array(data)], label=[nd_array(label)], pad=pad)

    def reset(self):
        self._stop_prefetch()
        if self.shuffle:
            self._rng.shuffle(self._seq)
        self._cursor = 0
        self._epoch = getattr(self, "_epoch", -1) + 1
        self._start_prefetch(self._prefetch_depth)

    def close(self):
        stopped = self._stop_prefetch()
        self._pool.shutdown(wait=stopped)
        if stopped:
            # only close the fd once no producer/decoder can still read it;
            # otherwise leave cleanup to GC rather than risk EBADF races
            self._reader.close()


def _read_idx_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, num = struct.unpack(">ii", f.read(8))
        if magic == 2051:  # images
            rows, cols = struct.unpack(">ii", f.read(8))
            data = np.frombuffer(f.read(), np.uint8).reshape(num, rows, cols)
        elif magic == 2049:  # labels
            data = np.frombuffer(f.read(), np.uint8)
        else:
            raise ValueError(f"bad idx magic {magic} in {path}")
    return data


class MNISTIter(_PrefetchMixin, DataIter):
    """MNIST idx-file iterator (ref: src/io/iter_mnist.cc `MNISTIter`)."""

    def __init__(self, image="train-images-idx3-ubyte", label="train-labels-idx1-ubyte",
                 batch_size=128, shuffle=False, flat=False, silent=True,
                 part_index=0, num_parts=1, seed=0, prefetch_buffer=2, **kwargs):
        super().__init__(batch_size)
        imgs = _read_idx_images(image).astype(np.float32) / 255.0
        labels = _read_idx_images(label).astype(np.float32)
        if num_parts > 1:
            per = len(imgs) // num_parts
            sl = slice(part_index * per, (part_index + 1) * per)
            imgs, labels = imgs[sl], labels[sl]
        self._X = imgs.reshape(len(imgs), -1) if flat else imgs[:, None, :, :]
        self._y = labels
        self.flat = flat
        self.shuffle = shuffle
        self._rng = np.random.RandomState(seed)
        self._prefetch_depth = prefetch_buffer
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self._X.shape[1:], np.float32)]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,), np.float32)]

    def _produce(self):
        if self._cursor + self.batch_size > len(self._X):
            raise StopIteration
        sl = self._order[self._cursor:self._cursor + self.batch_size]
        self._cursor += self.batch_size
        return DataBatch(data=[nd_array(self._X[sl])],
                         label=[nd_array(self._y[sl])], pad=0)

    def reset(self):
        self._stop_prefetch()
        self._order = np.arange(len(self._X))
        if self.shuffle:
            self._rng.shuffle(self._order)
        self._cursor = 0
        self._start_prefetch(self._prefetch_depth)


class LibSVMIter(DataIter):
    """LibSVM text-format iterator producing CSR batches
    (ref: src/io/iter_libsvm.cc `LibSVMIter`). Feature vectors come out as
    CSRNDArray (ref's kCSRStorage batches); dense labels.
    """

    def __init__(self, data_libsvm, data_shape, batch_size, label_libsvm=None,
                 label_shape=None, part_index=0, num_parts=1, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(int(x) for x in (
            data_shape if not np.isscalar(data_shape) else (data_shape,)))
        rows = []
        labels = []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                rows.append([(int(k), float(v)) for k, v in
                             (p.split(":") for p in parts[1:])])
        if label_libsvm:
            labels = []
            with open(label_libsvm) as f:
                for line in f:
                    if line.strip():
                        labels.append(float(line.split()[0]))
        if num_parts > 1:
            per = len(rows) // num_parts
            sl = slice(part_index * per, (part_index + 1) * per)
            rows, labels = rows[sl], labels[sl]
        self._rows = rows
        self._labels = np.asarray(labels, np.float32)
        self._cursor = 0

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape, np.float32)]

    @property
    def provide_label(self):
        return [DataDesc("label", (self.batch_size,), np.float32)]

    def reset(self):
        self._cursor = 0

    def next(self):
        from .ndarray import sparse as _sparse

        if self._cursor + self.batch_size > len(self._rows):
            raise StopIteration
        take = self._rows[self._cursor:self._cursor + self.batch_size]
        lab = self._labels[self._cursor:self._cursor + self.batch_size]
        self._cursor += self.batch_size
        # build CSR directly (O(nnz)) — never densify the feature dim
        indptr = np.zeros(self.batch_size + 1, np.int64)
        for r, row in enumerate(take):
            indptr[r + 1] = indptr[r] + len(row)
        indices = np.fromiter((k for row in take for k, _ in row), np.int64,
                              count=int(indptr[-1]))
        values = np.fromiter((v for row in take for _, v in row), np.float32,
                             count=int(indptr[-1]))
        csr = _sparse.csr_matrix((values, indices, indptr),
                                 shape=(self.batch_size,) + self.data_shape)
        return DataBatch(data=[csr], label=[nd_array(lab)], pad=0)
