"""Global random state.

The reference keeps per-device PRNG resources seeded by mx.random.seed
(ref: src/resource.cc kRandom pools, python/mxnet/random.py). TPU-native
design: a single counter-based root key; every consumer takes a fresh split,
so results are reproducible per seed and independent per call — and, under
pjit, per replica when folded with axis index.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "next_key", "current_seed"]

_LOCK = threading.Lock()
_SEED = 0
_KEY = None


def seed(seed_state, ctx="all"):
    """Seed the global generator (ref: mx.random.seed)."""
    global _SEED, _KEY
    with _LOCK:
        _SEED = int(seed_state)
        _KEY = jax.random.PRNGKey(_SEED)


def current_seed():
    return _SEED


def next_key():
    """Return a fresh PRNG key (thread-safe split of the root key). Under
    `key_override` (hybrid tracing) splits the overridden key instead."""
    global _KEY
    override = getattr(_OVERRIDE, "key", None)
    if override is not None:
        new, sub = jax.random.split(override)
        _OVERRIDE.key = new
        return sub
    with _LOCK:
        if _KEY is None:
            _KEY = jax.random.PRNGKey(_SEED)
        _KEY, sub = jax.random.split(_KEY)
        return sub


import contextlib as _contextlib

_OVERRIDE = threading.local()


@_contextlib.contextmanager
def key_override(key):
    """Thread an explicit key through next_key() — used while jit-tracing
    hybridized blocks so randomness is a function argument, not trace-time
    state."""
    prev = getattr(_OVERRIDE, "key", None)
    _OVERRIDE.key = key
    try:
        yield
    finally:
        _OVERRIDE.key = prev
