"""Global random state.

The reference keeps per-device PRNG resources seeded by mx.random.seed
(ref: src/resource.cc kRandom pools, python/mxnet/random.py). TPU-native
design: a single counter-based root key; every consumer takes a fresh split,
so results are reproducible per seed and independent per call — and, under
pjit, per replica when folded with axis index.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "next_key", "current_seed", "get_state", "set_state"]

_LOCK = threading.Lock()
_SEED = 0
_COUNTER = 0

# keys are precomputed in blocks: ONE jitted vmap(fold_in) dispatch per
# _BLOCK_N calls instead of an eager threefry per call (~75us charged to
# every cached-forward invocation). The values are bit-identical to
# per-call fold_in(PRNGKey(seed), counter); the block is host-resident
# numpy so handing a key out costs no device dispatch at all.
_BLOCK_N = 256
_BLOCK = None
_BLOCK_BASE = 0
_REFILL = None

try:  # moved between jax.core and jax._src.core across jax versions
    from jax.core import trace_state_clean as _trace_state_clean
except ImportError:
    try:
        from jax._src.core import trace_state_clean as _trace_state_clean
    except ImportError:
        def _trace_state_clean():
            # unknown jax internals: disable the block path entirely
            # (correctness of traced callers over the amortization win)
            return False


def seed(seed_state, ctx="all"):
    """Seed the global generator (ref: mx.random.seed)."""
    global _SEED, _COUNTER, _BLOCK
    with _LOCK:
        _SEED = int(seed_state)
        _COUNTER = 0
        _BLOCK = None


def current_seed():
    return _SEED


def get_state():
    """Checkpointable generator position. The whole state is (seed,
    counter) on the host — keys derive via fold_in — so restoring it
    makes every subsequent `next_key()` bit-identical (docs/
    FAULT_TOLERANCE.md — Preemption and exact resume)."""
    with _LOCK:
        return {"seed": _SEED, "counter": _COUNTER}


def set_state(state):
    """Restore a `get_state()` snapshot (exact-resume counterpart of
    `seed()`, which always rewinds the counter to 0)."""
    global _SEED, _COUNTER, _BLOCK
    with _LOCK:
        _SEED = int(state["seed"])
        _COUNTER = int(state["counter"])
        _BLOCK = None


def _refill(seed_val, start):
    global _REFILL
    if _REFILL is None:
        def fill(root, counters):
            return jax.vmap(lambda c: jax.random.fold_in(root, c))(counters)

        _REFILL = jax.jit(fill)
    import numpy as np

    counters = np.arange(start, start + _BLOCK_N, dtype=np.uint32)
    return jax.device_get(_REFILL(jax.random.PRNGKey(seed_val), counters))


def next_key():
    """Return a fresh PRNG key. The global state is (seed, counter) on the
    HOST — keys derive via fold_in, so calling inside a jax trace never leaks
    a traced key into global state. Under `key_override` (hybrid tracing) the
    overridden key is split instead."""
    global _COUNTER, _BLOCK, _BLOCK_BASE
    override = getattr(_OVERRIDE, "key", None)
    if override is not None:
        new, sub = jax.random.split(override)
        _OVERRIDE.key = new
        return sub
    with _LOCK:
        _COUNTER += 1
        c = _COUNTER
        if not _trace_state_clean():
            # inside a jit trace: derive the key as literals (a closed-over
            # constant, the pre-block behavior). Running the jitted refill
            # here would inline it into the outer trace and cache a TRACED
            # value into module state — a leaked-tracer bug.
            return jax.random.fold_in(jax.random.PRNGKey(_SEED), c)
        if _BLOCK is None or not (_BLOCK_BASE <= c < _BLOCK_BASE + _BLOCK_N):
            _BLOCK_BASE = c
            _BLOCK = _refill(_SEED, c)
        return _BLOCK[c - _BLOCK_BASE]


import contextlib as _contextlib

_OVERRIDE = threading.local()


@_contextlib.contextmanager
def key_override(key):
    """Thread an explicit key through next_key() — used while jit-tracing
    hybridized blocks so randomness is a function argument, not trace-time
    state."""
    prev = getattr(_OVERRIDE, "key", None)
    _OVERRIDE.key = key
    try:
        yield
    finally:
        _OVERRIDE.key = prev
