"""Global random state.

The reference keeps per-device PRNG resources seeded by mx.random.seed
(ref: src/resource.cc kRandom pools, python/mxnet/random.py). TPU-native
design: a single counter-based root key; every consumer takes a fresh split,
so results are reproducible per seed and independent per call — and, under
pjit, per replica when folded with axis index.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "next_key", "current_seed"]

_LOCK = threading.Lock()
_SEED = 0
_COUNTER = 0


def seed(seed_state, ctx="all"):
    """Seed the global generator (ref: mx.random.seed)."""
    global _SEED, _COUNTER
    with _LOCK:
        _SEED = int(seed_state)
        _COUNTER = 0


def current_seed():
    return _SEED


def next_key():
    """Return a fresh PRNG key. The global state is (seed, counter) on the
    HOST — keys derive via fold_in, so calling inside a jax trace never leaks
    a traced key into global state. Under `key_override` (hybrid tracing) the
    overridden key is split instead."""
    global _COUNTER
    override = getattr(_OVERRIDE, "key", None)
    if override is not None:
        new, sub = jax.random.split(override)
        _OVERRIDE.key = new
        return sub
    with _LOCK:
        _COUNTER += 1
        c = _COUNTER
    return jax.random.fold_in(jax.random.PRNGKey(_SEED), c)


import contextlib as _contextlib

_OVERRIDE = threading.local()


@_contextlib.contextmanager
def key_override(key):
    """Thread an explicit key through next_key() — used while jit-tracing
    hybridized blocks so randomness is a function argument, not trace-time
    state."""
    prev = getattr(_OVERRIDE, "key", None)
    _OVERRIDE.key = key
    try:
        yield
    finally:
        _OVERRIDE.key = prev
