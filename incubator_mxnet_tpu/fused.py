"""Fused training steps.

TPU-native analog of the reference's bulked execution: where the graph
executor pre-creates engine ops and bulks whole fwd/bwd segments
(ref: graph_executor.cc InitCachedOps:1073, InitOpSegs:1187,
MXNET_EXEC_BULK_EXEC_*), here the ENTIRE training step — forward, backward,
and optimizer update — is one jit-compiled XLA program with parameter
buffers donated, so updates are in-place in HBM and the only per-step host
work is the dispatch call.

Under a mesh, inputs sharded on the batch axis + replicated params make the
same program data-parallel: GSPMD inserts the gradient all-reduce over ICI
(the kvstore='device'/'nccl' path of the reference).

Optimizer coverage: EVERY built-in optimizer (SGD, NAG, SGLD, Signum, FTML,
DCASGD, LBSGD, Adam, AdaGrad, RMSProp, AdaDelta, Ftrl, Adamax, Nadam,
AdamW, Test) ships an exact fused_update whose 3-step trajectory is tested
against its eager update() (tests/test_optimizer.py). Custom optimizers
without one fall back to tracing their eager update() inside the step
(with a RuntimeWarning): correct for pure-jnp-math updates, but Python-side
state (per-index update counts, host RNG draws) freezes at trace time —
implement fused_update(name, weight, grad, state, lr, t=None) for
time-dependent or stochastic custom updates.
"""
from __future__ import annotations

import time as _time

import jax
import jax.numpy as jnp

from . import autograd
from . import config
from . import random as _global_random
from . import telemetry as _telemetry
from .telemetry import compilereg as _compilereg
from . import compile_cache as _compile_cache
from .telemetry import stepstats as _stepstats
from .gluon.block import _ParamSubst
from .ndarray.ndarray import NDArray
from .optimizer import _cast_state_like as _cast_like

__all__ = ["GluonTrainStep", "resolve_remat_policy"]

# Friendly tiers for MXTPU_REMAT_POLICY, ordered by how much they save
# (everything_saveable = no recompute) vs recompute (nothing_saveable =
# the legacy remat=True behavior). Any exact jax.checkpoint_policies
# attribute name is also accepted.
_REMAT_POLICY_ALIASES = {
    "dots": "dots_saveable",
    "dots_no_batch": "dots_with_no_batch_dims_saveable",
    "offload": "offload_dot_with_no_batch_dims",
    "nothing": "nothing_saveable",
    "everything": "everything_saveable",
}


def _convs_and_dots_saveable(prim, *_, **__):
    """The 'convs' tier: keep MXU results (convolutions AND matmuls) for
    the backward, recompute only cheap elementwise/BN chains. jax's
    builtin dots_* policies save dot_general only — on a conv net they
    recompute every convolution (the expensive op) while saving nothing,
    which is why the batch-256 bf16 remat config regressed instead of
    merely trading flops for memory."""
    return prim.name in ("conv_general_dilated", "dot_general")


def resolve_remat_policy(name):
    """Map a MXTPU_REMAT_POLICY value to a jax.checkpoint policy callable.

    Accepts the friendly tier names ('convs', 'dots', 'dots_no_batch',
    'offload', 'nothing', 'everything') or any exact attribute of
    jax.checkpoint_policies. Returns None for the empty string (legacy
    all-or-nothing checkpointing). Raises ValueError for unknown names,
    listing what is available."""
    if not name:
        return None
    if name == "convs":
        return _convs_and_dots_saveable
    cp = jax.checkpoint_policies
    attr = _REMAT_POLICY_ALIASES.get(name, name)
    pol = getattr(cp, attr, None)
    if pol is None:
        known = ["convs"] + sorted(_REMAT_POLICY_ALIASES) + sorted(
            a for a in dir(cp) if not a.startswith("_"))
        raise ValueError(
            f"unknown remat policy {name!r} (MXTPU_REMAT_POLICY); expected "
            f"one of {known}")
    if attr == "offload_dot_with_no_batch_dims":
        # this policy is a factory taking (src, dst) memory kinds
        pol = pol("device", "pinned_host")
    return pol


class GluonTrainStep:
    """Compile net+loss+optimizer into one donated-buffer step.

    step(x, y) -> loss (device scalar, async). Parameters and optimizer
    states live as jax arrays owned by this object and are written back into
    the net's Parameters after every step (same objects, rebound data).
    """

    def __init__(self, net, loss_fn, optimizer, mesh=None, batch_axis=0, device=None,
                 init_on_device=False, compute_dtype=None,
                 shard_optimizer_states=False, remat=False,
                 remat_policy=None, shard_policy=None):
        self.net = net
        self.loss_fn = loss_fn
        self.opt = optimizer
        self.mesh = mesh
        self.device = device  # single target device (e.g. the TPU chip)
        # regenerate parameter/state buffers ON the target device instead of
        # shipping the host-initialized values over the wire: one tiny seed
        # crosses instead of the full model (~100MB for ResNet-50). Values
        # are fresh random draws with each param's host scale — identical
        # program and throughput, different (valid) weights; meant for
        # benchmarking remote-attached chips where bulk transfers are the
        # least reliable link, not for resuming real training.
        self.init_on_device = init_on_device
        if init_on_device and mesh is not None:
            raise ValueError(
                "init_on_device supports the single-device path only; for a "
                "mesh, params are placed by sharding annotations at build")
        # mixed precision the TPU way (the reference's multi-precision SGD,
        # ref: optimizer_op.cc mp_sgd_update): master params and optimizer
        # states stay float32; inside the step, floating params and inputs
        # are cast to compute_dtype (e.g. bfloat16) so convs/matmuls ride
        # the MXU at full rate, while gradients and updates are f32.
        # Contrast with net.cast("bfloat16"), which trains pure-bf16.
        self.compute_dtype = jnp.dtype(compute_dtype) if compute_dtype else None
        # rematerialization (jax.checkpoint over the whole forward): the
        # backward recomputes activations instead of keeping them in HBM —
        # the TPU-native form of the reference's MXNET_BACKWARD_DO_MIRROR /
        # memonger (ref: docs/faq/env_var.md, example memonger usage).
        # Trades ~1/3 more FLOPs for activation memory, buying larger
        # batches on memory-bound models. Numerics are identical (same
        # ops, same order, recomputed).
        self.remat = bool(remat)
        # selective remat: a named jax.checkpoint_policies policy (see
        # resolve_remat_policy) decides WHICH intermediates survive to
        # the backward instead of recomputing everything. On the
        # HBM-saturated bf16 path, blanket recompute ADDS traffic (the
        # measured batch-256 regression, docs/PERF_ANALYSIS.md §0);
        # 'convs' keeps the expensive conv/matmul results and recomputes
        # only cheap elementwise, trading the least bandwidth for the
        # memory saved. A non-empty policy implies remat.
        if remat_policy is None:
            remat_policy = config.get("MXTPU_REMAT_POLICY")
        self.remat_policy = remat_policy or ""
        resolve_remat_policy(self.remat_policy)  # validate eagerly
        if self.remat_policy:
            self.remat = True
        # ZeRO sharding policy over the mesh's 'data' axis (ROADMAP item
        # 5): 'replicated' keeps the legacy placement; 'zero1' shards
        # optimizer state + f32 masters 1/N (largest divisible axis per
        # tensor, recorded per param — see parallel.zero); 'zero2' also
        # reduce-scatters gradients so the update reads only the local
        # shard. shard_optimizer_states=True (the pre-policy spelling)
        # remains an alias for zero1.
        from .parallel import zero as _zero

        explicit = shard_policy is not None
        if shard_policy is None:
            shard_policy = config.get("MXTPU_SHARD_POLICY")
        if not shard_policy and shard_optimizer_states:
            shard_policy = "zero1"
        shard_policy = _zero.resolve_policy(shard_policy)
        if shard_policy != "replicated" and mesh is None:
            if explicit or shard_optimizer_states:
                raise ValueError(
                    f"shard_policy={shard_policy!r} requires a mesh")
            # env knob set globally but this step has no mesh: nothing
            # to shard over — keep the (identical) replicated program
            shard_policy = "replicated"
        self.shard_policy = shard_policy
        self.shard_optimizer_states = shard_policy != "replicated"
        self.state_specs = None  # per-tensor placement record (mesh builds)
        self._built = False
        self._n = 0
        from .optimizer import Optimizer as _OptBase

        if (type(self.opt).fused_update is _OptBase.fused_update
                and type(self.opt) is not _OptBase):
            # every built-in optimizer ships an exact fused_update; a custom
            # one falls back to tracing its eager update(), which freezes
            # any Python-side state (update counts, host RNG) at trace time
            import warnings

            warnings.warn(
                f"{type(self.opt).__name__} has no dedicated fused_update; "
                f"tracing its eager update() instead. Time-dependent or "
                f"stochastic optimizers should implement "
                f"fused_update(name, weight, grad, state, lr, t=None).",
                RuntimeWarning)

    def _build(self, x, y):
        # resolve deferred parameter shapes abstractly: eval_shape traces the
        # forward without touching the device (no per-op dispatch/compile)
        def warm(xd, yd):
            # predict mode: BN must not write (traced) aux values into the
            # real parameter arrays during this abstract pass
            prev = autograd.set_training(False)
            try:
                return self.loss_fn(
                    self.net, NDArray._from_data(xd), NDArray._from_data(yd)
                )._data
            finally:
                autograd.set_training(prev)

        from .gluon.parameter import abstract_init_mode

        with abstract_init_mode():
            jax.eval_shape(
                warm,
                jax.ShapeDtypeStruct(x.shape, x._data.dtype),
                jax.ShapeDtypeStruct(y.shape, y._data.dtype),
            )
        net = self.net
        # materialize any still-deferred params concretely (outside trace)
        for _n, _p in net.collect_params().items():
            if _p._data is None and _p._deferred_init is not None and _p._shape_known():
                _p._finish_deferred_init()
        params = list(net.collect_params().items())
        self.names = [n for n, _ in params]
        self.param_objs = [p for _, p in params]
        self.grad_mask = [p.grad_req != "null" for p in self.param_objs]
        # create_fused_state lets an optimizer carry extra traced state that
        # its eager path keeps in Python (e.g. Nadam's m_schedule)
        make_state = getattr(self.opt, "create_fused_state",
                             self.opt.create_state)
        self._states = [
            self._state_data(make_state(i, p.data())) if m else None
            for i, (p, m) in enumerate(zip(self.param_objs, self.grad_mask))
        ]
        self._params = [p.data()._data for p in self.param_objs]
        if self.device is not None and self.mesh is None:
            if self.init_on_device:
                self._params, self._states = self._materialize_on_device()
            else:
                # bulk host->device transfer of params/states (host init)
                self._params = [jax.device_put(d, self.device)
                                for d in self._params]
                self._states = jax.tree_util.tree_map(
                    lambda d: jax.device_put(d, self.device), self._states
                )
        mesh = self.mesh
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(mesh, P())
            self._params = [jax.device_put(d, rep) for d in self._params]
            if self.shard_policy != "replicated":
                # ZeRO-1 the GSPMD way: optimizer states (including f32
                # masters, which live inside the multi-precision state
                # tuples) sharded over the dp axis along each tensor's
                # largest divisible axis; the scalar/ragged remainder
                # stays replicated. From these placements XLA derives
                # reduce-scatter(grads) -> sharded update ->
                # all-gather(params) instead of a full gradient
                # all-reduce + replicated update — same math, 1/N state
                # HBM. zero2 makes the grad reduce-scatter explicit in
                # _make_step. The per-tensor decision lands in
                # self.state_specs (see shard_placements()).
                from .parallel import zero as _zero

                self._states, self.state_specs = _zero.place_tree(
                    self._states, mesh)
            else:
                self._states = jax.tree_util.tree_map(
                    lambda d: jax.device_put(d, rep), self._states
                )
            self._data_sharding = NamedSharding(mesh, P("data"))
        else:
            self._data_sharding = None
        pending = getattr(self, "_pending_states", None)
        if pending is not None:
            # load_states() was called before the first step: overwrite the
            # freshly created states with the checkpointed values, keeping
            # this build's placements (incl. sharded optimizer states)
            self._states = jax.tree_util.tree_map(
                lambda cur, new: jax.device_put(jnp.asarray(new),
                                                cur.sharding)
                if hasattr(cur, "sharding") else new,
                self._states, pending)
            self._pending_states = None
        # HBM ledger: the fused path owns its state buffers (the eager
        # Trainer tracks its own), so account them here — with sharded
        # placements the ledger reports per-device (addressable-shard)
        # bytes, which is where ZeRO's (N-1)/N saving shows up
        _telemetry.ledger.track(list(self._states), "optimizer_state")
        self._step_fn = self._make_step()
        if mesh is not None:
            # pin output placements to the input ones: without this XLA may
            # propagate replicated outputs for sharded optimizer states,
            # re-sharding every step and defeating the 1/N state HBM
            param_sh = [d.sharding for d in self._params]
            state_sh = jax.tree_util.tree_map(lambda d: d.sharding,
                                              self._states)
            self._out_sh = (None, param_sh, state_sh)
        else:
            self._out_sh = None
        # each fused program goes through the persistent compile cache
        # (no-op wrapper when MXTPU_COMPILE_CACHE_DIR is unset): a
        # restarted process deserializes the executable instead of
        # paying the 81-111s XLA compile again (ROADMAP item 4)
        self._step = _compile_cache.wrap(
            "GluonTrainStep.step",
            jax.jit(self._step_fn, donate_argnums=(0, 1),
                    out_shardings=self._out_sh),
            donated=(0, 1))

        def scan_fn(params, states, xs, ys, keys, lrs, ts):
            def body(carry, inp):
                p, s = carry
                x, y, key, lr, t = inp
                loss, p2, s2 = self._step_fn(p, s, x, y, key, lr, t)
                return (p2, s2), loss

            (params, states), losses = jax.lax.scan(
                body, (params, states), (xs, ys, keys, lrs, ts))
            return losses, params, states

        # one jit wrapper; its cache keys on shapes, so varying K reuses
        # previously compiled executables
        self._scan = _compile_cache.wrap(
            "GluonTrainStep.scan",
            jax.jit(scan_fn, donate_argnums=(0, 1),
                    out_shardings=(None,) + self._out_sh[1:]
                    if self._out_sh is not None else None),
            donated=(0, 1))
        self._accum = _compile_cache.wrap(
            "GluonTrainStep.accum",
            jax.jit(self._accum_fn, donate_argnums=(0, 1),
                    out_shardings=self._out_sh),
            donated=(0, 1))
        self._built = True

    def _materialize_on_device(self):
        """Regenerate param/state buffers on the target device.

        One jitted program per group; only a seed crosses the wire. Each
        parameter is redrawn as mean + std * normal with its host-init
        moments (so BN gammas stay at 1.0 exactly, conv kernels keep their
        Xavier scale); optimizer-state arrays are zeros except the rare
        nonzero leaf, which is transferred as-is."""
        import numpy as np

        sharding = jax.sharding.SingleDeviceSharding(self.device)
        specs = []
        for d in self._params:
            h = np.asarray(d, dtype=np.float32)
            specs.append((tuple(d.shape), d.dtype,
                          float(h.mean()), float(h.std())))

        def gen(seed):
            key = jax.random.PRNGKey(seed)
            outs = []
            for i, (shape, dtype, mean, std) in enumerate(specs):
                k = jax.random.fold_in(key, i)
                v = mean + jax.random.normal(k, shape, jnp.float32) * std
                outs.append(v.astype(dtype))
            return tuple(outs)

        params = list(jax.jit(gen, out_shardings=sharding)(0))

        leaves, treedef = jax.tree_util.tree_flatten(self._states)
        resolved = {}
        zero_specs, zero_idx = [], []
        for i, leaf in enumerate(leaves):
            if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                if np.asarray(leaf).any():  # nonzero init state: ship it
                    resolved[i] = jax.device_put(leaf, self.device)
                else:
                    zero_idx.append(i)
                    zero_specs.append((tuple(leaf.shape), leaf.dtype))
            else:
                resolved[i] = leaf
        if zero_idx:
            zeros = jax.jit(
                lambda: tuple(jnp.zeros(s, d) for s, d in zero_specs),
                out_shardings=sharding)()
            for j, i in enumerate(zero_idx):
                resolved[i] = zeros[j]
        states = jax.tree_util.tree_unflatten(
            treedef, [resolved[i] for i in range(len(leaves))])
        return params, states

    def shard_placements(self):
        """Per-parameter record of the optimizer-state placements the
        shard policy chose: {param_name: [PartitionSpec, ...]} with one
        spec per state leaf (empty list for grad_req='null' params).
        P('data')-style specs mark sharded leaves; P() marks the
        divisibility fallback to replication. None before the first
        build or for meshless/replicated steps."""
        if self.state_specs is None:
            return None
        out = {}
        for name, spec in zip(self.names, self.state_specs):
            out[name] = jax.tree_util.tree_leaves(spec)
        return out

    def _retrack_states(self, old_states):
        """Each step donates the state buffers and returns fresh arrays;
        move the HBM ledger's optimizer_state accounting from the dead
        buffers to the live ones (donation frees device memory NOW,
        before the Python objects die)."""
        _telemetry.ledger.untrack(list(old_states))
        _telemetry.ledger.track(list(self._states), "optimizer_state")

    @staticmethod
    def _state_data(state):
        if state is None:
            return None
        if isinstance(state, (tuple, list)):
            return tuple(s._data if isinstance(s, NDArray) else s for s in state)
        return state._data if isinstance(state, NDArray) else state

    def _make_step(self):
        names = self.names
        grad_names = [n for n, m in zip(names, self.grad_mask) if m]

        cdt = self.compute_dtype
        mesh = self.mesh
        grad_specs = None
        pin_rep = None
        if mesh is not None and self.shard_policy != "replicated":
            # The bit-identity fence (see parallel.zero.pin_replicated):
            # params entering the forward and gradients leaving the
            # backward are pinned replicated so the sharded state inputs
            # cannot repartition the fwd/bwd math. Sharding then lives
            # only in the elementwise update; the new weights *settle
            # into the state layout* after the first step (GSPMD
            # propagates it through the update), which is exact, saves
            # param bytes too, and costs one extra compile at step 2.
            from .parallel import zero as _zero

            def pin_rep(tree):
                return _zero.pin_replicated(tree, mesh)

            if self.shard_policy == "zero2":
                # zero2: additionally constrain each pinned gradient to
                # the same largest-divisible-axis layout its optimizer
                # state uses, so the update consumes only the local
                # shard and the full gradient dies right after the
                # slice (a layout constraint — values unchanged)
                n_dev = mesh.shape["data"]
                grad_specs = [
                    _zero.largest_axis_spec(tuple(d.shape), n_dev)
                    for d, m in zip(self._params, self.grad_mask) if m]
                _shard_grads = _zero.shard_grads

        def forward(grad_params, other_params, x, y, key):
            if cdt is not None:
                # bf16 compute against f32 master weights: cast floating
                # params and data; BN aux stats stay f32 (other_params)
                grad_params = [d.astype(cdt)
                               if jnp.issubdtype(d.dtype, jnp.floating) else d
                               for d in grad_params]
                if jnp.issubdtype(x.dtype, jnp.floating):
                    x = x.astype(cdt)
            mapping = {}
            for n, d in zip(grad_names, grad_params):
                mapping[n] = NDArray._from_data(d)
            for n, d in other_params.items():
                mapping[n] = NDArray._from_data(d)
            prev_t = autograd.set_training(True)
            prev_r = autograd.set_recording(False)
            try:
                with _ParamSubst(mapping), _global_random.key_override(key):
                    loss = self.loss_fn(self.net, NDArray._from_data(x), NDArray._from_data(y))
            finally:
                autograd.set_training(prev_t)
                autograd.set_recording(prev_r)
            # loss reduction in at least f32 (a bf16 batch-mean loses
            # precision in exactly the scalar people monitor); promoted,
            # not pinned, so float64 nets keep an f64 loss
            ldt = jnp.promote_types(loss._data.dtype, jnp.float32)
            loss_data = jnp.mean(loss._data.astype(ldt))
            # aux state updates (BN running stats) show up as rebound arrays
            aux_new = {
                n: mapping[n]._data
                for n in other_params
                if mapping[n]._data is not other_params[n]
            }
            return loss_data, aux_new

        forward_scan = forward
        if self.remat and self.remat_policy:
            # policy-selective remat: the named policy decides which
            # intermediates are saved (e.g. 'convs' keeps conv and
            # matmul results, recomputing only cheap elementwise in the
            # backward) — strictly less recompute AND less traffic than
            # the blanket checkpoint below on bandwidth-bound programs.
            policy = resolve_remat_policy(self.remat_policy)
            forward_scan = jax.checkpoint(forward, policy=policy,
                                          prevent_cse=False)
            forward = jax.checkpoint(forward, policy=policy)
        elif self.remat:
            # recompute the forward during backward instead of saving
            # activations (identical numerics, ~1/3 more FLOPs, far less
            # HBM) — applied to the WHOLE net forward; XLA still fuses
            # inside each recomputation. The accum scan body gets the
            # barrier-free variant (prevent_cse=False is documented safe
            # under scan and avoids optimization-barrier ops); `step`
            # keeps the default because the same function is jitted
            # standalone (scan_steps reuses step inside its scan, where
            # the barrier is merely conservative).
            forward_scan = jax.checkpoint(forward, prevent_cse=False)
            forward = jax.checkpoint(forward)

        def step(params, states, x, y, key, lr, t):
            grad_params = [d for d, m in zip(params, self.grad_mask) if m]
            other_params = {
                n: d for n, d, m in zip(names, params, self.grad_mask) if not m
            }
            if pin_rep is not None:
                grad_params = pin_rep(grad_params)
                other_params = pin_rep(other_params)
            (loss, aux_new), grads = jax.value_and_grad(forward, has_aux=True)(
                grad_params, other_params, x, y, key
            )
            if pin_rep is not None:
                grads = pin_rep(grads)
            if grad_specs is not None:
                grads = _shard_grads(grads, mesh, grad_specs)
            new_params, new_states = [], []
            gi = 0
            for i, (n, d, m) in enumerate(zip(names, params, self.grad_mask)):
                if m:
                    w, st = self.opt.fused_update(n, d, grads[gi], states[i],
                                                  lr, t=t)
                    gi += 1
                    # pin param/state dtypes: the f32 lr/hyperparam scalars
                    # promote bf16 update math to f32 (the right accumulation
                    # discipline), but the OUTPUT must keep the input dtype
                    # or the scan_steps carry (params/states thread through
                    # a lax.scan) fails to typecheck for bf16-cast nets
                    new_params.append(w.astype(d.dtype))
                    new_states.append(_cast_like(st, states[i]))
                else:
                    new_params.append(aux_new.get(n, d))
                    new_states.append(None)
            return loss, new_params, new_states

        def accum(params, states, xs, ys, keys, lr, t):
            """K micro-batches -> ONE optimizer update, one device program.

            Gradients SUM over micro-batches (set rescale_grad to
            1/(micro_batch * K) for a mean over the effective batch —
            the reference's grad_req='add' accumulation contract); BN aux
            stats update every micro-batch, threaded through the scan
            carry."""
            grad_params = [d for d, m in zip(params, self.grad_mask) if m]
            other_params = {
                n: d for n, d, m in zip(names, params, self.grad_mask) if not m
            }
            if pin_rep is not None:
                grad_params = pin_rep(grad_params)
                other_params = pin_rep(other_params)

            def body(carry, inp):
                others, gsum, lsum = carry
                x, y, key = inp
                (loss, aux_new), grads = jax.value_and_grad(
                    forward_scan, has_aux=True)(grad_params, others, x, y,
                                                key)
                if pin_rep is not None:
                    grads = pin_rep(grads)
                if grad_specs is not None:
                    # shard inside the scan: the micro-batch accumulator
                    # itself lives 1/N per device (sum of slices ==
                    # slice of sum, so accumulation order is untouched)
                    grads = _shard_grads(grads, mesh, grad_specs)
                others = {**others, **aux_new}
                gsum = [a + g for a, g in zip(gsum, grads)]
                return (others, gsum, lsum + loss.astype(lsum.dtype)), None

            zero_g = [jnp.zeros_like(d) for d in grad_params]
            # loss accumulator in the same promoted dtype forward() emits
            # (>= f32; f64 for float64 nets), so the f64 path keeps an f64
            # loss through accumulation too
            float_dts = [d.dtype for d in grad_params
                         if jnp.issubdtype(d.dtype, jnp.floating)]
            acc_dt = jnp.promote_types(
                jnp.result_type(*float_dts) if float_dts else jnp.float32,
                jnp.float32)
            (others_f, gsum, lsum), _ = jax.lax.scan(
                body, (other_params, zero_g, jnp.zeros((), acc_dt)),
                (xs, ys, keys))
            new_params, new_states = [], []
            gi = 0
            for i, (n, d, m) in enumerate(zip(names, params, self.grad_mask)):
                if m:
                    w, st = self.opt.fused_update(n, d, gsum[gi], states[i],
                                                  lr, t=t)
                    gi += 1
                    new_params.append(w.astype(d.dtype))
                    new_states.append(_cast_like(st, states[i]))
                else:
                    new_params.append(others_f.get(n, d))
                    new_states.append(None)
            return lsum / xs.shape[0], new_params, new_states

        self._accum_fn = accum
        return step

    def __call__(self, x, y):
        if not self._built:
            self._build(
                x if isinstance(x, NDArray) else NDArray(jnp.asarray(x)),
                y if isinstance(y, NDArray) else NDArray(jnp.asarray(y)),
            )
        xd = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        yd = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        with _stepstats.phase("h2d"):
            if self._data_sharding is not None:
                xd = jax.device_put(xd, self._data_sharding)
                yd = jax.device_put(yd, self._data_sharding)
            elif self.device is not None:
                xd = jax.device_put(xd, self.device)
                yd = jax.device_put(yd, self.device)
        key = _global_random.next_key()
        self._n += 1
        self.opt.num_update = self._n
        lr = self.opt.lr_scheduler(self._n) if self.opt.lr_scheduler else self.opt.lr
        sig = None
        telem = _telemetry.enabled()
        if telem and not getattr(self._step, "is_cached", False):
            # the persistent-cache wrapper does its own registration
            # (cached hits must NOT count as compile events); this
            # dispatch-timing fallback covers the plain-jit path only
            sig = ((tuple(xd.shape), str(xd.dtype)),
                   (tuple(yd.shape), str(yd.dtype)))
            first = not _compilereg.seen("GluonTrainStep.step", sig)
            t0 = _time.perf_counter()
        old_states = self._states if telem else None
        with _stepstats.phase("dispatch"):
            loss, self._params, self._states = self._step(
                self._params, self._states, xd, yd, key,
                jnp.asarray(lr, jnp.float32),
                jnp.asarray(float(self._n), jnp.float32),
            )
        if telem:
            self._retrack_states(old_states)
        if sig is not None:
            # a first-seen batch signature means this dispatch traced and
            # compiled; any later new signature is a retrace (the event
            # the persistent compile cache exists to eliminate)
            _compilereg.register(
                "GluonTrainStep.step", sig,
                compile_s=(_time.perf_counter() - t0) if first else None)
        if telem:
            _stepstats.step_end()
        return NDArray._from_data(loss)

    def scan_steps(self, xs, ys):
        """Run K training steps as ONE device program: `lax.scan` over the
        leading axis of pre-staged batches, params/states threaded through
        the carry with buffers donated.

        This is the deepest form of the reference's bulked execution
        (MXNET_EXEC_BULK_EXEC_*): zero host work between steps, so device
        throughput is independent of dispatch latency (which dominates on
        remote-attached chips and matters on busy hosts). Feed distinct
        batches stacked on axis 0: xs (K, B, ...), ys (K, B, ...).
        Returns the K per-step losses as one NDArray.
        """
        xd = xs._data if isinstance(xs, NDArray) else jnp.asarray(xs)
        yd = ys._data if isinstance(ys, NDArray) else jnp.asarray(ys)
        if not self._built:
            self._build(NDArray._from_data(xd[0]), NDArray._from_data(yd[0]))
        k = int(xd.shape[0])
        if self._data_sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            stacked = NamedSharding(self.mesh, P(None, "data"))
            xd = jax.device_put(xd, stacked)
            yd = jax.device_put(yd, stacked)
        elif self.device is not None:
            xd = jax.device_put(xd, self.device)
            yd = jax.device_put(yd, self.device)
        keys = jnp.stack([_global_random.next_key() for _ in range(k)])
        lrs, ts = [], []
        for _ in range(k):
            self._n += 1
            lrs.append(self.opt.lr_scheduler(self._n)
                       if self.opt.lr_scheduler else self.opt.lr)
            ts.append(float(self._n))
        self.opt.num_update = self._n
        telem = _telemetry.enabled()
        old_states = self._states if telem else None
        losses, self._params, self._states = self._scan(
            self._params, self._states, xd, yd, keys,
            jnp.asarray(lrs, jnp.float32), jnp.asarray(ts, jnp.float32))
        if telem:
            self._retrack_states(old_states)
        return NDArray._from_data(losses)

    def accum_steps(self, xs, ys):
        """K micro-batches -> ONE optimizer update (gradient accumulation)
        as one device program: grads sum over the K forward/backwards
        (lax.scan), then the optimizer applies once. The big-effective-
        batch analog of the reference's grad_req='add' workflow — set
        rescale_grad = 1/(micro_batch*K) for a mean over the effective
        batch. xs: (K, B, ...), ys: (K, ...). Returns the mean loss."""
        xd = xs._data if isinstance(xs, NDArray) else jnp.asarray(xs)
        yd = ys._data if isinstance(ys, NDArray) else jnp.asarray(ys)
        if not self._built:
            self._build(NDArray._from_data(xd[0]), NDArray._from_data(yd[0]))
        k = int(xd.shape[0])
        if self._data_sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            stacked = NamedSharding(self.mesh, P(None, "data"))
            xd = jax.device_put(xd, stacked)
            yd = jax.device_put(yd, stacked)
        elif self.device is not None:
            xd = jax.device_put(xd, self.device)
            yd = jax.device_put(yd, self.device)
        keys = jnp.stack([_global_random.next_key() for _ in range(k)])
        self._n += 1  # ONE update
        self.opt.num_update = self._n
        lr = (self.opt.lr_scheduler(self._n) if self.opt.lr_scheduler
              else self.opt.lr)
        telem = _telemetry.enabled()
        old_states = self._states if telem else None
        loss, self._params, self._states = self._accum(
            self._params, self._states, xd, yd, keys,
            jnp.asarray(lr, jnp.float32),
            jnp.asarray(float(self._n), jnp.float32))
        if telem:
            self._retrack_states(old_states)
        return NDArray._from_data(loss)

    def save_states(self, fname):
        """Serialize optimizer states + the update count for resume (the
        fused path's Trainer.save_states). Parameters travel separately
        via sync_params() + net.save_parameters; this file carries the
        optimizer side only."""
        import pickle

        if not self._built:
            raise RuntimeError("save_states before the first step: "
                               "optimizer states do not exist yet")
        states_np = jax.tree_util.tree_map(jax.device_get, self._states)
        with open(fname, "wb") as f:
            pickle.dump({"n": self._n, "states": states_np}, f)

    def load_states(self, fname):
        """Restore optimizer states saved by save_states. May be called
        before or after the first step; placements (including sharded
        optimizer states) follow the step's current configuration."""
        import pickle

        with open(fname, "rb") as f:
            d = pickle.load(f)
        self._n = int(d["n"])
        self.opt.num_update = self._n
        if self._built:
            self._states = jax.tree_util.tree_map(
                lambda cur, new: jax.device_put(jnp.asarray(new),
                                                cur.sharding)
                if hasattr(cur, "sharding") else new,
                self._states, d["states"])
        else:
            self._pending_states = d["states"]

    def memory_stats(self, x, y, name="train_step"):
        """Compile-time device memory breakdown of the fused step (the
        storage-profiler answer: per-program HBM from XLA's own analysis,
        recorded into profiler.dumps_memory())."""
        from . import profiler
        from . import random as _rng_mod

        if not self._built:
            self._build(
                x if isinstance(x, NDArray) else NDArray(jnp.asarray(x)),
                y if isinstance(y, NDArray) else NDArray(jnp.asarray(y)),
            )
        xd = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        yd = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        return profiler.memory_analysis(
            self._step, self._params, self._states, xd, yd,
            _rng_mod.next_key(), jnp.asarray(self.opt.lr, jnp.float32),
            jnp.asarray(1.0, jnp.float32), name=name)

    def cost_stats(self, x, y):
        """XLA cost-model totals (flops, bytes accessed) of the compiled
        single-step program — the bytes/step number bench.py records next
        to img/s. Lowers against abstract shapes (no donated buffer is
        touched); with the persistent compilation cache the re-lower is a
        cache hit. Returns {} when the backend exposes no cost model."""
        if not self._built:
            self._build(
                x if isinstance(x, NDArray) else NDArray(jnp.asarray(x)),
                y if isinstance(y, NDArray) else NDArray(jnp.asarray(y)),
            )
        xd = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        yd = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        try:
            abstract = _compile_cache.abstractify(
                (self._params, self._states, xd, yd,
                 jnp.zeros((2,), jnp.uint32),
                 jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)))
            if getattr(self._step, "is_cached", False):
                # cache-resolved: a warm process reads the executable
                # from disk (and registers a cached hit, not a compile)
                ca = self._step.aot_compile(*abstract).cost_analysis()
            else:
                ca = self._step.lower(*abstract).compile().cost_analysis()
            if isinstance(ca, list):  # older jax returns [dict]
                ca = ca[0]
            res = {"flops": float(ca.get("flops", 0.0)),
                   "bytes_accessed": float(ca.get("bytes accessed", 0.0))}
            if res and _telemetry.enabled():
                if getattr(self._step, "is_cached", False):
                    sigd = _compile_cache.abstract_signature(abstract)
                else:
                    sigd = ((tuple(xd.shape), str(xd.dtype)),
                            (tuple(yd.shape), str(yd.dtype)))
                    _compilereg.register("GluonTrainStep.step", sigd)
                _compilereg.annotate("GluonTrainStep.step", signature=sigd,
                                     cost=res)
            return res
        except Exception:  # no cost model on this backend/runtime
            return {}

    def warmup(self, x, y):
        """AOT-precompile the fused train step for (x, y)-shaped batches
        into the persistent compile cache without executing a step (no
        param/state buffer is touched or donated) — `tools/warmup.py`'s
        entry point. Abstract args keep the live buffers' committed
        shardings, so the entry written here is the exact one the first
        real step will look up. Returns the cache resolution status:
        "hit" (already on disk), "miss" (compiled and persisted), "memo"
        (already resolved in this process), or "disabled" (no
        MXTPU_COMPILE_CACHE_DIR configured)."""
        if not self._built:
            self._build(
                x if isinstance(x, NDArray) else NDArray(jnp.asarray(x)),
                y if isinstance(y, NDArray) else NDArray(jnp.asarray(y)),
            )
        xd = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        yd = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        if self._data_sharding is not None:
            xd = jax.device_put(xd, self._data_sharding)
            yd = jax.device_put(yd, self._data_sharding)
        elif self.device is not None:
            xd = jax.device_put(xd, self.device)
            yd = jax.device_put(yd, self.device)
        if not getattr(self._step, "is_cached", False):
            return "disabled"
        abstract = _compile_cache.abstractify(
            (self._params, self._states, xd, yd,
             jnp.zeros((2,), jnp.uint32),
             jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)))
        return self._step.warm(*abstract)

    def sync_params(self):
        """Write current param values back into the net's Parameters."""
        for p, d in zip(self.param_objs, self._params):
            p._data._data = d
