"""Runtime feature detection (ref: src/libinfo.cc, python/mxnet/runtime.py)."""
from __future__ import annotations

import collections

import jax

__all__ = ["Feature", "feature_list", "Features"]

Feature = collections.namedtuple("Feature", ["name", "enabled"])


def _detect():
    feats = {
        "TPU": False, "CPU": True, "XLA": True, "PALLAS": True,
        "BF16": True, "F16C": True, "INT64_TENSOR_SIZE": True,
        "DIST_KVSTORE": True, "OPENCV": False, "BLAS_OPEN": True,
        "SIGNAL_HANDLER": False, "PROFILER": True,
    }
    try:
        feats["TPU"] = any(d.platform != "cpu" for d in jax.devices())
    except RuntimeError:
        pass
    try:
        import cv2  # noqa: F401

        feats["OPENCV"] = True
    except ImportError:
        pass
    return feats


def feature_list():
    return [Feature(k, v) for k, v in _detect().items()]


class Features(dict):
    def __init__(self):
        super().__init__({f.name: f for f in feature_list()})

    def is_enabled(self, name):
        return self[name].enabled
