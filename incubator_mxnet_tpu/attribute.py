"""Attribute scopes for symbols (ref: python/mxnet/attribute.py —
AttrScope:26). `with mx.AttrScope(ctx_group="dev1", lr_mult="0.1"):` stamps
the given attributes onto every symbol (and auto-created weight variable)
built inside the scope; nested scopes merge, inner keys winning.

Scope state lives on a per-thread stack (never on the scope object), so
one AttrScope instance can be entered repeatedly — even nested within
itself — without corrupting later symbol builds, and a scope active in
one thread is invisible to others."""
from __future__ import annotations

__all__ = ["AttrScope", "current"]

from .base import ThreadLocalStack

# (scope_object, effective_attrs) frames per thread; effective = all
# enclosing scopes merged, inner keys winning
_STACK = ThreadLocalStack()


class AttrScope:
    def __init__(self, **kwargs):
        self._attr = dict(kwargs)

    def get(self, attr=None):
        """This scope's attributes merged with explicit `attr`
        (explicit wins)."""
        out = self._attr.copy()
        if attr:
            out.update(attr)
        return out

    def __enter__(self):
        top = _STACK.top()
        parent = top[1] if top else {}
        merged = {**parent, **self._attr}
        _STACK.push((self, merged))
        return self

    def __exit__(self, exc_type, exc_value, traceback):
        _STACK.pop()


def current():
    """The innermost active scope in this thread, or None."""
    top = _STACK.top()
    return top[0] if top else None


def resolve(attr=None):
    """Attributes the active scopes assign, merged with `attr`
    (explicit wins). lr_mult/wd_mult get their dunder twins — the
    spelling Optimizer.set_lr_mult/set_wd_mult read from attr_dict."""
    top = _STACK.top()
    effective = top[1] if top else None
    if not effective:
        out = dict(attr) if attr else {}
    else:
        out = effective.copy()
        if attr:
            out.update(attr)
    for mult in ("lr_mult", "wd_mult"):
        if mult in out and f"__{mult}__" not in out:
            out[f"__{mult}__"] = out[mult]
    return out
