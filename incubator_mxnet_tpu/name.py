"""Automatic symbol naming scopes (ref: python/mxnet/name.py —
NameManager:25, Prefix:74). `with mx.name.Prefix("layer1_"):` prepends the
prefix to every auto-generated (and explicit) symbol name created in the
scope; a plain NameManager scope restarts hint counters from 0.

The active-manager state lives on a per-thread stack so one manager
object can be entered repeatedly (even nested within itself) without
leaving the scope permanently active, and scopes do not leak across
threads."""
from __future__ import annotations

from .symbol.symbol import name_uid

__all__ = ["NameManager", "Prefix", "current"]

from .base import ThreadLocalStack

_STACK = ThreadLocalStack()  # per-thread active-manager stack


class NameManager:
    """Scope that turns `hint`s into unique names. Each manager instance
    counts per hint from zero; entering pushes it as the active scope."""

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        """Resolve a symbol name: an explicit `name` wins, else
        `hint<N>` with this manager's counter."""
        if name:
            return name
        n = self._counter.get(hint, 0)
        self._counter[hint] = n + 1
        return f"{hint}{n}"

    def __enter__(self):
        _STACK.push(self)
        return self

    def __exit__(self, exc_type, exc_value, traceback):
        _STACK.pop()


class Prefix(NameManager):
    """NameManager that prepends `prefix` to every resolved name."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)


def current():
    """The innermost active manager in this thread, or None."""
    return _STACK.top()


def resolve(name, hint):
    """Active-scope name resolution; without a scope, fall back to the
    process-global per-hint uid counters (stable auto-names like
    `slicechannel0` across managers)."""
    mgr = current()
    if mgr is not None:
        return mgr.get(name, hint)
    return name or name_uid(hint)
