"""DLRM-style recommender: dense MLP tower + per-field sparse embedding
arm + pairwise feature interaction (ref: the reference's sparse/ criteo
examples — linear_classification/wide-and-deep over dist_async kvstore —
modernized to the DLRM interaction layout those pipelines evolved into).

The sparse arm is the terascale part: each categorical field owns a
PS-row-sharded table (`embedding.ShardedEmbeddingService`), and one step
pulls EVERY field's deduped, bucket-padded unique rows with a single
multi-table RPC per shard server — at most `num_shards` pull RPCs per
step for the whole model, vs fields × shards on the naive per-key wire
(`per_key=True`, the recommender bench's baseline). Worker-resident
embedding state stays O(batch uniques); with `service=None` the arm
falls back to local sparse-grad Embedding blocks and the model is
self-contained.
"""
from __future__ import annotations

import numpy as np

from .. import autograd as _ag
from .. import ndarray as nd
from ..gluon import nn
from ..gluon.block import Block
from ..gluon.contrib.nn import SparseEmbedding

__all__ = ["DLRM"]


class _SparseArm(Block):
    """F per-field tables on one service; forward turns an id batch
    (B, F) into field embeddings (B, F, E) with ONE multi-table pull."""

    def __init__(self, service, field_vocabs, dim, table_prefix, scale,
                 seed, per_key, **kwargs):
        super().__init__(**kwargs)
        self._service = service
        self._dim = int(dim)
        self._per_key = bool(per_key)
        self._tables = [
            service.table(f"{table_prefix}f{i}", v, dim, scale=scale,
                          seed=seed + i)
            for i, v in enumerate(field_vocabs)]

    def _requests(self, ids):
        ids = np.asarray(ids, np.int64)
        return [(t.name, ids[:, i]) for i, t in enumerate(self._tables)]

    def prefetch(self, ids):
        if not self._per_key:
            self._service.prefetch(self._requests(ids))

    def forward(self, ids):
        from ..embedding import LEDGER_ROLE
        from ..telemetry import ledger as _ledger

        ids = np.asarray(ids.asnumpy() if hasattr(ids, "asnumpy") else ids,
                         np.int64)
        b = ids.shape[0]
        requests = self._requests(ids)
        if self._per_key:
            pulled = [self._service.pull_per_key(name, raw)
                      for name, raw in requests]
        else:
            blocks, plan = self._service.pull(requests)
            pulled = [(blk, inv, n)
                      for blk, (_name, inv, n, _ids) in zip(blocks, plan)]
        outs = []
        for (name, raw), (block, inv, n_uniq) in zip(requests, pulled):
            rows_nd = nd.array(block)
            _ledger.track(rows_nd, LEDGER_ROLE)
            if _ag.is_recording():
                _ag.mark_variables(
                    [rows_nd], [nd.zeros(block.shape, dtype=block.dtype)])
                self._service.stash_grad(name, np.unique(raw), rows_nd,
                                         n_uniq)
            out = nd.Embedding(nd.array(inv.astype(np.int32)), rows_nd,
                               input_dim=int(block.shape[0]),
                               output_dim=self._dim)
            outs.append(out.reshape((b, 1, self._dim)))
        return nd.concat(*outs, dim=1)


class _LocalArm(Block):
    """service=None fallback: per-field local sparse-grad embeddings."""

    def __init__(self, field_vocabs, dim, **kwargs):
        super().__init__(**kwargs)
        self._dim = int(dim)
        with self.name_scope():
            for i, v in enumerate(field_vocabs):
                self.register_child(SparseEmbedding(v, dim), f"f{i}")

    def prefetch(self, ids):
        pass

    def forward(self, ids):
        if not hasattr(ids, "asnumpy"):
            ids = nd.array(np.asarray(ids, np.int64))
        b = int(ids.shape[0])
        outs = [emb(ids[:, i]).reshape((b, 1, self._dim))
                for i, emb in enumerate(self._children.values())]
        return nd.concat(*outs, dim=1)


class DLRM(Block):
    """`forward(dense_x, sparse_ids)` -> logits (B, 1).

    dense_x: (B, num_dense) float features -> bottom MLP -> (B, embed_dim).
    sparse_ids: (B, num_fields) int ids, field f in [0, field_vocabs[f]).
    Interaction: the bottom output joins the field embeddings as an extra
    "field" and all pairwise dot products (flattened (F+1)^2 Gram matrix)
    concat with the bottom output into the top MLP.
    """

    def __init__(self, field_vocabs, num_dense=4, embed_dim=8,
                 bottom_units=(32, 16), top_units=(32, 16), service=None,
                 per_key=False, table_prefix="dlrm_", scale=0.05, seed=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.field_vocabs = tuple(int(v) for v in field_vocabs)
        self.num_fields = len(self.field_vocabs)
        self.embed_dim = int(embed_dim)
        with self.name_scope():
            if service is not None:
                self.sparse_arm = _SparseArm(
                    service, self.field_vocabs, embed_dim, table_prefix,
                    scale, seed, per_key)
            else:
                self.sparse_arm = _LocalArm(self.field_vocabs, embed_dim)
            self.bottom = nn.HybridSequential()
            for u in bottom_units:
                self.bottom.add(nn.Dense(u, activation="relu"))
            # the bottom tower must land in embedding space to join the
            # interaction as an extra field
            self.bottom.add(nn.Dense(self.embed_dim, activation="relu"))
            self.top = nn.HybridSequential()
            for u in top_units:
                self.top.add(nn.Dense(u, activation="relu"))
            self.top.add(nn.Dense(1))

    def prefetch(self, sparse_ids):
        """Enqueue the NEXT batch's row pulls on the service's background
        worker (no-op in local/per-key mode)."""
        self.sparse_arm.prefetch(
            sparse_ids.asnumpy() if hasattr(sparse_ids, "asnumpy")
            else sparse_ids)

    def forward(self, dense_x, sparse_ids):
        emb = self.sparse_arm(sparse_ids)             # (B, F, E)
        bot = self.bottom(dense_x)                    # (B, E)
        b = int(emb.shape[0])
        z = nd.concat(bot.reshape((b, 1, self.embed_dim)), emb, dim=1)
        gram = nd.batch_dot(z, z, transpose_b=True)   # (B, F+1, F+1)
        feats = nd.concat(bot, gram.reshape((b, -1)), dim=1)
        return self.top(feats)
