"""Transformer LM flagship: every parallelism axis on one model.

Capability beyond the reference (SURVEY §2.2/§5.7: MXNet's long-sequence
story was bucketing + the fused RNN op; TP/PP/SP/EP were absent). This is the
TPU-native composition point for the `parallel` package:

- data parallel       : batch sharded on the `dp` mesh axis (GSPMD or shard_map)
- tensor parallel     : attention heads + FFN hidden sharded on `tp` (GSPMD
                        sharding rules, parallel.tensor)
- expert parallel     : MoE expert axis sharded on `ep` (parallel.moe)
- sequence parallel   : ring attention over `sp` (parallel.ring_attention)
- pipeline parallel   : layer stack sharded on `pp` (parallel.pipeline)

Two jitted training steps are provided:
- `make_gspmd_train_step`   — mesh ('dp','ep','tp'): annotation-driven
  sharding; XLA inserts the grad all-reduce and MoE all-to-all.
- `make_pipeline_train_step`— mesh ('dp','sp','pp'): explicit shard_map SPMD
  pipeline with ring attention inside each stage.

Both return scalar loss and apply an SGD update in the same jit (donated
params — the fused-step pattern of incubator_mxnet_tpu.fused).
"""
from __future__ import annotations

import dataclasses
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.pipeline import spmd_pipeline
from ..parallel.moe import moe_ffn
from ..parallel.ring_attention import ring_attention
from ..parallel.tensor import make_shardings

__all__ = [
    "TransformerConfig",
    "init_params",
    "apply",
    "make_gspmd_train_step",
    "make_pipeline_train_step",
    "init_kv_cache",
    "decode_step",
    "prefill",
    "generate",
    "beam_search",
    "init_paged_kv_cache",
    "decode_step_paged",
    "prefill_paged",
    "decode_step_paged_wide",
]


@dataclasses.dataclass
class TransformerConfig:
    vocab: int = 256
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 128
    max_len: int = 128
    n_experts: int = 0  # 0 = dense FFN
    dtype: str = "float32"
    use_flash: bool = False  # Pallas flash-attention kernels for attention
    use_fused_xent: bool = False  # Pallas fused softmax-xent loss kernel


def init_params(cfg: TransformerConfig, seed: int = 0):
    """Stacked-layer parameter dict: every per-layer tensor has a leading
    (n_layers,) axis so the stack can be scanned (single-chip) or sharded on
    `pp` (pipeline)."""
    rng = np.random.RandomState(seed)
    d, f, L, V = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.vocab
    dt = cfg.dtype

    def W(*shape, scale=None):
        scale = scale if scale is not None else 1.0 / np.sqrt(shape[-2])
        return jnp.asarray(rng.randn(*shape).astype(dt) * scale)

    p = {
        "embed": W(V, d, scale=0.02),
        "pos": W(cfg.max_len, d, scale=0.02),
        "ln_f_g": jnp.ones((d,), dt),
        "ln_f_b": jnp.zeros((d,), dt),
        "wq": W(L, d, d),
        "wk": W(L, d, d),
        "wv": W(L, d, d),
        "wo": W(L, d, d),
        "ln1_g": jnp.ones((L, d), dt),
        "ln1_b": jnp.zeros((L, d), dt),
        "ln2_g": jnp.ones((L, d), dt),
        "ln2_b": jnp.zeros((L, d), dt),
    }
    if cfg.n_experts:
        p["router"] = W(L, d, cfg.n_experts, scale=0.02)
        p["w1"] = W(L, cfg.n_experts, d, f)
        p["w2"] = W(L, cfg.n_experts, f, d, scale=1.0 / np.sqrt(f))
    else:
        p["w1"] = W(L, d, f)
        p["w2"] = W(L, f, d, scale=1.0 / np.sqrt(f))
    return p


_NON_STACKED = ("embed", "pos", "ln_f_g", "ln_f_b")


def _stack_keys(params):
    """Keys of per-layer (stacked, leading n_layers axis) params — the single
    predicate used by both the scanned forward and the pipeline sharding."""
    return [k for k in params if k not in _NON_STACKED]


def _ln(x, g, b, eps=1e-5):
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * g + b


def _split_heads(x, n_heads):
    B, T, d = x.shape
    return x.reshape(B, T, n_heads, d // n_heads)


FLASH_DENSE_FALLBACKS_TOTAL = "mxtpu_flash_dense_fallbacks_total"
_FLASH_FALLBACKS_HELP = (
    "Training flash-attention calls that fell back to the dense S×S "
    "attention (non-causal sequences that do not tile into blocks — "
    "causal remainders are padded into the Pallas path instead), by site "
    "and reason.")


def _count_flash_dense_fallback(site, reason):
    # trace-time event (shapes are static), so the counter costs nothing
    # on the per-step hot path; lazy import keeps this module jax-only
    # when telemetry is off (same idiom as pallas_kernels flash_decode)
    from .. import telemetry

    telemetry.inc(FLASH_DENSE_FALLBACKS_TOTAL, help=_FLASH_FALLBACKS_HELP,
                  site=site, reason=reason)


def _flash_attention_fn(q, k, v, causal=True, block=128):
    """Adapter onto the Pallas flash kernels (ops/pallas_kernels.py):
    model layout (B, T, H, Dh) <-> kernel layout (B, H, T, Dh).

    A sequence length that does not tile into blocks no longer silently
    pays the dense S×S path when causal: q/k/v zero-pad along T to the
    next block multiple, the kernel runs, and the output slices back to
    T. Exact because a causal query at t < T never attends a padded key
    at t' >= T (cost: < one block of extra rows). Non-causal remainders
    would let every query see the padded keys, so they still fall back to
    dense — now COUNTED via mxtpu_flash_dense_fallbacks_total instead of
    vanishing from the perf picture."""
    from ..ops.pallas_kernels import flash_attention

    T = q.shape[1]
    blk = min(block, T)
    pad = (-T) % blk
    if pad and not causal:
        _count_flash_dense_fallback("models.transformer",
                                    "non_causal_remainder")
        return _dense_attention(q, k, v, causal)
    if pad:
        widths = ((0, 0), (0, pad), (0, 0), (0, 0))
        q = jnp.pad(q, widths)
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
    out = flash_attention(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                          v.transpose(0, 2, 1, 3), causal=causal,
                          block_q=blk, block_k=blk)
    out = out.transpose(0, 2, 1, 3)
    return out[:, :T] if pad else out


def _dense_attention(q, k, v, causal=True):
    # q,k,v: (B, T, H, Dh)
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _layer(lp, x, cfg, attn_fn):
    """One transformer block. lp = per-layer param dict (no leading L axis).
    x: (B, T, d). Returns (y, aux_loss)."""
    h = _ln(x, lp["ln1_g"], lp["ln1_b"])
    q = _split_heads(h @ lp["wq"], cfg.n_heads)
    k = _split_heads(h @ lp["wk"], cfg.n_heads)
    v = _split_heads(h @ lp["wv"], cfg.n_heads)
    a = attn_fn(q, k, v)
    B, T, _ = x.shape
    x = x + a.reshape(B, T, cfg.d_model) @ lp["wo"]
    h = _ln(x, lp["ln2_g"], lp["ln2_b"])
    if cfg.n_experts:
        flat = h.reshape(B * T, cfg.d_model)
        out, aux = moe_ffn(flat, lp["router"], lp["w1"], lp["w2"])
        return x + out.reshape(B, T, cfg.d_model), aux
    return x + jax.nn.gelu(h @ lp["w1"]) @ lp["w2"], jnp.zeros((), x.dtype)


def apply(params, tokens, cfg: TransformerConfig, attn_fn=None):
    """Forward pass: tokens (B, T) int32 -> logits (B, T, V). Scans the layer
    stack (compiler-friendly: one compiled block body)."""
    if attn_fn is None:
        attn_fn = _flash_attention_fn if cfg.use_flash else _dense_attention
    x = params["embed"][tokens] + params["pos"][: tokens.shape[1]][None]

    stacked = {k: params[k] for k in _stack_keys(params)}

    def body(carry, lp):
        x, aux = carry
        y, a = _layer(lp, x, cfg, attn_fn)
        return (y, aux + a), None

    (x, aux), _ = lax.scan(body, (x, jnp.zeros((), x.dtype)), stacked)
    x = _ln(x, params["ln_f_g"], params["ln_f_b"])
    logits = x @ params["embed"].T
    return logits, aux / max(cfg.n_layers, 1)


def _xent(logits, targets, fused=False):
    if fused:
        return _xent_fused_local(logits, targets)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]


def _xent_fused_local(logits, targets):
    """Per-device fused loss: Pallas kernel computing max/logsumexp/pick in
    one VMEM pass — no (B, V) softmax tensor in HBM
    (ops/pallas_kernels.softmax_xent)."""
    from ..ops.pallas_kernels import softmax_xent

    return softmax_xent(logits, targets)


# ---------------------------------------------------------------------------
# Incremental decoding: KV cache + one-token steps + jitted generate
# (the reference has no serving path; on TPU the decode loop is a single
# lax.scan program — static shapes, cache updates via dynamic_update_slice)
# ---------------------------------------------------------------------------


def init_kv_cache(cfg: TransformerConfig, batch: int, max_len: int | None = None):
    """Per-layer key/value cache: (L, B, T_max, H, Dh) + a scalar write
    position. Static T_max keeps every decode step the same XLA program.

    T_max is rounded up to a DECODE_BLOCK multiple (when larger than one
    block) so `flash_decode` always tiles — the silent dense fallback on
    untiled caches cost the Pallas path exactly when caches got long
    enough to need it. Extra slots are masked by `n_valid`, so numerics
    are unchanged."""
    from ..ops.pallas_kernels import DECODE_BLOCK

    T = int(max_len or cfg.max_len)
    if T > DECODE_BLOCK and T % DECODE_BLOCK:
        T += DECODE_BLOCK - T % DECODE_BLOCK
    H = cfg.n_heads
    Dh = cfg.d_model // H
    shape = (cfg.n_layers, batch, T, H, Dh)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
        "pos": jnp.zeros((), jnp.int32),
    }


def decode_step(params, cache, tokens, cfg: TransformerConfig):
    """One token through the stack with cached attention state.

    tokens: (B,) int32 — the token at position cache["pos"]. The caller
    must keep pos < the cache's T_max (generate() checks this at trace
    time; past capacity, dynamic_update_slice would silently clamp).
    Returns (logits (B, V), new_cache). Attention reads the full static
    cache and masks positions beyond pos (no dynamic shapes)."""
    B = tokens.shape[0]
    pos = cache["pos"]
    T_max = cache["k"].shape[2]
    x = params["embed"][tokens] + jax.lax.dynamic_index_in_dim(
        params["pos"], pos, axis=0, keepdims=False)  # (B, d)

    stacked = {k: params[k] for k in _stack_keys(params)}

    def body(x, layer_in):
        lp, k_cache, v_cache = layer_in
        h = _ln(x, lp["ln1_g"], lp["ln1_b"])  # (B, d)
        q = (h @ lp["wq"]).reshape(B, cfg.n_heads, -1)
        k = (h @ lp["wk"]).reshape(B, cfg.n_heads, -1)
        v = (h @ lp["wv"]).reshape(B, cfg.n_heads, -1)
        k_cache = lax.dynamic_update_slice_in_dim(
            k_cache, k[:, None].astype(k_cache.dtype), pos,
            axis=1)  # (B, T_max, H, Dh)
        v_cache = lax.dynamic_update_slice_in_dim(
            v_cache, v[:, None].astype(v_cache.dtype), pos, axis=1)
        if cfg.use_flash:
            from ..ops.pallas_kernels import flash_decode

            a = flash_decode(q, k_cache, v_cache, pos + 1)
        else:
            from ..ops.pallas_kernels import dense_decode_attention

            a = dense_decode_attention(q, k_cache, v_cache, pos + 1)
        x = x + a.reshape(B, cfg.d_model) @ lp["wo"]
        h = _ln(x, lp["ln2_g"], lp["ln2_b"])
        if cfg.n_experts:
            out, _ = moe_ffn(h, lp["router"], lp["w1"], lp["w2"])
            x = x + out
        else:
            x = x + jax.nn.gelu(h @ lp["w1"]) @ lp["w2"]
        return x, (k_cache, v_cache)

    x, (new_k, new_v) = lax.scan(body, x, (stacked, cache["k"], cache["v"]))
    x = _ln(x, params["ln_f_g"], params["ln_f_b"])
    logits = x @ params["embed"].T
    new_cache = {"k": new_k, "v": new_v, "pos": pos + 1}
    return logits, new_cache


def prefill(params, cache, prompt, cfg: TransformerConfig):
    """Fill the cache with the whole prompt in ONE batched pass (the
    O(T_p)-sequential decode_step loop would serialize T_p attention
    launches). Returns (cache, last-token logits (B, V))."""
    B, T_p = prompt.shape
    x = params["embed"][prompt] + params["pos"][:T_p][None]
    stacked = {k: params[k] for k in _stack_keys(params)}

    def body(x, layer_in):
        lp, k_cache, v_cache = layer_in
        h = _ln(x, lp["ln1_g"], lp["ln1_b"])
        q = _split_heads(h @ lp["wq"], cfg.n_heads)
        k = _split_heads(h @ lp["wk"], cfg.n_heads)
        v = _split_heads(h @ lp["wv"], cfg.n_heads)
        # cache dtype follows cfg.dtype; activations may be wider (f32
        # master weights) — cast at the cache-write boundary
        k_cache = lax.dynamic_update_slice_in_dim(
            k_cache, k.astype(k_cache.dtype), 0, axis=1)
        v_cache = lax.dynamic_update_slice_in_dim(
            v_cache, v.astype(v_cache.dtype), 0, axis=1)
        a = _dense_attention(q, k, v, causal=True)
        x = x + a.reshape(B, T_p, cfg.d_model) @ lp["wo"]
        h = _ln(x, lp["ln2_g"], lp["ln2_b"])
        if cfg.n_experts:
            flat = h.reshape(B * T_p, cfg.d_model)
            out, _ = moe_ffn(flat, lp["router"], lp["w1"], lp["w2"])
            x = x + out.reshape(B, T_p, cfg.d_model)
        else:
            x = x + jax.nn.gelu(h @ lp["w1"]) @ lp["w2"]
        return x, (k_cache, v_cache)

    x, (new_k, new_v) = lax.scan(body, x, (stacked, cache["k"], cache["v"]))
    h = _ln(x[:, -1], params["ln_f_g"], params["ln_f_b"])
    logits = h @ params["embed"].T
    return {"k": new_k, "v": new_v,
            "pos": jnp.asarray(T_p, jnp.int32)}, logits


# ---------------------------------------------------------------------------
# Paged decoding: K/V in a global page pool shared by every decode slot
# (serving path — the dense cache above burns B x T_max HBM and forces the
# whole batch to one depth; pages + per-slot positions are what continuous
# batching needs: serving/engine.py drives these three functions)
# ---------------------------------------------------------------------------


def init_paged_kv_cache(cfg: TransformerConfig, num_pages: int,
                        page_size: int):
    """Per-layer paged K/V pool: (L, num_pages, page_size, H, Dh). No
    position scalar — slot positions live with the caller (the engine),
    one per decode slot. Page 0 is the null page by convention
    (serving.pages.PageAllocator never hands it out): dead slots and
    padded prefill rows scatter their writes there."""
    H = cfg.n_heads
    Dh = cfg.d_model // H
    shape = (cfg.n_layers, num_pages, page_size, H, Dh)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype)}


def _page_write_index(page_table, positions, page_size):
    """Flat pool row (page * page_size + offset) where each slot's next
    token lands. positions: (S,) — tokens already cached per slot."""
    page = jnp.take_along_axis(
        page_table, (positions // page_size)[:, None], axis=1)[:, 0]
    return page * page_size + positions % page_size


def decode_step_paged(params, paged, tokens, positions, page_table,
                      cfg: TransformerConfig):
    """One token for every decode slot, each at its OWN depth.

    paged: init_paged_kv_cache dict; tokens (S,) int32; positions (S,)
    int32 — tokens already cached per slot (the new token is written at
    that offset, then attention covers positions+1); page_table
    (S, P_max) int32 rows of owned page ids. Dead slots (all-zero table
    row, position 0) write to the null page and produce garbage logits
    the caller discards. Returns (logits (S, V), new_paged). Shapes are
    static in (S, P_max, pool) — every call is one XLA program."""
    S = tokens.shape[0]
    num_pages, page_size = paged["k"].shape[1], paged["k"].shape[2]
    x = params["embed"][tokens] + params["pos"][positions]  # (S, d)
    n_valid = positions + 1
    write_idx = _page_write_index(page_table, positions, page_size)

    stacked = {k: params[k] for k in _stack_keys(params)}

    def body(x, layer_in):
        lp, k_pool, v_pool = layer_in  # (num_pages, page_size, H, Dh)
        h = _ln(x, lp["ln1_g"], lp["ln1_b"])
        q = (h @ lp["wq"]).reshape(S, cfg.n_heads, -1)
        k = (h @ lp["wk"]).reshape(S, cfg.n_heads, -1)
        v = (h @ lp["wv"]).reshape(S, cfg.n_heads, -1)
        flat = (num_pages * page_size,) + k_pool.shape[2:]
        k_pool = k_pool.reshape(flat).at[write_idx].set(
            k.astype(k_pool.dtype)).reshape(k_pool.shape)
        v_pool = v_pool.reshape(flat).at[write_idx].set(
            v.astype(v_pool.dtype)).reshape(v_pool.shape)
        from ..ops.pallas_kernels import paged_decode_attention

        a = paged_decode_attention(q, k_pool, v_pool, page_table, n_valid)
        x = x + a.reshape(S, cfg.d_model) @ lp["wo"]
        h = _ln(x, lp["ln2_g"], lp["ln2_b"])
        if cfg.n_experts:
            out, _ = moe_ffn(h, lp["router"], lp["w1"], lp["w2"])
            x = x + out
        else:
            x = x + jax.nn.gelu(h @ lp["w1"]) @ lp["w2"]
        return x, (k_pool, v_pool)

    x, (new_k, new_v) = lax.scan(body, x, (stacked, paged["k"], paged["v"]))
    x = _ln(x, params["ln_f_g"], params["ln_f_b"])
    logits = x @ params["embed"].T
    return logits, {"k": new_k, "v": new_v}


def prefill_paged(params, paged, prompts, true_lens, page_table,
                  cfg: TransformerConfig):
    """Prefill a BUCKET of prompts straight into their pages in one pass.

    prompts: (S, T_b) int32 padded to the bucket length; true_lens (S,)
    — real prompt length per row (padding rows use 0); page_table
    (S, P_max). Causal attention makes every position < true_len exact
    regardless of the padding tail; padded positions scatter to the null
    page and their activations are never read. Returns (new_paged,
    logits (S, V) at each row's LAST REAL token — the first sampled
    continuation token, matching prefill()'s x[:, -1] for full rows."""
    S, T_b = prompts.shape
    num_pages, page_size = paged["k"].shape[1], paged["k"].shape[2]
    x = params["embed"][prompts] + params["pos"][:T_b][None]
    stacked = {k: params[k] for k in _stack_keys(params)}

    t = jnp.arange(T_b)
    valid = t[None, :] < true_lens[:, None]  # (S, T_b)
    page = jnp.take_along_axis(
        page_table, jnp.broadcast_to((t // page_size)[None], (S, T_b)),
        axis=1)
    write_idx = jnp.where(valid, page * page_size + t[None] % page_size,
                          0).reshape(S * T_b)

    def body(x, layer_in):
        lp, k_pool, v_pool = layer_in
        h = _ln(x, lp["ln1_g"], lp["ln1_b"])
        q = _split_heads(h @ lp["wq"], cfg.n_heads)
        k = _split_heads(h @ lp["wk"], cfg.n_heads)
        v = _split_heads(h @ lp["wv"], cfg.n_heads)
        flat = (num_pages * page_size,) + k_pool.shape[2:]
        kw = k.reshape((S * T_b,) + k.shape[2:]).astype(k_pool.dtype)
        vw = v.reshape((S * T_b,) + v.shape[2:]).astype(v_pool.dtype)
        k_pool = k_pool.reshape(flat).at[write_idx].set(kw).reshape(
            k_pool.shape)
        v_pool = v_pool.reshape(flat).at[write_idx].set(vw).reshape(
            v_pool.shape)
        a = _dense_attention(q, k, v, causal=True)
        x = x + a.reshape(S, T_b, cfg.d_model) @ lp["wo"]
        h = _ln(x, lp["ln2_g"], lp["ln2_b"])
        if cfg.n_experts:
            flat_h = h.reshape(S * T_b, cfg.d_model)
            out, _ = moe_ffn(flat_h, lp["router"], lp["w1"], lp["w2"])
            x = x + out.reshape(S, T_b, cfg.d_model)
        else:
            x = x + jax.nn.gelu(h @ lp["w1"]) @ lp["w2"]
        return x, (k_pool, v_pool)

    x, (new_k, new_v) = lax.scan(body, x, (stacked, paged["k"], paged["v"]))
    last = jnp.maximum(true_lens - 1, 0)  # (S,)
    x_last = jnp.take_along_axis(
        x, last[:, None, None].astype(jnp.int32), axis=1)[:, 0]  # (S, d)
    h = _ln(x_last, params["ln_f_g"], params["ln_f_b"])
    logits = h @ params["embed"].T
    return {"k": new_k, "v": new_v}, logits


def decode_step_paged_wide(params, paged, tokens, start, n_real, page_table,
                           cfg: TransformerConfig):
    """Q consecutive tokens per decode slot in ONE pass — the wider-query
    decode program behind three serving levers: chunked prefill
    (Q = chunk size, carrying the running position in `start`),
    cached-prefix tail prefill (`start` = tokens mapped from the prefix
    cache), and n-gram speculative verification (Q = lookahead + 1,
    accepted prefixes advance positions in bulk).

    tokens: (S, Q) int32 — token j of row s sits at position
    start[s] + j; start: (S,) int32 — tokens already cached per slot;
    n_real: (S,) int32 — rows write K/V only for j < n_real (tokens
    beyond scatter to the null page: chunk-tail padding, dead slots).
    Attention for query j covers positions < start[s] + j + 1 — the
    paged prefix written by earlier calls plus intra-call causal — via
    ops.pallas_kernels.paged_decode_attention_wide. Positions past the
    page table's capacity or the positional table also land on the null
    page (speculative rows may run past a sequence's last owned page;
    their outputs are discarded by the caller).

    Returns (logits (S, Q, V), new_paged). Shapes are static in
    (S, Q, P_max, pool) — every call is one XLA program."""
    S, Q = tokens.shape
    num_pages, page_size = paged["k"].shape[1], paged["k"].shape[2]
    j = jnp.arange(Q, dtype=jnp.int32)
    pos = start[:, None] + j[None, :]  # (S, Q) global positions
    cap = min(page_table.shape[1] * page_size, params["pos"].shape[0])
    writable = (j[None, :] < n_real[:, None]) & (pos < cap)
    safe_pos = jnp.where(pos < cap, pos, 0)
    x = params["embed"][tokens] + params["pos"][safe_pos]  # (S, Q, d)
    page = jnp.take_along_axis(page_table, safe_pos // page_size, axis=1)
    write_idx = jnp.where(
        writable, page * page_size + safe_pos % page_size, 0
    ).reshape(S * Q)

    stacked = {k: params[k] for k in _stack_keys(params)}

    def body(x, layer_in):
        lp, k_pool, v_pool = layer_in
        h = _ln(x, lp["ln1_g"], lp["ln1_b"])
        q = _split_heads(h @ lp["wq"], cfg.n_heads)  # (S, Q, H, Dh)
        k = _split_heads(h @ lp["wk"], cfg.n_heads)
        v = _split_heads(h @ lp["wv"], cfg.n_heads)
        flat = (num_pages * page_size,) + k_pool.shape[2:]
        kw = k.reshape((S * Q,) + k.shape[2:]).astype(k_pool.dtype)
        vw = v.reshape((S * Q,) + v.shape[2:]).astype(v_pool.dtype)
        k_pool = k_pool.reshape(flat).at[write_idx].set(kw).reshape(
            k_pool.shape)
        v_pool = v_pool.reshape(flat).at[write_idx].set(vw).reshape(
            v_pool.shape)
        from ..ops.pallas_kernels import paged_decode_attention_wide

        a = paged_decode_attention_wide(q, k_pool, v_pool, page_table,
                                        start)
        x = x + a.reshape(S, Q, cfg.d_model) @ lp["wo"]
        h = _ln(x, lp["ln2_g"], lp["ln2_b"])
        if cfg.n_experts:
            flat_h = h.reshape(S * Q, cfg.d_model)
            out, _ = moe_ffn(flat_h, lp["router"], lp["w1"], lp["w2"])
            x = x + out.reshape(S, Q, cfg.d_model)
        else:
            x = x + jax.nn.gelu(h @ lp["w1"]) @ lp["w2"]
        return x, (k_pool, v_pool)

    x, (new_k, new_v) = lax.scan(body, x, (stacked, paged["k"], paged["v"]))
    x = _ln(x, params["ln_f_g"], params["ln_f_b"])
    logits = x @ params["embed"].T
    return logits, {"k": new_k, "v": new_v}


def _filter_logits(logits, top_k=0, top_p=0.0):
    """Standard sampling filters, static-shape (jit-safe): top_k keeps the
    k largest logits, top_p (nucleus) keeps the smallest prefix of the
    sorted distribution whose mass exceeds p; everything else goes to
    -inf. The caller must pass TEMPERATURE-SCALED logits so the nucleus
    is taken on the actual sampling distribution."""
    need_sorted = (top_p and top_p > 0.0) or (top_k and top_k > 0)
    if not need_sorted:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]  # descending
    if top_k and top_k > 0:
        k = min(int(top_k), logits.shape[-1])  # clamp to vocab
        kth = sorted_logits[..., k - 1][..., None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p and top_p > 0.0:
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens whose PRECEDING mass is < p (always keeps the top-1)
        keep_sorted = jnp.concatenate(
            [jnp.zeros_like(cum[..., :1]), cum[..., :-1]], -1) < top_p
        cutoff = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                         axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def generate(params, prompt, n_steps, cfg: TransformerConfig, key=None,
             temperature=0.0, max_len=None, top_k=0, top_p=0.0):
    """Autoregressive generation as ONE jittable program: prefill the cache
    by scanning the prompt, then sample/argmax n_steps continuation tokens.

    prompt: (B, T_p) int32. Returns (B, n_steps) int32. temperature 0 =
    greedy; otherwise categorical sampling with `key`, optionally
    restricted by top_k / nucleus top_p."""
    B, T_p = prompt.shape
    cache = init_kv_cache(cfg, B, max_len)
    T_max = cache["k"].shape[2]
    if T_p + n_steps > T_max:
        # all lengths are static: fail at trace time instead of letting
        # dynamic_update_slice clamp writes onto the last cache slot
        raise ValueError(
            f"prompt ({T_p}) + n_steps ({n_steps}) exceeds the cache "
            f"capacity ({T_max}); raise max_len")
    if T_p + n_steps > params["pos"].shape[0]:
        raise ValueError(
            f"prompt ({T_p}) + n_steps ({n_steps}) exceeds max_len "
            f"({params['pos'].shape[0]}) positional embeddings")
    if key is None:
        key = jax.random.PRNGKey(0)

    cache, last_logits = prefill(params, cache, prompt, cfg)

    def sample(logits, k):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # temperature first, then filters: the nucleus must be taken on
        # the distribution actually sampled from
        logits = _filter_logits(logits / temperature, top_k=top_k,
                                top_p=top_p)
        return jax.random.categorical(k, logits, axis=-1).astype(jnp.int32)

    def gen_body(carry, k):
        cache, logits = carry
        tok = sample(logits, k)
        new_logits, cache = decode_step(params, cache, tok, cfg)
        return (cache, new_logits), tok

    keys = jax.random.split(key, n_steps)
    _, toks = lax.scan(gen_body, (cache, last_logits), keys)
    return toks.T  # (B, n_steps)


def beam_search(params, prompt, n_steps, cfg: TransformerConfig,
                beam_size=4, max_len=None):
    """Beam-search decoding as one jittable program.

    prompt (B, T_p) int32 -> (sequences (B, beam, n_steps) int32,
    scores (B, beam) summed log-probs), beams sorted best-first. The scan
    carries only the cache and per-beam scores; sequences are rebuilt at
    the end by backtracking the per-step parent pointers (no growing
    buffers inside the loop)."""
    B, T_p = prompt.shape
    K, V = int(beam_size), cfg.vocab
    cache = init_kv_cache(cfg, B, max_len)
    T_max = cache["k"].shape[2]
    # the first token comes from prefill logits, so only n_steps-1 decode
    # writes/pos-embedding reads happen (positions T_p .. T_p+n_steps-2)
    if T_p + n_steps - 1 > T_max:
        raise ValueError(
            f"prompt ({T_p}) + n_steps ({n_steps}) exceeds the cache "
            f"capacity ({T_max}); raise max_len")
    if T_p + n_steps - 1 > params["pos"].shape[0]:
        raise ValueError(
            f"prompt ({T_p}) + n_steps ({n_steps}) exceeds max_len "
            f"({params['pos'].shape[0]}) positional embeddings")

    cache, logits = prefill(params, cache, prompt, cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)  # (B, V)
    scores, first = lax.top_k(logp, K)  # (B, K)
    first = first.astype(jnp.int32)

    # replicate the cache per beam: (L, B, T, H, D) -> (L, B*K, T, H, D)
    def rep(x):
        return jnp.repeat(x, K, axis=1)

    cache = {"k": rep(cache["k"]), "v": rep(cache["v"]), "pos": cache["pos"]}

    def step(carry, _):
        cache, scores, tokens = carry  # tokens (B, K) from previous step
        logits, cache = decode_step(params, cache, tokens.reshape(B * K),
                                    cfg)
        logp = jax.nn.log_softmax(logits, axis=-1).reshape(B, K, V)
        total = scores[..., None] + logp  # (B, K, V)
        scores, flat = lax.top_k(total.reshape(B, K * V), K)  # (B, K)
        parents = (flat // V).astype(jnp.int32)  # which beam each came from
        tokens = (flat % V).astype(jnp.int32)
        # reorder every beam-replicated cache row to follow its parent
        gather = (jnp.arange(B)[:, None] * K + parents).reshape(B * K)
        cache = {"k": cache["k"][:, gather], "v": cache["v"][:, gather],
                 "pos": cache["pos"]}
        return (cache, scores, tokens), (tokens, parents)

    (cache, scores, last), (toks, parents) = lax.scan(
        step, (cache, scores, first), None, length=n_steps - 1)
    # toks/parents: (n_steps-1, B, K); prepend the first-step tokens
    # and backtrack parents from the end to recover each beam's sequence
    def back(carry, step_data):
        beam_idx = carry  # (B, K) which beam each final beam was at t+1
        tok_t, par_t = step_data
        tok = jnp.take_along_axis(tok_t, beam_idx, axis=1)
        beam_idx = jnp.take_along_axis(par_t, beam_idx, axis=1)
        return beam_idx, tok

    init_idx = jnp.tile(jnp.arange(K, dtype=jnp.int32)[None], (B, 1))
    beam_idx, rev = lax.scan(back, init_idx, (toks, parents), reverse=True)
    first_tok = jnp.take_along_axis(first, beam_idx, axis=1)  # (B, K)
    seqs = jnp.concatenate([first_tok[None], rev], axis=0)  # (n_steps, B, K)
    return seqs.transpose(1, 2, 0), scores


# ---------------------------------------------------------------------------
# GSPMD step: dp x ep x tp
# ---------------------------------------------------------------------------

TP_RULES = [
    # attention: split heads (= output features of wq/wk/wv, input of wo)
    (r"^wq$|^wk$|^wv$", P(None, None, "tp")),
    (r"^wo$", P(None, "tp", None)),
    # dense FFN: Megatron column-then-row
    (r"^w1$", P(None, None, "tp") ),
    (r"^w2$", P(None, "tp", None)),
    (r"^embed$", P(None, None)),
]

MOE_TP_RULES = [
    (r"^wq$|^wk$|^wv$", P(None, None, "tp")),
    (r"^wo$", P(None, "tp", None)),
    # MoE FFN: experts on ep, hidden on tp
    (r"^w1$", P(None, "ep", None, "tp")),
    (r"^w2$", P(None, "ep", "tp", None)),
    (r"^router$", P()),
]


def make_gspmd_train_step(mesh: Mesh, cfg: TransformerConfig, lr=0.1, aux_weight=0.01):
    """Fused train step over a ('dp','ep','tp') mesh: batch on dp, MoE experts
    on ep, heads/FFN-hidden on tp. Returns (step, sharded_params).

    step(params, tokens, targets) -> (loss, new_params); jitted with donated
    params, shardings annotation-driven (GSPMD inserts collectives)."""
    params = init_params(cfg)
    rules = MOE_TP_RULES if cfg.n_experts else TP_RULES
    shardings = make_shardings(params, rules, mesh)
    params = {k: jax.device_put(v, shardings[k]) for k, v in params.items()}
    data_sharding = NamedSharding(mesh, P("dp", None))

    def loss_fn(p, tokens, targets):
        logits, aux = apply(p, tokens, cfg)
        if cfg.use_fused_xent:
            # pallas_call has no GSPMD partitioning rule — without this
            # shard_map XLA would replicate the (B, T, V) logits on every
            # chip to run the kernel; mapping over dp keeps it local
            losses = jax.shard_map(
                _xent_fused_local, mesh=mesh,
                in_specs=(P("dp", None, None), P("dp", None)),
                out_specs=P("dp", None))(logits, targets)
        else:
            losses = _xent(logits, targets)
        return jnp.mean(losses) + aux_weight * aux

    def step(p, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(p, tokens, targets)
        new_p = jax.tree.map(lambda w, g: w - lr * g, p, grads)
        return loss, new_p

    jstep = jax.jit(
        step,
        in_shardings=(shardings, data_sharding, data_sharding),
        out_shardings=(NamedSharding(mesh, P()), shardings),
        donate_argnums=(0,),
    )

    def run_step(p, tokens, targets):
        # stage host batches onto the mesh explicitly: on a mesh spanning
        # processes, jit cannot auto-commit raw host arrays (every process
        # holds the same batch; device_put builds the global array from
        # each process's addressable shards)
        tokens = jax.device_put(jnp.asarray(tokens), data_sharding)
        targets = jax.device_put(jnp.asarray(targets), data_sharding)
        return jstep(p, tokens, targets)

    return run_step, params


# ---------------------------------------------------------------------------
# shard_map step: dp x sp x pp (ring attention + SPMD pipeline)
# ---------------------------------------------------------------------------

def make_pipeline_train_step(mesh: Mesh, cfg: TransformerConfig, lr=0.1, n_micro=2):
    """Fused train step over a ('dp','sp','pp') mesh: batch sharded on dp and
    microbatched through an SPMD pipeline whose stages are the layer stack
    sharded on pp; inside each stage, attention is ring attention with the
    sequence sharded on sp. Returns (step, params).

    Per-call global shapes: tokens/targets (batch, seq). Requires
    batch % (dp * n_micro) == 0, seq % sp == 0, n_layers % pp == 0."""
    assert cfg.n_experts == 0, "pipeline step uses the dense FFN"
    params = init_params(cfg)
    pp = mesh.shape["pp"]
    assert cfg.n_layers % pp == 0

    stack_keys = _stack_keys(params)
    pspecs = {k: (P("pp") if k in stack_keys else P()) for k in params}
    params = {
        k: jax.device_put(v, NamedSharding(mesh, pspecs[k])) for k, v in params.items()
    }

    def stage_fn(stage_params, x):
        """Apply this stage's layer slice to one microbatch activation.
        x: (mb_local, T_local, d); stage_params leaves: (L/pp, ...)."""
        attn = functools.partial(ring_attention, axis_name="sp", causal=True)

        def body(h, lp):
            y, _ = _layer(lp, h, cfg, attn)
            return y, None

        y, _ = lax.scan(body, x, stage_params)
        return y

    def local_step(p, tokens, targets):
        """Runs per-device under shard_map over ('dp','sp','pp').
        tokens/targets: (b_local, T_local) int32."""
        def loss_fn(p):
            b, t = tokens.shape
            sp_idx = lax.axis_index("sp")
            pos0 = sp_idx * t  # global position offset of this sequence shard
            x = p["embed"][tokens] + lax.dynamic_slice_in_dim(p["pos"], pos0, t, axis=0)[None]
            stage_params = {k: p[k] for k in stack_keys}
            mb = b // n_micro
            micro = x.reshape(n_micro, mb, t, cfg.d_model)
            out = spmd_pipeline(stage_fn, stage_params, micro, axis_name="pp")
            h = out.reshape(b, t, cfg.d_model)
            h = _ln(h, p["ln_f_g"], p["ln_f_b"])
            logits = h @ p["embed"].T
            losses = _xent(logits, targets, cfg.use_fused_xent)
            # replicated-scalar loss: only the device's own shard contributes,
            # psum over every mesh axis; pp ranks all hold identical outputs so
            # gate the contribution to pp rank 0.
            is_pp0 = (lax.axis_index("pp") == 0).astype(losses.dtype)
            total = lax.psum(jnp.sum(losses) * is_pp0, ("dp", "sp", "pp"))
            count = losses.size * mesh.shape["dp"] * mesh.shape["sp"]  # static
            return total / count

        loss, grads = jax.value_and_grad(loss_fn)(p)
        # grads of replicated params are device-varying partials (each device
        # saw its batch/sequence shard): all-reduce to the replicated mean.
        # pp-sharded stack grads are already correct per-stage; average over
        # the axes they are replicated on (dp, sp).
        def reduce_grad(k, g):
            axes = ("dp", "sp") if k in stack_keys else ("dp", "sp", "pp")
            return lax.pmean(g, axes)

        grads = {k: reduce_grad(k, g) for k, g in grads.items()}
        new_p = {k: p[k] - lr * grads[k] for k in p}
        return loss, new_p

    in_specs = (pspecs, P("dp", "sp"), P("dp", "sp"))
    out_specs = (P(), pspecs)
    smapped = jax.shard_map(local_step, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    jstep = jax.jit(smapped, donate_argnums=(0,))
    dp, sp = mesh.shape["dp"], mesh.shape["sp"]

    def checked_step(p, tokens, targets):
        b, t = tokens.shape
        if b % (dp * n_micro):
            raise ValueError(f"batch {b} not divisible by dp*n_micro = {dp * n_micro}")
        if t % sp:
            raise ValueError(f"seq len {t} not divisible by sp = {sp}")
        return jstep(p, tokens, targets)

    return checked_step, params
