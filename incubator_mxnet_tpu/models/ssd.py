"""SSD single-shot detector symbols
(ref: example/ssd/symbol/symbol_builder.py get_symbol_train/get_symbol +
example/ssd/symbol/common.py multi_layer_feature/multibox_layer).

TPU-first notes: every stage is fixed-shape (anchors, targets, NMS all
mask-based — see ops/vision.py), so train and detect symbols jit into
single XLA programs; the whole multi-scale head concat is one fused graph.
"""
from __future__ import annotations

from .. import symbol as sym

__all__ = ["get_symbol_train", "get_symbol", "default_spec"]


def default_spec():
    """Per-scale anchor spec: (sizes, ratios) per feature stride."""
    return {
        "sizes": [(0.2, 0.27), (0.37, 0.45), (0.54, 0.62)],
        "ratios": [(1, 2, 0.5)] * 3,
    }


def _conv_act(data, name, num_filter, kernel=(3, 3), pad=(1, 1), stride=(1, 1)):
    c = sym.Convolution(data, kernel=kernel, pad=pad, stride=stride,
                        num_filter=num_filter, name=name)
    bn = sym.BatchNorm(c, name=name + "_bn")
    return sym.Activation(bn, act_type="relu")


def _body(data, base_filters=32):
    """Small VGG-ish backbone emitting 3 feature scales (strides 8/16/32)
    (ref: example/ssd/symbol/vgg16_reduced.py role)."""
    x = _conv_act(data, "c1", base_filters)
    x = sym.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max")
    x = _conv_act(x, "c2", base_filters * 2)
    x = sym.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max")
    x = _conv_act(x, "c3", base_filters * 4)
    f1 = _conv_act(x, "c3b", base_filters * 4)            # stride 4... pool next
    x = sym.Pooling(f1, kernel=(2, 2), stride=(2, 2), pool_type="max")
    f2 = _conv_act(x, "c4", base_filters * 8)             # stride 8
    x = sym.Pooling(f2, kernel=(2, 2), stride=(2, 2), pool_type="max")
    f3 = _conv_act(x, "c5", base_filters * 8)             # stride 16
    return [f2, f3, sym.Pooling(f3, kernel=(2, 2), stride=(2, 2),
                                pool_type="max")]


def _multibox_layer(features, num_classes, sizes, ratios, clip=False):
    """Per-scale loc/cls heads + anchors, concatenated over scales
    (ref: example/ssd/symbol/common.py multibox_layer)."""
    loc_layers, cls_layers, anchor_layers = [], [], []
    num_classes_b = num_classes + 1  # + background
    for k, feat in enumerate(features):
        n_anchor = len(sizes[k]) + len(ratios[k]) - 1
        loc = sym.Convolution(feat, kernel=(3, 3), pad=(1, 1),
                              num_filter=n_anchor * 4,
                              name=f"loc_pred_{k}")
        # (B, A*4, H, W) -> (B, H, W, A*4) -> (B, -1)
        loc = sym.transpose(loc, axes=(0, 2, 3, 1))
        loc_layers.append(sym.Flatten(loc))

        cls = sym.Convolution(feat, kernel=(3, 3), pad=(1, 1),
                              num_filter=n_anchor * num_classes_b,
                              name=f"cls_pred_{k}")
        cls = sym.transpose(cls, axes=(0, 2, 3, 1))
        cls_layers.append(sym.Flatten(cls))

        anchor_layers.append(sym.contrib.MultiBoxPrior(
            feat, sizes=tuple(sizes[k]), ratios=tuple(ratios[k]), clip=clip,
            name=f"anchors_{k}"))

    loc_preds = sym.Concat(*loc_layers, dim=1, name="multibox_loc_pred")
    cls_concat = sym.Concat(*cls_layers, dim=1)
    # (B, sum_k H_k*W_k*A_k*C) -> (B, N, C) -> (B, C, N)
    cls_preds = sym.Reshape(cls_concat, shape=(0, -1, num_classes_b))
    cls_preds = sym.transpose(cls_preds, axes=(0, 2, 1))
    anchors = sym.Concat(*anchor_layers, dim=1, name="multibox_anchors")
    return loc_preds, cls_preds, anchors


def get_symbol_train(num_classes=20, nms_thresh=0.5, force_suppress=False,
                     nms_topk=400, base_filters=32, spec=None, **kwargs):
    """Training symbol: outputs [cls_prob, loc_loss, cls_label, det]
    (ref: symbol_builder.py get_symbol_train)."""
    spec = spec or default_spec()
    data = sym.Variable("data")
    label = sym.Variable("label")
    features = _body(data, base_filters)
    loc_preds, cls_preds, anchors = _multibox_layer(
        features, num_classes, spec["sizes"], spec["ratios"], clip=False)

    loc_target, loc_target_mask, cls_target = sym.contrib.MultiBoxTarget(
        anchors, label, cls_preds, overlap_threshold=0.5, ignore_label=-1,
        negative_mining_ratio=3, minimum_negative_samples=0,
        variances=(0.1, 0.1, 0.2, 0.2), name="multibox_target")

    cls_prob = sym.SoftmaxOutput(cls_preds, cls_target, ignore_label=-1,
                                 use_ignore=True, multi_output=True,
                                 normalization="valid", name="cls_prob")
    loc_diff = loc_preds - loc_target
    masked_loc_diff = loc_target_mask * loc_diff
    loc_loss_ = sym.smooth_l1(masked_loc_diff, scalar=1.0,
                              name="loc_loss_")
    loc_loss = sym.MakeLoss(loc_loss_, name="loc_loss")

    cls_label = sym.BlockGrad(cls_target, name="cls_label")
    det = sym.contrib.MultiBoxDetection(
        cls_prob, loc_preds, anchors, nms_threshold=nms_thresh,
        force_suppress=force_suppress, variances=(0.1, 0.1, 0.2, 0.2),
        nms_topk=nms_topk)
    det = sym.BlockGrad(det, name="det_out")
    return sym.Group([cls_prob, loc_loss, cls_label, det])


def get_symbol(num_classes=20, nms_thresh=0.5, force_suppress=False,
               nms_topk=400, base_filters=32, spec=None, **kwargs):
    """Inference symbol -> (B, N, 6) detections
    (ref: symbol_builder.py get_symbol)."""
    spec = spec or default_spec()
    data = sym.Variable("data")
    features = _body(data, base_filters)
    loc_preds, cls_preds, anchors = _multibox_layer(
        features, num_classes, spec["sizes"], spec["ratios"], clip=False)
    cls_prob = sym.SoftmaxActivation(cls_preds, mode="channel")
    return sym.contrib.MultiBoxDetection(
        cls_prob, loc_preds, anchors, nms_threshold=nms_thresh,
        force_suppress=force_suppress, variances=(0.1, 0.1, 0.2, 0.2),
        nms_topk=nms_topk, name="detection")
