"""Symbol-API model builders (ref: example/image-classification/symbols/).

These build `Symbol` graphs for the Module training path; the Gluon twins
live in gluon.model_zoo.
"""
from . import lenet, mlp, resnet, alexnet  # noqa: F401
from .lenet import get_symbol as get_lenet  # noqa: F401
from .mlp import get_symbol as get_mlp  # noqa: F401
from .resnet import get_symbol as get_resnet  # noqa: F401
from . import ssd  # noqa: F401
# gluon-API models (eager; the sparse embedding tier is eager-only)
from . import dlrm  # noqa: F401
from .dlrm import DLRM  # noqa: F401
