"""Device contexts.

TPU-native equivalent of the reference's Context (ref: include/mxnet/base.h
`Context`, python/mxnet/context.py). A Context names a JAX device; `tpu()` is
the first-class accelerator, `gpu()` aliases to the accelerator so reference
scripts run unchanged, `cpu()` is the host.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context", "num_gpus", "num_tpus"]


def _accel_platform():
    """Best available accelerator platform string."""
    try:
        platforms = {d.platform for d in jax.devices()}
    except RuntimeError:
        return "cpu"
    for p in ("tpu", "axon", "gpu", "cuda", "rocm"):
        if p in platforms:
            return p
    return "cpu"


class Context:
    """A device context: (device_type, device_id) naming one JAX device.

    Unlike the reference (where Context routes to per-device engine worker
    queues and storage managers), a Context here resolves to a `jax.Device`;
    placement/async scheduling are delegated to XLA's runtime.
    """

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 5}
    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_type, self.device_id = device_type.device_type, device_type.device_id
        else:
            self.device_type = device_type
            self.device_id = device_id
        if self.device_type not in self.devstr2str():
            raise ValueError(f"unknown device type {self.device_type}")

    @classmethod
    def devstr2str(cls):
        return cls.devstr2type

    @property
    def device_typeid(self):
        return self.devstr2type[self.device_type]

    # -- JAX resolution ---------------------------------------------------
    def jax_device(self):
        """Resolve to the backing jax.Device.

        Always a process-LOCAL device: under jax.distributed, jax.devices()
        includes other processes' (non-addressable) devices, and a Context
        must never place data there (the reference's Context is likewise
        process-local; cross-process movement is the kvstore's job).
        """
        dt = self.device_type
        if dt in ("cpu", "cpu_pinned"):
            try:  # CPU backend devices even when an accelerator is default
                devs = jax.local_devices(backend="cpu")
            except RuntimeError:
                devs = jax.local_devices()
            return devs[min(self.device_id, len(devs) - 1)]
        # gpu and tpu both map onto the available accelerator
        plat = _accel_platform()
        try:
            devs = jax.local_devices(backend=plat) if plat != "cpu" \
                else jax.local_devices()
        except RuntimeError:
            devs = [d for d in jax.local_devices() if d.platform == plat] \
                or jax.local_devices()
        if self.device_id >= len(devs):
            raise ValueError(
                f"device_id {self.device_id} out of range: {len(devs)} {plat} device(s)"
            )
        return devs[self.device_id]

    # -- dunder -----------------------------------------------------------
    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    def __enter__(self):
        if not hasattr(Context._default_ctx, "stack"):
            Context._default_ctx.stack = []
        Context._default_ctx.stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._default_ctx.stack.pop()

    @classmethod
    def default_ctx(cls):
        stack = getattr(cls._default_ctx, "stack", None)
        if stack:
            return stack[-1]
        return Context("cpu", 0)


def cpu(device_id=0):
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def gpu(device_id=0):
    """Alias context for the accelerator (kept for reference-API parity)."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    """The first-class accelerator context (the north-star `mx.tpu()`)."""
    return Context("tpu", device_id)


def current_context():
    return Context.default_ctx()


def num_gpus():
    return num_tpus()


def num_tpus():
    plat = _accel_platform()
    if plat == "cpu":
        return 0
    return len(jax.devices(plat))
