"""RecordIO (ref: python/mxnet/recordio.py:37 MXRecordIO, :212 indexed;
binary format ref: dmlc-core recordio — magic 0xced7230a framing).

Pure-Python implementation of the same on-disk format (kMagic + cflag/length
word, 4-byte aligned records) so shards written by the reference tooling
layout are readable; a C++ reader lands with the native io engine.
"""
from __future__ import annotations

import ctypes
import os
import struct

import numpy as np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xCED7230A
_CFLAG_BITS = 29
_CFLAG_MASK = (1 << _CFLAG_BITS) - 1


def _encode_lrec(cflag, length):
    return (cflag << _CFLAG_BITS) | length


def _decode_lrec(lrec):
    return lrec >> _CFLAG_BITS, lrec & _CFLAG_MASK


class MXRecordIO:
    """Sequential record file reader/writer (ref: recordio.py:37)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.handle = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("invalid flag")
        self.is_open = True
        self.pid = os.getpid()

    def close(self):
        if self.is_open:
            self.handle.close()
            self.is_open = False

    def reset(self):
        self.close()
        self.open()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        d = dict(self.__dict__)
        d["handle"] = None
        if d["is_open"]:
            d["is_open"] = False
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        if self.flag in ("w", "r"):
            self.open()

    def _check_pid(self):
        if self.pid != os.getpid():
            # reopen after fork (ref: recordio.py fork handling)
            self.open()

    def write(self, buf):
        assert self.writable
        self._check_pid()
        self.handle.write(struct.pack("<II", _MAGIC, _encode_lrec(0, len(buf))))
        self.handle.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        self._check_pid()
        head = self.handle.read(8)
        if len(head) < 8:
            return None
        magic, lrec = struct.unpack("<II", head)
        if magic != _MAGIC:
            raise IOError(f"invalid record magic {magic:#x} in {self.uri}")
        _, length = _decode_lrec(lrec)
        buf = self.handle.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.handle.read(pad)
        return buf

    def tell(self):
        return self.handle.tell()

    def seek(self, pos):
        assert not self.writable
        self.handle.seek(pos)


class MXIndexedRecordIO(MXRecordIO):
    """Keyed random access via a .idx file (ref: recordio.py:212)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "r" and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)
        elif self.flag == "w":
            self.fidx = open(self.idx_path, "w")

    def close(self):
        if self.is_open and self.fidx is not None:
            self.fidx.close()
            self.fidx = None
        super().close()

    def read_idx(self, idx):
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write(f"{key}\t{pos}\n")
        self.idx[key] = pos
        self.keys.append(key)


class IRHeader:
    """Image record header (ref: recordio.py IRHeader: flag, label, id, id2)."""

    __slots__ = ("flag", "label", "id", "id2")

    def __init__(self, flag, label, id, id2):  # noqa: A002
        self.flag = flag
        self.label = label
        self.id = id
        self.id2 = id2


_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """(ref: recordio.py pack)"""
    label = header.label
    if np.isscalar(label):
        hdr = struct.pack(_IR_FORMAT, 0, float(label), header.id, header.id2)
        return hdr + s
    label = np.asarray(label, dtype=np.float32)
    hdr = struct.pack(_IR_FORMAT, label.size, 0.0, header.id, header.id2)
    return hdr + label.tobytes() + s


def unpack(s):
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        arr = np.frombuffer(s[: flag * 4], dtype=np.float32)
        s = s[flag * 4:]
        header = IRHeader(flag, arr, id_, id2)
    else:
        header = IRHeader(flag, label, id_, id2)
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """(ref: recordio.py pack_img) — encode with OpenCV."""
    import cv2

    if img_fmt.lower() in (".jpg", ".jpeg"):
        encode_params = [cv2.IMWRITE_JPEG_QUALITY, quality]
    elif img_fmt.lower() == ".png":
        encode_params = [cv2.IMWRITE_PNG_COMPRESSION, quality]
    else:
        encode_params = None
    ret, buf = cv2.imencode(img_fmt, img, encode_params)
    assert ret, "failed to encode image"
    return pack(header, buf.tobytes())


def unpack_img(s, iscolor=-1):
    import cv2

    header, s = unpack(s)
    img = cv2.imdecode(np.frombuffer(s, dtype=np.uint8), iscolor)
    return header, img


# ---------------------------------------------------------------------------
# Native (C++) fast path — mmap + thread-pool batch reads (src/recordio.cc).
# ---------------------------------------------------------------------------


class NativeRecordReader:
    """Zero-copy random-access reader over the same on-disk format, backed by
    the C++ engine (the reference's C++ recordio/threaded-reader analog)."""

    def __init__(self, uri):
        from . import _native

        self._lib = _native.recordio_lib()
        if self._lib is None:
            raise RuntimeError("native recordio library unavailable (g++ build failed)")
        self._handle = self._lib.rio_open_reader(uri.encode())
        if not self._handle:
            raise IOError(f"cannot open {uri}")
        self.uri = uri

    def __len__(self):
        return int(self._lib.rio_num_records(self._handle))

    def read(self, i):
        data = ctypes.POINTER(ctypes.c_uint8)()
        length = ctypes.c_uint32()
        rc = self._lib.rio_record(self._handle, i, ctypes.byref(data), ctypes.byref(length))
        if rc != 0:
            raise IndexError(i)
        return ctypes.string_at(data, length.value)

    def read_batch(self, indices):
        """Parallel fetch of many records -> list[bytes]."""
        n = len(indices)
        idx = (ctypes.c_int64 * n)(*indices)
        lens = [int(self._lib.rio_record_len(self._handle, i)) for i in indices]
        offsets, acc = [], 0
        for ln in lens:
            offsets.append(acc)
            acc += ln
        buf = (ctypes.c_uint8 * max(acc, 1))()
        offs = (ctypes.c_int64 * n)(*offsets)
        rc = self._lib.rio_read_batch(self._handle, idx, n, buf, offs)
        if rc != 0:
            raise IOError("batch read failed")
        raw = bytes(buf)
        return [raw[o : o + ln] for o, ln in zip(offsets, lens)]

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.rio_close_reader(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeRecordWriter:
    """C++ writer producing the same shard format."""

    def __init__(self, uri):
        from . import _native

        self._lib = _native.recordio_lib()
        if self._lib is None:
            raise RuntimeError("native recordio library unavailable")
        self._handle = self._lib.rio_open_writer(uri.encode())
        if not self._handle:
            raise IOError(f"cannot open {uri}")

    def write(self, buf):
        arr = (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf)
        pos = self._lib.rio_write(self._handle, arr, len(buf))
        if pos < 0:
            raise IOError("write failed")
        return int(pos)

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.rio_close_writer(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
