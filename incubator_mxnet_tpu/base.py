"""Base definitions shared across the framework.

TPU-native re-imagination of the reference framework's base layer
(ref: include/mxnet/base.h, python/mxnet/base.py). Instead of a C ABI +
ctypes handle zoo, the substrate is JAX/XLA: arrays are `jax.Array`s, ops are
traced/jitted functions, and the "engine" is XLA's async dispatch.
"""
from __future__ import annotations

import numpy as np

__all__ = ["MXNetError", "DType", "dtype_np", "canonical_dtype", "string_types"]

string_types = (str,)


class MXNetError(RuntimeError):
    """Framework error type (ref: python/mxnet/base.py MXNetError)."""


# Canonical dtype names (ref: mshadow type enum used by TBlob / NDArray).
_DTYPE_ALIASES = {
    "float32": np.float32,
    "float64": np.float64,
    "float16": np.float16,
    "bfloat16": None,  # resolved lazily via ml_dtypes/jnp
    "uint8": np.uint8,
    "int8": np.int8,
    "int32": np.int32,
    "int64": np.int64,
    "bool": np.bool_,
}


def dtype_np(dtype):
    """Resolve a user-supplied dtype (str/np.dtype/jnp dtype) to a numpy dtype."""
    if dtype is None:
        return np.dtype(np.float32)
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            import ml_dtypes

            return np.dtype(ml_dtypes.bfloat16)
        if dtype in _DTYPE_ALIASES:
            return np.dtype(_DTYPE_ALIASES[dtype])
    return np.dtype(dtype)


def canonical_dtype(dtype) -> str:
    """Canonical string name for a dtype."""
    return dtype_np(dtype).name


class DType:
    """Namespace of supported dtypes."""

    float16 = "float16"
    float32 = "float32"
    float64 = "float64"
    bfloat16 = "bfloat16"
    uint8 = "uint8"
    int8 = "int8"
    int32 = "int32"
    int64 = "int64"


class ThreadLocalStack:
    """Per-thread stack for scope context managers (name/attribute scopes;
    ref: the reference keeps these thread-local, tests/test_thread_local.py)."""

    def __init__(self):
        import threading

        self._local = threading.local()

    def frames(self):
        try:
            return self._local.stack
        except AttributeError:
            self._local.stack = []
            return self._local.stack

    def push(self, frame):
        self.frames().append(frame)

    def pop(self):
        return self.frames().pop()

    def top(self):
        frames = self.frames()
        return frames[-1] if frames else None
