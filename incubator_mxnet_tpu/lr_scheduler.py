"""Learning-rate schedules.

Capability parity with the reference's scheduler set (ref:
python/mxnet/lr_scheduler.py), re-expressed in this framework's idiom:
every schedule is a STATELESS closed-form function of `num_update` — the
warmup ramp and the decay law compose in one place (`__call__`), and each
subclass contributes only its decay formula. The reference instead mutates
`base_lr`/`count` as updates stream by; closed forms make schedules safe to
evaluate from any step (checkpoint resume, jitted lr as scalar input) and
trivially testable.
"""
from __future__ import annotations

import bisect
import math

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler"]


class LRScheduler:
    """Base: linear/quadratic warmup from warmup_begin_lr to base_lr over
    warmup_steps, then the subclass decay law."""

    def __init__(self, base_lr=0.01, warmup_steps=0, warmup_begin_lr=0,
                 warmup_mode="linear"):
        self.base_lr = base_lr
        self.warmup_steps = warmup_steps
        self.warmup_begin_lr = warmup_begin_lr
        self.warmup_final_lr = base_lr
        self.warmup_mode = warmup_mode

    def get_warmup_lr(self, num_update):
        t = num_update / self.warmup_steps
        if self.warmup_mode == "linear":
            return self.warmup_begin_lr + (self.warmup_final_lr
                                           - self.warmup_begin_lr) * t
        return self.warmup_final_lr * t * t

    def _decay(self, num_update):
        raise NotImplementedError

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        return self._decay(num_update)


class FactorScheduler(LRScheduler):
    """lr = base_lr * factor^(number of `step`-sized intervals completed),
    floored at stop_factor_lr (ref: FactorScheduler)."""

    def __init__(self, step, factor=1.0, stop_factor_lr=1e-8, base_lr=0.01,
                 **kw):
        super().__init__(base_lr, **kw)
        if step < 1:
            raise ValueError("step must be >= 1")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr

    def _decay(self, num_update):
        n = max(0, (num_update - 1) // self.step)
        return max(self.base_lr * self.factor ** n, self.stop_factor_lr)


class MultiFactorScheduler(LRScheduler):
    """lr = base_lr * factor^(number of milestones passed)
    (ref: MultiFactorScheduler)."""

    def __init__(self, step, factor=1.0, base_lr=0.01, **kw):
        super().__init__(base_lr, **kw)
        self.step = sorted(step)
        self.factor = factor

    def _decay(self, num_update):
        # milestone m is passed once num_update > m
        n = bisect.bisect_left(self.step, num_update)
        return self.base_lr * self.factor ** n


class _AnnealToFinal(LRScheduler):
    """Shared shape for poly/cosine: interpolate base_lr -> final_lr over
    [warmup_steps, max_update] by a profile of the progress fraction."""

    def __init__(self, max_update, base_lr=0.01, final_lr=0, **kw):
        super().__init__(base_lr, **kw)
        self.max_update = max_update
        self.final_lr = final_lr
        self.max_steps = max_update - self.warmup_steps

    def _profile(self, frac):
        raise NotImplementedError

    def _decay(self, num_update):
        if num_update > self.max_update:
            return self.final_lr
        frac = (num_update - self.warmup_steps) / self.max_steps
        return self.final_lr + (self.base_lr - self.final_lr) * self._profile(frac)


class PolyScheduler(_AnnealToFinal):
    """Polynomial decay profile (1 - frac)^pwr (ref: PolyScheduler)."""

    def __init__(self, max_update, base_lr=0.01, pwr=2, final_lr=0, **kw):
        super().__init__(max_update, base_lr, final_lr, **kw)
        self.power = pwr

    def _profile(self, frac):
        return (1 - frac) ** self.power


class CosineScheduler(_AnnealToFinal):
    """Half-cosine decay profile (1 + cos(pi frac)) / 2
    (ref: CosineScheduler)."""

    def _profile(self, frac):
        return (1 + math.cos(math.pi * frac)) / 2
