"""Multi-process bootstrap (ref: ps-lite env protocol DMLC_ROLE/
DMLC_PS_ROOT_* consumed by src/kvstore/kvstore_dist.h; launcher
tools/launch.py).

TPU-native: every process is a JAX distributed client; the launcher exports
MXTPU_COORDINATOR / MXTPU_NUM_PROCESSES / MXTPU_PROCESS_ID (plus the
reference-compatible DMLC_* names) and `init_from_env` turns them into
`jax.distributed.initialize`. Collectives then ride ICI within a host and
DCN across hosts — serverless all-reduce instead of parameter servers.
"""
from __future__ import annotations

from . import config as _config

__all__ = ["init_from_env", "is_initialized"]

_INITIALIZED = False


def is_initialized():
    return _INITIALIZED


def init_from_env():
    """Initialize jax.distributed from launcher env vars; idempotent no-op
    when unlaunched (single-process) or already initialized."""
    global _INITIALIZED
    if _INITIALIZED:
        return True
    import jax

    try:  # user may have initialized jax.distributed themselves
        if jax.distributed.is_initialized():
            _INITIALIZED = True
            return True
    except AttributeError:  # older jax without is_initialized
        pass
    coord = _config.get("MXTPU_COORDINATOR")
    nproc = _config.get("MXTPU_NUM_PROCESSES")
    if not coord or nproc <= 1:
        return False
    try:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=nproc,
            process_id=_config.get("MXTPU_PROCESS_ID"),
        )
    except RuntimeError as e:
        # backend already started (a computation ran before kvstore.create):
        # too late to join the job — surface a clear message
        raise RuntimeError(
            "kvstore 'dist_*' must be created before the first computation "
            "(jax backends are already initialized); create the kvstore "
            "first or call distributed.init_from_env() at program start"
        ) from e
    _INITIALIZED = True
    return True
