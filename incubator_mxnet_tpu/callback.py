"""Training callbacks.

Capability parity with the reference's callback set (ref:
python/mxnet/callback.py), re-expressed in this framework's idiom: periodic
behavior is one `_every` combinator applied to plain functions, and the
Speedometer is a small timer state machine (`_Window`) separated from its
logging. The callback signatures are unchanged — epoch-end callbacks get
(iter_no, sym, arg, aux); batch-end callbacks get a BatchEndParam-style
object with .epoch/.nbatch/.eval_metric.
"""
from __future__ import annotations

import logging
import time

from .model import save_checkpoint

__all__ = ["Speedometer", "do_checkpoint", "module_checkpoint",
           "LogValidationMetricsCallback",
           "log_train_metric", "ProgressBar"]


def _every(period, fn):
    """Run `fn` on every `period`-th 1-based tick."""
    period = int(max(1, period))

    def _callback(tick, *args, **kwargs):
        if (tick + 1) % period == 0:
            fn(tick + 1, *args, **kwargs)

    return _callback


def do_checkpoint(prefix, period=1):
    """Epoch-end callback saving `prefix-symbol.json` + `prefix-NNNN.params`
    (ref: callback.py do_checkpoint)."""
    return _every(period, lambda epoch, sym=None, arg=None, aux=None:
                  save_checkpoint(prefix, epoch, sym, arg, aux))


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Epoch-end callback delegating to the module's own checkpointing
    (ref: callback.py module_checkpoint)."""
    return _every(period, lambda epoch, *a:
                  mod.save_checkpoint(prefix, epoch, save_optimizer_states))


def log_train_metric(period, auto_reset=False):
    """Batch-end callback logging the running training metric
    (ref: callback.py log_train_metric)."""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            for name, value in param.eval_metric.get_name_value():
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class _Window:
    """Samples/sec over the batches since the last report or reset."""

    def __init__(self):
        self.t0 = None
        self.nbatch0 = 0

    def restart(self, nbatch):
        self.t0 = time.time()
        self.nbatch0 = nbatch

    def rate(self, nbatch, batch_size):
        dt = time.time() - self.t0
        return (nbatch - self.nbatch0) * batch_size / dt if dt > 0 else 0.0


class Speedometer:
    """Batch-end throughput logger (ref: callback.py Speedometer). Reading
    the metric forces a device sync, same as the reference's WaitToRead."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self._win = _Window()
        self._last_nbatch = 0

    def __call__(self, param):
        wrapped = param.nbatch < self._last_nbatch  # new epoch restarted at 0
        self._last_nbatch = param.nbatch
        if self._win.t0 is None or wrapped:
            self._win.restart(param.nbatch)
            return
        if param.nbatch % self.frequent != 0 or param.nbatch == self._win.nbatch0:
            return
        speed = self._win.rate(param.nbatch, self.batch_size)
        if param.eval_metric is not None:
            pairs = param.eval_metric.get_name_value()
            if self.auto_reset:
                param.eval_metric.reset()
            logging.info("Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s",
                         param.epoch, param.nbatch, speed,
                         "\t".join(f"{n}={v:f}" for n, v in pairs))
        else:
            logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                         param.epoch, param.nbatch, speed)
        self._win.restart(param.nbatch)


class ProgressBar:
    """Batch-end textual progress bar (ref: callback.py ProgressBar)."""

    def __init__(self, total, length=80):
        self.total = total
        self.length = length

    def __call__(self, param):
        frac = param.nbatch / float(self.total)
        filled = int(round(self.length * frac))
        logging.info("[%s] %s%%",
                     "=" * filled + "-" * (self.length - filled),
                     int(round(100 * frac)))


class LogValidationMetricsCallback:
    """Log eval metrics at epoch end (ref: callback.py
    LogValidationMetricsCallback) — the eval_end_callback counterpart of
    log_train_metric."""

    def __call__(self, param):
        if not param.eval_metric:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f",
                         param.epoch, name, value)
