"""Numeric testing toolbox (ref: python/mxnet/test_utils.py — shipped in the
package). The key oracle is `check_consistency`: run the same symbol under
several contexts/dtypes and cross-check — TPU correctness = consistency of
tpu vs cpu, exactly the cpu-vs-gpu pattern of the reference (:1224).
"""
from __future__ import annotations

import numpy as np

from .context import Context, cpu, current_context, tpu, num_tpus
from .ndarray.ndarray import NDArray
from .ndarray import array as nd_array
from . import random as _rnd

__all__ = [
    "default_context", "set_default_context", "assert_almost_equal",
    "almost_equal", "same", "rand_ndarray", "rand_shape_nd", "random_arrays",
    "check_numeric_gradient", "check_symbolic_forward", "check_symbolic_backward",
    "check_consistency", "simple_forward", "create_2d_tensor", "rand_coord_2d",
]

_DEFAULT_CTX = [None]


def default_context():
    """(ref: test_utils.py:52) — retarget the whole suite at a device."""
    if _DEFAULT_CTX[0] is not None:
        return _DEFAULT_CTX[0]
    return current_context()


def set_default_context(ctx):
    _DEFAULT_CTX[0] = ctx


def same(a, b):
    return np.array_equal(a, b)


def almost_equal(a, b, rtol=1e-5, atol=1e-20, equal_nan=False):
    return np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b"), equal_nan=False):
    """(ref: test_utils.py:474)"""
    a = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else np.asarray(b)
    if not almost_equal(a, b, rtol, atol, equal_nan):
        index = np.unravel_index(np.argmax(np.abs(a - b)), a.shape) if a.shape else ()
        rel = np.max(np.abs(a - b) / (np.abs(b) + atol + 1e-30))
        raise AssertionError(
            f"Items are not equal (rtol={rtol}, atol={atol}): max rel err {rel} "
            f"at {index}: {names[0]}={a[index] if a.shape else a}, "
            f"{names[1]}={b[index] if b.shape else b}"
        )


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, stype="default", density=None, dtype=None, ctx=None):
    arr = np.random.uniform(-1, 1, shape).astype(dtype or np.float32)
    if stype == "default":
        return nd_array(arr, ctx=ctx)
    from .ndarray import sparse

    return sparse.cast_storage(nd_array(arr), stype)


def random_arrays(*shapes):
    arrays = [np.random.randn(*s).astype(np.float32) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    ctx = ctx or default_context()
    inputs = {k: nd_array(v, ctx=ctx) for k, v in inputs.items()}
    exe = sym.bind(ctx, args=inputs, grad_req="null")
    outputs = [o.asnumpy() for o in exe.forward(is_train=is_train)]
    if len(outputs) == 1:
        outputs = outputs[0]
    return outputs


def _parse_location(sym, location, ctx):
    if isinstance(location, dict):
        return {k: nd_array(v, ctx=ctx) if not isinstance(v, NDArray) else v
                for k, v in location.items()}
    return {k: nd_array(v, ctx=ctx) if not isinstance(v, NDArray) else v
            for k, v in zip(sym.list_arguments(), location)}


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None, use_forward_train=True,
                           ctx=None, grad_stype_dict=None, dtype=np.float32):
    """Finite-difference gradient check (ref: test_utils.py:801)."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    args = {k: v for k, v in location.items()}
    grad_nodes = grad_nodes or list(args.keys())
    exe = sym.bind(
        ctx, args=args, grad_req={k: ("write" if k in grad_nodes else "null") for k in args},
        aux_states=aux_states,
    )
    exe.forward(is_train=True)
    out = exe.outputs[0].asnumpy()
    head_grad = np.ones_like(out)
    exe.backward([nd_array(head_grad, ctx=ctx)])
    sym_grads = {k: exe.grad_dict[k].asnumpy() for k in grad_nodes if k in exe.grad_dict}

    for name in grad_nodes:
        base = location[name].asnumpy().astype(np.float64)
        num_grad = np.zeros_like(base)
        flat = base.reshape(-1)
        ng_flat = num_grad.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + numeric_eps / 2
            exe.arg_dict[name]._data = nd_array(base.astype(dtype))._data.reshape(base.shape)
            exe.forward(is_train=use_forward_train)
            f_pos = float((exe.outputs[0].asnumpy() * head_grad).sum())
            flat[i] = orig - numeric_eps / 2
            exe.arg_dict[name]._data = nd_array(base.astype(dtype))._data.reshape(base.shape)
            exe.forward(is_train=use_forward_train)
            f_neg = float((exe.outputs[0].asnumpy() * head_grad).sum())
            ng_flat[i] = (f_pos - f_neg) / numeric_eps
            flat[i] = orig
        exe.arg_dict[name]._data = nd_array(base.astype(dtype))._data.reshape(base.shape)
        assert_almost_equal(
            sym_grads[name], num_grad, rtol=rtol, atol=atol or rtol * 0.1,
            names=(f"analytic {name}", f"numeric {name}"),
        )


def check_symbolic_forward(sym, location, expected, rtol=1e-5, atol=None,
                           aux_states=None, ctx=None, equal_nan=False, dtype=np.float32):
    """(ref: test_utils.py:939)"""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    exe = sym.bind(ctx, args=location, grad_req="null", aux_states=aux_states)
    outputs = exe.forward(is_train=False)
    for out, exp in zip(outputs, expected):
        assert_almost_equal(out, exp, rtol=rtol, atol=atol or 1e-20, equal_nan=equal_nan)
    return [o.asnumpy() for o in outputs]


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-5, atol=None,
                            aux_states=None, grad_req="write", ctx=None, equal_nan=False,
                            dtype=np.float32):
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    exe = sym.bind(ctx, args=location, grad_req=grad_req, aux_states=aux_states)
    exe.forward(is_train=True)
    exe.backward([nd_array(g, ctx=ctx) if not isinstance(g, NDArray) else g for g in out_grads])
    if isinstance(expected, dict):
        for name, exp in expected.items():
            assert_almost_equal(exe.grad_dict[name], exp, rtol, atol or 1e-20,
                                names=(f"grad {name}", "expected"), equal_nan=equal_nan)
    else:
        for name, exp in zip(sym.list_arguments(), expected):
            if exp is None:
                continue
            assert_almost_equal(exe.grad_dict[name], exp, rtol, atol or 1e-20,
                                names=(f"grad {name}", "expected"), equal_nan=equal_nan)
    return {k: v.asnumpy() for k, v in exe.grad_dict.items()}


def check_consistency(sym, ctx_list, scale=1.0, grad_req="write",
                      arg_params=None, aux_params=None, rtol=1e-4, atol=1e-5,
                      raise_on_err=True, use_uniform=False):
    """Cross-context oracle (ref: test_utils.py:1224): run the same symbol on
    each context (e.g. cpu vs tpu) and cross-check outputs + gradients."""
    assert len(ctx_list) > 1
    if isinstance(sym, (list, tuple)):
        syms = list(sym)
    else:
        syms = [sym] * len(ctx_list)

    exe_list = []
    shapes0 = {k: v for k, v in ctx_list[0].items() if k != "ctx"}
    arg_values = None
    for s, spec in zip(syms, ctx_list):
        ctx = spec["ctx"]
        shapes = {k: v for k, v in spec.items() if k != "ctx" and not k.endswith("dtype")}
        type_dict = {k[: -len("_dtype")]: v for k, v in spec.items() if k.endswith("_dtype")}
        exe = s.simple_bind(ctx=ctx, grad_req=grad_req, type_dict=type_dict, **shapes)
        if arg_values is None:
            arg_values = {}
            for name, arr in exe.arg_dict.items():
                if use_uniform:
                    arg_values[name] = np.random.uniform(-0.1, 0.1, arr.shape).astype(np.float32)
                else:
                    arg_values[name] = (np.random.randn(*arr.shape) * scale).astype(np.float32)
            if arg_params:
                arg_values.update({k: v.asnumpy() if isinstance(v, NDArray) else v for k, v in arg_params.items()})
        for name, arr in exe.arg_dict.items():
            arr._data = nd_array(arg_values[name].astype(arr.dtype))._data
        if aux_params:
            for name, v in aux_params.items():
                if name in exe.aux_dict:
                    exe.aux_dict[name]._data = nd_array(v)._data
        exe_list.append(exe)

    outputs = []
    for exe in exe_list:
        exe.forward(is_train=(grad_req != "null"))
        if grad_req != "null":
            exe.backward([nd_array(np.ones(o.shape, dtype=np.float32)) for o in exe.outputs])
        outputs.append([o.asnumpy() for o in exe.outputs])

    ref = outputs[0]
    for i, outs in enumerate(outputs[1:], 1):
        for o_ref, o in zip(ref, outs):
            assert_almost_equal(o, o_ref, rtol=rtol, atol=atol,
                                names=(f"ctx[{i}] out", "ctx[0] out"))
    if grad_req != "null":
        ref_grads = {k: v.asnumpy() for k, v in exe_list[0].grad_dict.items()}
        for i, exe in enumerate(exe_list[1:], 1):
            for k, v in exe.grad_dict.items():
                assert_almost_equal(v, ref_grads[k], rtol=rtol, atol=atol,
                                    names=(f"ctx[{i}] grad {k}", "ctx[0] grad"))
    return outputs


def create_2d_tensor(rows, columns, dtype=np.int64):
    a = np.arange(0, rows).reshape(rows, 1)
    b = np.broadcast_to(a, shape=(a.shape[0], columns))
    return nd_array(b.astype(dtype))


def rand_coord_2d(x_low, x_high, y_low, y_high):
    x = np.random.randint(x_low, x_high)
    y = np.random.randint(y_low, y_high)
    return x, y
