"""Engine facade.

The reference's dependency engine (ref: src/engine/ — ThreadedEnginePerDevice,
var-version dependency tracking) is replaced by XLA's async runtime: every
dispatched computation is ordered by its argument buffers, exactly the
read/write-var ordering the reference implements by hand. This module keeps
the reference's control API (bulking, waitall) as thin shims.
"""
from __future__ import annotations

import contextlib
import os

import jax

__all__ = ["waitall", "bulk", "set_bulk_size"]

_BULK_SIZE = int(os.environ.get("MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN", "15"))


def waitall():
    """(ref: Engine::WaitForAll / MXNDArrayWaitAll)"""
    try:
        jax.effects_barrier()
    except Exception:
        pass


def set_bulk_size(size):
    """(ref: Engine::set_bulk_size) — XLA fuses whole jitted programs, so
    bulking is inherent; retained for API parity."""
    global _BULK_SIZE
    prev = _BULK_SIZE
    _BULK_SIZE = size
    return prev


@contextlib.contextmanager
def bulk(size):
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
