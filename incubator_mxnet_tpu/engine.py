"""Engine facade.

The reference's dependency engine (ref: src/engine/ — ThreadedEnginePerDevice,
var-version dependency tracking) is replaced by XLA's async runtime: every
dispatched computation is ordered by its argument buffers, exactly the
read/write-var ordering the reference implements by hand. This module keeps
the reference's control API (bulking, waitall) as thin shims.
"""
from __future__ import annotations

import contextlib
import logging
import os
import time

import jax

__all__ = ["waitall", "bulk", "set_bulk_size"]

from . import config as _config
from . import telemetry as _telemetry

_BULK_SIZE = _config.get("MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN", 15)

_log = logging.getLogger(__name__)


def waitall():
    """(ref: Engine::WaitForAll / MXNDArrayWaitAll). Barrier failures are
    never raised (parity with the reference's best-effort WaitForAll from
    Python) but they ARE observable: debug log + telemetry error counter."""
    t0 = time.perf_counter() if _telemetry.enabled() else None
    try:
        jax.effects_barrier()
    except Exception as e:
        _log.debug("engine.waitall: effects barrier failed: %r", e,
                   exc_info=True)
        _telemetry.inc("mxtpu_engine_waitall_errors_total",
                       help="engine.waitall barriers that raised "
                            "(swallowed; see debug log for tracebacks).")
    finally:
        if t0 is not None:
            dt = time.perf_counter() - t0
            _telemetry.observe("mxtpu_engine_waitall_seconds", dt,
                               help="Wall time blocked in engine.waitall.")
            # waitall is the loop's explicit device barrier; its blocked
            # time is the step's device_sync phase
            _telemetry.stepstats.record("device_sync", dt)


def set_bulk_size(size):
    """(ref: Engine::set_bulk_size) — XLA fuses whole jitted programs, so
    bulking is inherent; retained for API parity."""
    global _BULK_SIZE
    prev = _BULK_SIZE
    _BULK_SIZE = size
    return prev


@contextlib.contextmanager
def bulk(size):
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)


# ---------------------------------------------------------------------------
# Host-side dependency engine (ref: src/engine/threaded_engine*.cc). Device
# ordering belongs to XLA; this schedules HOST work — pipeline stages,
# checkpoint IO, comm — with read/write-var semantics. Native C++ scheduler
# (src/engine.cc) with a serial NaiveEngine fallback/debug mode, selected by
# MXNET_ENGINE_TYPE exactly like the reference (ref: src/engine/engine.cc:32).
# ---------------------------------------------------------------------------
import ctypes as _ctypes
import threading as _threading

_TRAMPOLINE_T = _ctypes.CFUNCTYPE(None, _ctypes.c_int64)


class Var:
    """Engine variable token (ref: engine::Var)."""

    __slots__ = ("_id", "_engine")

    def __init__(self, vid, engine):
        self._id = vid
        self._engine = engine

    @property
    def version(self):
        return self._engine._var_version(self._id)


class ThreadedEngine:
    """Async host scheduler over the native C++ engine
    (ref: ThreadedEnginePerDevice). Ops are Python callables; read vars may
    run concurrently, writes are exclusive, order is FIFO per var."""

    def __init__(self, num_workers=None):
        from . import _native

        self._lib = _native.load("mxtpu_engine", ["engine.cc"])
        if self._lib is None:
            raise RuntimeError("native engine unavailable (g++ build failed)")
        self._configure(self._lib)
        self._ops = {}
        self._op_lock = _threading.Lock()
        self._next_op = [0]
        self._exceptions = []

        @_TRAMPOLINE_T
        def tramp(op_id):
            with self._op_lock:
                fn, var_ids = self._ops.pop(op_id)
            try:
                fn()
            except BaseException as e:  # surfaced at wait_* (ref:
                with self._op_lock:  # threaded_engine.cc:474 rethrow)
                    self._exceptions.append((e, var_ids))

        self._tramp = tramp  # keep alive
        if num_workers is None:
            num_workers = _config.get("MXNET_CPU_WORKER_NTHREADS")
        self._h = self._lib.eng_create(num_workers, tramp)

    @staticmethod
    def _configure(lib):
        if getattr(lib, "_eng_configured", False):
            return
        lib.eng_create.restype = _ctypes.c_void_p
        lib.eng_create.argtypes = [_ctypes.c_int, _TRAMPOLINE_T]
        lib.eng_destroy.argtypes = [_ctypes.c_void_p]
        lib.eng_new_var.restype = _ctypes.c_int64
        lib.eng_new_var.argtypes = [_ctypes.c_void_p]
        lib.eng_push.argtypes = [
            _ctypes.c_void_p, _ctypes.c_int64,
            _ctypes.POINTER(_ctypes.c_int64), _ctypes.c_int,
            _ctypes.POINTER(_ctypes.c_int64), _ctypes.c_int,
        ]
        lib.eng_wait_for_var.argtypes = [_ctypes.c_void_p, _ctypes.c_int64]
        lib.eng_wait_all.argtypes = [_ctypes.c_void_p]
        lib.eng_var_version.restype = _ctypes.c_uint64
        lib.eng_var_version.argtypes = [_ctypes.c_void_p, _ctypes.c_int64]
        lib._eng_configured = True

    def new_variable(self):
        """(ref: Engine::NewVariable)"""
        return Var(self._lib.eng_new_var(self._h), self)

    def push(self, fn, read_vars=(), write_vars=()):
        """Async-execute fn once all dependencies clear
        (ref: Engine::PushAsync). Like the reference, read and write sets
        must be disjoint — a var in both is treated as write-only (the
        stronger dependency), and duplicates are dropped."""
        wids, rids = [], []
        for v in write_vars:
            if v._id not in wids:
                wids.append(v._id)
        for v in read_vars:
            if v._id not in wids and v._id not in rids:
                rids.append(v._id)
        with self._op_lock:
            op_id = self._next_op[0]
            self._next_op[0] += 1
            self._ops[op_id] = (fn, frozenset(rids + wids))
        r = (_ctypes.c_int64 * max(1, len(rids)))(*rids)
        w = (_ctypes.c_int64 * max(1, len(wids)))(*wids)
        self._lib.eng_push(self._h, op_id, r, len(rids), w, len(wids))

    def wait_for_var(self, var):
        """(ref: Engine::WaitForVar) — rethrows exceptions from ops that
        touched this var (ref: threaded_engine.cc exception capture)."""
        self._lib.eng_wait_for_var(self._h, var._id)
        self._raise_pending(var._id)

    def wait_all(self):
        """(ref: Engine::WaitForAll) — rethrows any pending op exception."""
        self._lib.eng_wait_all(self._h)
        self._raise_pending(None)

    def _raise_pending(self, var_id):
        with self._op_lock:
            for i, (exc, vids) in enumerate(self._exceptions):
                if var_id is None or var_id in vids:
                    del self._exceptions[i]
                    raise exc

    def _var_version(self, vid):
        return int(self._lib.eng_var_version(self._h, vid))

    def stop(self):
        if getattr(self, "_h", None):
            self._lib.eng_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class NaiveEngine:
    """Serial debug engine (ref: src/engine/naive_engine.cc) — executes each
    op synchronously at push; the bisect tool for ordering bugs."""

    def __init__(self, num_workers=None):
        self._versions = {}
        self._next = [0]

    def new_variable(self):
        v = Var(self._next[0], self)
        self._next[0] += 1
        self._versions[v._id] = 0
        return v

    def push(self, fn, read_vars=(), write_vars=()):
        fn()
        for v in write_vars:
            self._versions[v._id] += 1

    def wait_for_var(self, var):
        pass

    def wait_all(self):
        pass

    def _var_version(self, vid):
        return self._versions[vid]

    def stop(self):
        pass


_DEFAULT_ENGINE = None
_ENGINE_LOCK = _threading.Lock()


def _drain_default_engine():
    # drain + stop before interpreter finalization: worker threads must not
    # be joined while a ctypes trampoline could still need the GIL
    global _DEFAULT_ENGINE
    eng = _DEFAULT_ENGINE
    if isinstance(eng, ThreadedEngine):
        try:
            eng.wait_all()
        except BaseException:
            pass
        eng.stop()
    _DEFAULT_ENGINE = None


def get_engine():
    """Process-wide engine, type from MXNET_ENGINE_TYPE
    (ref: engine.cc:32-46 CreateEngine)."""
    global _DEFAULT_ENGINE
    with _ENGINE_LOCK:
        if _DEFAULT_ENGINE is None:
            import atexit

            kind = _config.get("MXNET_ENGINE_TYPE")
            if kind == "NaiveEngine":
                _DEFAULT_ENGINE = NaiveEngine()
            else:
                try:
                    _DEFAULT_ENGINE = ThreadedEngine()
                    atexit.register(_drain_default_engine)
                except RuntimeError as e:
                    import warnings

                    warnings.warn(
                        f"ThreadedEngine unavailable ({e}); degrading to "
                        f"NaiveEngine (serial dependency execution, slower "
                        f"async semantics). Set MXNET_ENGINE_TYPE=NaiveEngine "
                        f"to silence this.", RuntimeWarning)
                    _DEFAULT_ENGINE = NaiveEngine()
        return _DEFAULT_ENGINE


__all__ += ["Var", "ThreadedEngine", "NaiveEngine", "get_engine"]
