"""Collective helpers over a mesh.

The reference's reduce/broadcast kernels (src/kvstore/comm.h CommCPU:103,
CommDevice:451) + NCCL ring (kvstore_nccl.h) become XLA collectives: psum /
all_gather / ppermute inside shard_map, riding ICI.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["allreduce", "allgather", "broadcast", "reduce_scatter", "psum_in_shardmap"]


def allreduce(values, mesh=None, axis_name="data"):
    """Sum list of per-device arrays OR a sharded array across the mesh axis."""
    if isinstance(values, (list, tuple)):
        acc = values[0]
        for v in values[1:]:
            acc = acc + v
        return acc
    return values


def psum_in_shardmap(x, mesh, axis_name="data"):
    fn = jax.shard_map(
        lambda v: jax.lax.psum(v, axis_name),
        mesh=mesh, in_specs=P(axis_name), out_specs=P(), check_vma=False,
    )
    return fn(x)


def allgather(x, mesh, axis_name="data"):
    fn = jax.shard_map(
        lambda v: jax.lax.all_gather(v, axis_name, tiled=True),
        mesh=mesh, in_specs=P(axis_name), out_specs=P(), check_vma=False,
    )
    return fn(x)


def reduce_scatter(x, mesh, axis_name="data"):
    fn = jax.shard_map(
        lambda v: jax.lax.psum_scatter(v, axis_name, tiled=True),
        mesh=mesh, in_specs=P(None), out_specs=P(axis_name), check_vma=False,
    )
    return fn(x)


def broadcast(x, mesh):
    return jax.device_put(x, NamedSharding(mesh, P()))
