"""Collective helpers over a mesh.

The reference's reduce/broadcast kernels (src/kvstore/comm.h CommCPU:103,
CommDevice:451) + NCCL ring (kvstore_nccl.h) become XLA collectives: psum /
all_gather / ppermute inside shard_map, riding ICI.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["allreduce", "allgather", "broadcast", "reduce_scatter",
           "reduce_scatter_constraint", "psum_in_shardmap"]


def allreduce(values, mesh=None, axis_name="data"):
    """Sum list of per-device arrays OR a sharded array across the mesh axis."""
    if isinstance(values, (list, tuple)):
        acc = values[0]
        for v in values[1:]:
            acc = acc + v
        return acc
    return values


def psum_in_shardmap(x, mesh, axis_name="data"):
    fn = jax.shard_map(
        lambda v: jax.lax.psum(v, axis_name),
        mesh=mesh, in_specs=P(axis_name), out_specs=P(), check_vma=False,
    )
    return fn(x)


def allgather(x, mesh, axis_name="data"):
    fn = jax.shard_map(
        lambda v: jax.lax.all_gather(v, axis_name, tiled=True),
        mesh=mesh, in_specs=P(axis_name), out_specs=P(), check_vma=False,
    )
    return fn(x)


def reduce_scatter(x, mesh, axis_name="data"):
    fn = jax.shard_map(
        lambda v: jax.lax.psum_scatter(v, axis_name, tiled=True),
        mesh=mesh, in_specs=P(None), out_specs=P(axis_name), check_vma=False,
    )
    return fn(x)


def reduce_scatter_constraint(x, mesh, spec):
    """Traced-context counterpart of reduce_scatter(): inside one jitted
    GSPMD program the gradient reduction is inserted by the partitioner
    (not callable as the eager shard_map above), so the way to
    reduce-scatter is to constrain the logically-reduced value to a
    sharded layout — XLA then lowers all-reduce + slice into a
    reduce-scatter and downstream consumers (the ZeRO optimizer update)
    read only the local shard. Used by the zero2 policy
    (parallel.zero.shard_grads)."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def broadcast(x, mesh):
    return jax.device_put(x, NamedSharding(mesh, P()))
