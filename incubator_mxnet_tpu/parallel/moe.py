"""Expert parallelism: mixture-of-experts FFN with capacity-based dispatch.

Capability beyond the reference (SURVEY §2.2: MXNet has no MoE / expert
parallelism). TPU-native design: routing is expressed as dense einsums against
a (tokens, experts, capacity) dispatch tensor — compiler-friendly static
shapes, no gather/scatter of ragged groups — and the expert dimension is
sharded over an `ep` mesh axis. Under `jit` with GSPMD shardings, XLA lowers
the dispatch/combine einsums into all-to-all exchanges over ICI automatically;
`moe_ffn_shardmap` is the explicit `lax.all_to_all` variant for use inside
`shard_map`.

Top-1 routing with an auxiliary load-balance loss (Shazeer et al. 2017 /
Switch Transformer), fixed per-expert capacity, dropped-token semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["moe_dispatch", "moe_ffn", "moe_ffn_shardmap"]


def moe_dispatch(tokens, router_w, n_experts, capacity):
    """Compute top-1 dispatch/combine tensors and the load-balance aux loss.

    tokens: (T, d); router_w: (d, E). Returns (dispatch (T,E,C) 0/1,
    combine (T,E,C) gate-weighted, aux_loss scalar).
    """
    logits = tokens @ router_w
    probs = jax.nn.softmax(logits, axis=-1)  # (T, E)
    expert = jnp.argmax(probs, axis=-1)  # (T,)
    gate = jnp.max(probs, axis=-1)  # (T,)
    onehot = jax.nn.one_hot(expert, n_experts, dtype=tokens.dtype)  # (T, E)
    # position of each token within its expert's queue; tokens beyond
    # capacity are dropped (residual connection carries them unchanged).
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0  # (T, E), -1 where unrouted
    pos_tok = jnp.max(pos, axis=-1)  # (T,)
    keep = (pos_tok >= 0) & (pos_tok < capacity)
    disp = (
        onehot[:, :, None]
        * jax.nn.one_hot(jnp.clip(pos_tok, 0, capacity - 1), capacity, dtype=tokens.dtype)[:, None, :]
        * keep[:, None, None]
    )  # (T, E, C)
    combine = disp * gate[:, None, None]
    # load-balance loss: E * sum_e fraction_routed_e * mean_prob_e
    frac = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = n_experts * jnp.sum(frac * mean_prob)
    return disp, combine, aux


def moe_ffn(tokens, router_w, w1, w2, *, capacity_factor=2.0):
    """GSPMD MoE FFN. tokens: (T, d); w1: (E, d, f); w2: (E, f, d).

    Shard w1/w2 on their expert axis with PartitionSpec("ep", ...) and XLA
    inserts the token all-to-all. Returns (out (T, d), aux_loss).
    """
    E = w1.shape[0]
    T = tokens.shape[0]
    capacity = max(1, int(capacity_factor * T / E))
    disp, combine, aux = moe_dispatch(tokens, router_w, E, capacity)
    xs = jnp.einsum("td,tec->ecd", tokens, disp)  # (E, C, d)
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xs, w1))
    ys = jnp.einsum("ecf,efd->ecd", h, w2)  # (E, C, d)
    out = jnp.einsum("ecd,tec->td", ys, combine)
    return out, aux


def moe_ffn_shardmap(tokens, router_w, w1, w2, *, axis_name="ep", capacity_factor=2.0):
    """Explicit expert-parallel MoE for use inside shard_map over `axis_name`.

    Per-device shapes: tokens (T_local, d) — token batch sharded over ep;
    w1 (E_local, d, f), w2 (E_local, f, d) — experts sharded over ep. Tokens
    route to the global expert set; dispatch travels via `lax.all_to_all`.
    """
    n = lax.psum(1, axis_name)
    E_local = w1.shape[0]
    E = E_local * n
    T = tokens.shape[0]
    capacity = max(1, int(capacity_factor * T / E))
    disp, combine, aux = moe_dispatch(tokens, router_w, E, capacity)
    xs = jnp.einsum("td,tec->ecd", tokens, disp)  # (E, C, d): rows grouped by owner device
    # scatter expert-rows to their owner; gather one chunk per source device.
    # (E, C, d) -> (E_local, n*C, d): expert k's queue is the concat of every
    # source device's C-slot block for it.
    xs = lax.all_to_all(xs, axis_name, split_axis=0, concat_axis=1, tiled=True)
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xs, w1))
    ys = jnp.einsum("ecf,efd->ecd", h, w2)
    ys = lax.all_to_all(ys, axis_name, split_axis=1, concat_axis=0, tiled=True)
    out = jnp.einsum("ecd,tec->td", ys, combine)
    # aux is computed from local tokens; average over the ep group
    return out, lax.pmean(aux, axis_name)
