"""Pipeline parallelism (SPMD circular-shift schedule over a `pp` mesh axis).

Capability beyond the reference: MXNet had no pipeline parallelism — only
step-wise `PartialForward` (ref: src/executor/graph_executor.cc:68) and manual
inter-layer placement via `group2ctx` (ref: python/mxnet/symbol/symbol.py:1415).
The TPU-native design is the standard GPipe-style SPMD pipeline: each device
along the `pp` mesh axis holds a contiguous slice of the layer stack (the
stage), microbatches enter at stage 0, and activations rotate to the next
stage over ICI via `lax.ppermute` each tick. The whole schedule is a single
`lax.scan`, so XLA overlaps the ppermute transfer of tick t with the stage
compute of tick t+1. Backward is plain `jax.grad` through the scan/ppermute.

Run `spmd_pipeline` inside `jax.shard_map` over the `pp` axis; stage
parameters are the full stacked-layer pytree sharded on their leading
(layer-stack) axis with `PartitionSpec("pp", ...)`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["spmd_pipeline"]


def spmd_pipeline(stage_fn, stage_params, inputs, *, axis_name="pp"):
    """Run a microbatched pipeline; call inside shard_map over `axis_name`.

    stage_fn(stage_params, x) -> y : applies THIS stage's layer slice to one
        microbatch activation (shapes of x and y must match so activations can
        rotate between stages).
    stage_params : pytree whose leaves are this device's stage slice (shard_map
        already consumed the leading pp axis).
    inputs : (n_microbatches, *mb_shape) microbatched input activations,
        available on every device (only stage 0 reads them).

    Returns (n_microbatches, *mb_shape) outputs, replicated across the pp axis
    (the last stage's results are psum-broadcast).
    """
    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = inputs.shape[0]
    total_ticks = n_micro + n_stages - 1
    perm_fwd = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests a fresh microbatch; later stages consume the
        # activation that rotated in from the previous stage last tick.
        idx = jnp.clip(t, 0, n_micro - 1)
        fresh = lax.dynamic_index_in_dim(inputs, idx, axis=0, keepdims=False)
        x = jnp.where(stage == 0, fresh, state)
        y = stage_fn(stage_params, x)
        # the last stage retires microbatch t-(n_stages-1) at tick t
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        is_out = jnp.logical_and(stage == n_stages - 1, t >= n_stages - 1)
        updated = lax.dynamic_update_index_in_dim(outputs, y, out_idx, axis=0)
        outputs = jnp.where(is_out, updated, outputs)
        state = lax.ppermute(y, axis_name, perm_fwd)
        return (state, outputs), None

    # carry inits derive from `inputs` (inheriting its varying mesh axes) and
    # are additionally marked varying over the pipeline axis, since the
    # rotating state/output differ per stage.
    state0 = lax.pvary(inputs[0] * 0, (axis_name,))
    out0 = lax.pvary(inputs * 0, (axis_name,))
    (_, outputs), _ = lax.scan(tick, (state0, out0), jnp.arange(total_ticks))
    # broadcast the last stage's outputs to every pp rank so downstream code
    # (final LN / unembed / loss) is replicated over pp.
    mask = (stage == n_stages - 1).astype(inputs.dtype)
    return lax.psum(outputs * mask, axis_name)
