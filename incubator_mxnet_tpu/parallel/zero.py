"""ZeRO partitioning policies over the GSPMD `data` mesh axis.

The reference replicates optimizer state on every device (its kvstore
keeps one momentum buffer per worker); ZeRO (Rajbhandari et al., 2019)
observes that optimizer state, master weights, and gradients are only
*consumed* shard-wise by the elementwise update, so each device needs
1/N of them:

- ``zero1`` — optimizer state + f32 master weights live sharded over the
  ``data`` axis. Pinned in/out shardings make XLA derive
  reduce-scatter(grads) -> sharded update -> all-gather(params) in the
  one fused step program.
- ``zero2`` — additionally constrains the gradients themselves to the
  sharded layout (collectives.reduce_scatter_constraint), so the full
  replicated gradient never materializes: the update consumes only the
  local grad shard.
- ``replicated`` — the legacy placement (everything on every device).

Placement rule (``largest_axis_spec``): shard a tensor along its largest
axis when that axis divides the mesh size; otherwise fall back to
replication for that tensor. The decision is recorded per tensor so
tools and tests can audit exactly what was sharded
(fused.GluonTrainStep.shard_placements()).
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .collectives import reduce_scatter_constraint

__all__ = ["POLICIES", "resolve_policy", "largest_axis_spec", "place_tree",
           "pin_replicated", "shard_grads", "mesh_axis_size"]

POLICIES = ("replicated", "zero1", "zero2")


def resolve_policy(name):
    """Validate a shard-policy name ('' is accepted as 'replicated' —
    the unset-knob spelling). Raises ValueError listing what exists."""
    policy = name or "replicated"
    if policy not in POLICIES:
        raise ValueError(
            f"unknown shard policy {name!r} (MXTPU_SHARD_POLICY); "
            f"expected one of {POLICIES}")
    return policy


def mesh_axis_size(mesh, axis_name="data"):
    return mesh.shape[axis_name]


def largest_axis_spec(shape, n, axis_name="data"):
    """PartitionSpec sharding `shape`'s largest axis over `axis_name`,
    or P() (replicated) when no axis of at least n elements divides n —
    the divisibility-aware fallback: a ragged tensor costs its full
    bytes on every device rather than a padded or uneven layout."""
    shape = tuple(shape)
    if not shape or n <= 1:
        return P()
    axis = max(range(len(shape)), key=lambda i: shape[i])
    if shape[axis] >= n and shape[axis] % n == 0:
        return P(*([None] * axis + [axis_name]))
    return P()


def place_tree(tree, mesh, axis_name="data"):
    """device_put every array leaf of `tree` per largest_axis_spec.

    Returns (placed_tree, spec_tree): spec_tree mirrors the structure
    with the PartitionSpec actually used per leaf — the per-tensor
    record the policy knob promises."""
    n = mesh_axis_size(mesh, axis_name)

    def spec_of(d):
        if getattr(d, "ndim", None) is None:
            return P()
        return largest_axis_spec(d.shape, n, axis_name)

    specs = jax.tree_util.tree_map(spec_of, tree)
    placed = jax.tree_util.tree_map(
        lambda d, s: jax.device_put(d, NamedSharding(mesh, s)), tree, specs)
    return placed, specs


def pin_replicated(tree, mesh):
    """Constrain every array leaf of `tree` to the replicated layout.

    This is the bit-identity fence: GSPMD sharding propagation is
    *global*, so sharded optimizer-state inputs would otherwise leak
    their layout onto the params' forward uses and repartition the
    forward/backward matmuls — reordering their reductions and shifting
    losses by an ulp. Pinning the params entering the forward AND the
    gradients leaving the backward confines sharding to the elementwise
    update region, where partitioning commutes with the math exactly
    (measured: zero1/zero2 losses and weights stay bitwise equal to the
    replicated program)."""
    rep = NamedSharding(mesh, P())

    def pin(d):
        if getattr(d, "ndim", None) is None:
            return d
        return jax.lax.with_sharding_constraint(d, rep)

    return jax.tree_util.tree_map(pin, tree)


def shard_grads(grads, mesh, specs):
    """The zero2 gradient path inside a traced step: constrain each
    (already replicated-pinned) gradient to its sharded spec so the
    optimizer update reads only the local shard and XLA frees the full
    gradient right after the slice. Values are unchanged (a layout
    constraint, not a rewrite), so zero2 stays bit-identical to
    zero1/replicated."""
    return [reduce_scatter_constraint(g, mesh, s)
            for g, s in zip(grads, specs)]
