"""Parallelism & distribution (TPU-native).

Covers SURVEY §2.2: data parallel (mesh batch sharding + GSPMD all-reduce),
model/tensor parallel (weight sharding specs), sequence/context parallel
(ring attention over ICI), and multi-host data parallel (DCN collectives).
The reference implements these with kvstore reduce kernels, NCCL and
ps-lite; here they are sharding declarations + XLA collectives.
"""
from .mesh import (  # noqa: F401
    make_mesh, make_nd_mesh, data_sharding, replicated, local_mesh,
)
from . import collectives  # noqa: F401
from . import zero  # noqa: F401
from . import ring_attention  # noqa: F401
from .ring_attention import ring_attention as ring_attention_fn  # noqa: F401
from .ring_attention import ring_self_attention_sharded, ulysses_attention  # noqa: F401
from .collectives import allreduce, allgather, broadcast  # noqa: F401
