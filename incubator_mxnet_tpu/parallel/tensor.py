"""Tensor (intra-op) parallelism helpers: named sharding rules for parameters.

Capability beyond the reference (SURVEY §2.2: tensor parallel absent in
MXNet). TPU-native design: TP is *not* hand-written collectives — it is
sharding annotations on weight matrices under `jit` over a mesh with a `tp`
axis. XLA/GSPMD propagates the shardings through the einsums and inserts the
minimal all-reduce (the Megatron column-then-row pattern falls out of
sharding W1 on its output axis and W2 on its input axis).

`shard_params` applies regex -> PartitionSpec rules to a flat param dict;
`constrain` is `with_sharding_constraint` for activations.
"""
from __future__ import annotations

import re

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["shard_params", "make_shardings", "constrain", "column_parallel", "row_parallel"]


def column_parallel(mesh_axis="tp"):
    """Spec for a (in, out) weight split on its output features (Megatron W1)."""
    return P(None, mesh_axis)


def row_parallel(mesh_axis="tp"):
    """Spec for a (in, out) weight split on its input features (Megatron W2);
    GSPMD inserts the trailing all-reduce of the partial products."""
    return P(mesh_axis, None)


def make_shardings(params, rules, mesh):
    """Map a flat {name: array} dict to {name: NamedSharding} via the first
    matching (regex, PartitionSpec) rule; unmatched params are replicated.

    A rule spec may have fewer axes than the array rank; it is right-padded
    with None (replicated trailing dims stay replicated)."""
    out = {}
    for name, arr in params.items():
        spec = P()
        for pat, s in rules:
            if re.search(pat, name):
                spec = s
                break
        nd = getattr(arr, "ndim", 0)
        if len(tuple(spec)) > nd:
            raise ValueError(
                f"sharding rule for {name!r} has {len(tuple(spec))} axes but "
                f"the param is rank {nd}: {tuple(spec)}")
        parts = tuple(spec) + (None,) * (nd - len(tuple(spec)))
        out[name] = NamedSharding(mesh, P(*parts))
    return out


def shard_params(params, rules, mesh):
    """device_put each param onto its rule-derived NamedSharding."""
    shardings = make_shardings(params, rules, mesh)
    return {k: jax.device_put(v, shardings[k]) for k, v in params.items()}


def constrain(x, mesh, *spec):
    """Anchor an activation's sharding inside jit (GSPMD hint)."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))
