"""Ring attention: sequence/context parallelism over ICI.

Capability beyond the reference (SURVEY §5.7: the 2019 framework had only
bucketing + fused RNN for long sequences). TPU-native design: the sequence
axis is sharded over a mesh axis; K/V blocks rotate around the ring via
`lax.ppermute` while each device accumulates flash-style online-softmax
partial results for its local Q block — memory per device is O(T/N), and the
K/V transfers overlap compute around the ICI ring (cf. Liu et al., Ring
Attention with Blockwise Transformers, 2023).

Also provides the all-to-all ("Ulysses"-style) variant that reshards
sequence -> heads for regular attention.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ring_attention", "ring_self_attention_sharded", "ulysses_attention"]

_NEG_INF = -1e30


def _block_attn(q, k, v, bias=None):
    """One q-block x k-block partial attention with running-softmax stats.

    q: (B, Tq, H, D); k,v: (B, Tk, H, D). Returns (o_partial, lse_partial)
    where o_partial is unnormalized (sum of softmax-numerator * v) given the
    local max; summary stats merge across blocks.
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    if bias is not None:
        logits = logits + bias
    m = jnp.max(logits, axis=-1)  # (B, H, Tq)
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)  # (B, H, Tq)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o, m, l


def _merge(o1, m1, l1, o2, m2, l2):
    """Merge two online-softmax partials (flash-attention accumulate)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    l = l1 * a1 + l2 * a2
    o = o1 * a1.transpose(0, 2, 1)[..., None] + o2 * a2.transpose(0, 2, 1)[..., None]
    return o, m, l


def ring_attention(q, k, v, axis_name, causal=False):
    """Attention over a ring-sharded sequence; call inside shard_map.

    Per-device shapes: q,k,v (B, T_local, H, D); the global sequence is the
    concatenation over the `axis_name` mesh axis. Returns (B, T_local, H, D).
    """
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    def make_bias(block_idx):
        if not causal:
            return None
        # global positions: q rows at my*Tq..., k cols at block_idx*Tk...
        q_pos = my * Tq + jnp.arange(Tq)
        k_pos = block_idx * k.shape[1] + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        return jnp.where(mask, 0.0, _NEG_INF)[None, None]

    def body(carry, _):
        o, m, l, k_cur, v_cur, idx = carry
        o2, m2, l2 = _block_attn(q, k_cur, v_cur, make_bias(idx))
        o, m, l = _merge(o, m, l, o2, m2, l2)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        idx_nxt = lax.ppermute(idx, axis_name, perm)
        return (o, m, l, k_nxt, v_nxt, idx_nxt), None

    # derive carry inits from q so they inherit q's varying mesh axes (works
    # whether the enclosing shard_map spans just `axis_name` or more axes)
    zq = q * 0.0
    o0 = zq
    m0 = zq.sum(-1).transpose(0, 2, 1) + _NEG_INF  # (B, H, Tq)
    l0 = zq.sum(-1).transpose(0, 2, 1)
    (o, m, l, _, _, _), _ = lax.scan(body, (o0, m0, l0, k, v, my), None, length=n)
    return o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]


def ring_self_attention_sharded(q, k, v, mesh, axis_name="sp", causal=False):
    """Convenience wrapper: global (B, T, H, D) arrays, sequence sharded on
    `axis_name`; runs ring_attention under shard_map."""
    spec = P(None, axis_name, None, None)
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    sharding = NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(t, sharding) for t in (q, k, v))
    return fn(q, k, v)


def ulysses_attention(q, k, v, axis_name):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style): reshard
    sequence-sharded (B, T/N, H, D) to head-sharded (B, T, H/N, D) with
    all_to_all, run full attention locally, reshard back. Call inside
    shard_map over `axis_name`."""
    def seq_to_heads(t):
        # (B, T/N, H, D) -> (B, T, H/N, D)
        return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(t):
        return lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) / jnp.sqrt(qh.shape[-1]).astype(q.dtype)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, vh)
    return heads_to_seq(out)
