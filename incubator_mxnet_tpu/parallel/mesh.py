"""Device mesh helpers.

TPU-native replacement for the reference's device topology machinery
(ref: src/kvstore/gpu_topology.h link-weight spanning trees): on TPU the
interconnect is the ICI torus and XLA already routes collectives optimally,
so "topology" reduces to declaring a `jax.sharding.Mesh` with named axes.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["make_mesh", "make_nd_mesh", "data_sharding", "replicated", "local_mesh"]


def _devices_of(contexts):
    from ..context import Context

    devs = []
    for c in contexts:
        if isinstance(c, Context):
            devs.append(c.jax_device())
        else:
            devs.append(c)
    return devs


def make_mesh(contexts=None, axis_names=("data",)):
    """1-D mesh over the given contexts (or all local devices)."""
    devs = _devices_of(contexts) if contexts else jax.devices()
    return Mesh(np.array(devs), axis_names=axis_names[:1])


def make_nd_mesh(axis_sizes: dict, devices=None):
    """N-D mesh, e.g. {'dp': 2, 'tp': 4}. Sizes must multiply to #devices."""
    devices = devices if devices is not None else jax.devices()
    names = tuple(axis_sizes.keys())
    sizes = tuple(axis_sizes.values())
    n = int(np.prod(sizes))
    if n != len(devices):
        raise ValueError(f"mesh {axis_sizes} needs {n} devices, have {len(devices)}")
    arr = np.array(devices).reshape(sizes)
    return Mesh(arr, axis_names=names)


def data_sharding(mesh, ndim, axis=0, mesh_axis="data"):
    spec = [None] * ndim
    spec[axis] = mesh_axis
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh):
    return NamedSharding(mesh, PartitionSpec())


def local_mesh(n=None, axis_names=("data",)):
    devs = jax.devices()
    if n is not None:
        devs = devs[:n]
    return Mesh(np.array(devs), axis_names=axis_names[:1])
