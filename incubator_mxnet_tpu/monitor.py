"""Monitor: periodic statistics over executor-visible arrays
(capability parity with python/mxnet/monitor.py Monitor + the executor
monitor_callback hooks at graph_executor.cc:1239).

Design note for the TPU build: the executor is one fused XLA program, so
per-internal-op taps don't exist — the observable surface is the bound
arguments and outputs, which `toc()` sweeps through the name filter. The
`install`/`stat_helper` callback protocol is kept for API parity (custom
evaluators can still push taps in)."""
from __future__ import annotations

import logging
import re

from .ndarray.ndarray import NDArray

__all__ = ["Monitor"]


def _mean_abs(arr):
    a = arr.asnumpy() if isinstance(arr, NDArray) else arr
    return float(abs(a).mean())


class Monitor:
    """Every `interval` tic/toc cycles, collect stat_func over all arrays
    whose name matches `pattern`."""

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False,
                 monitor_all=False):
        self.interval = interval
        self.stat_func = stat_func or _mean_abs
        self.sort = sort
        self.monitor_all = monitor_all
        self._name_filter = re.compile(pattern)
        self._exes = []
        self._taps = []
        self._step = 0
        self._armed = False

    # -- executor wiring ---------------------------------------------------
    def install(self, exe):
        exe.set_monitor_callback(self.stat_helper, self.monitor_all)
        self._exes.append(exe)

    def stat_helper(self, name, arr):
        """Callback protocol entry: record one named array if armed."""
        if self._armed and self._name_filter.match(name):
            self._taps.append((self._step, name, self.stat_func(arr)))

    # -- per-batch protocol ------------------------------------------------
    def tic(self):
        """Arm collection on the interval boundary (ref: Monitor.tic)."""
        if self._step % self.interval == 0:
            self._sync()
            self._taps = []
            self._armed = True
        self._step += 1

    def toc(self):
        """Disarm and return [(step, name, stat-string)] collected since
        tic, sweeping args + outputs of every installed executor."""
        if not self._armed:
            return []
        self._sync()
        for exe in self._exes:
            named = list(exe.arg_dict.items())
            named += list(zip(exe._symbol.list_outputs(), exe.outputs))
            for name, arr in named:
                if self._name_filter.match(name):
                    self._taps.append((self._step, name, self.stat_func(arr)))
        self._armed = False
        taps, self._taps = self._taps, []
        if self.sort:
            taps.sort(key=lambda t: t[1])
        return [(step, name, str(value)) for step, name, value in taps]

    def toc_print(self):
        for step, name, value in self.toc():
            logging.info("Batch: %7d %30s %s", step, name, value)

    def _sync(self):
        for exe in self._exes:
            for out in exe.outputs:
                out.wait_to_read()
