"""Monitor: tap intermediate outputs during training
(ref: python/mxnet/monitor.py:33, executor monitor_callback hooks
graph_executor.cc:1239)."""
from __future__ import annotations

import logging
import re

from .ndarray.ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False, monitor_all=False):
        if stat_func is None:
            def asum_stat(x):
                return float(abs(x.asnumpy()).mean()) if isinstance(x, NDArray) else float(abs(x).mean())

            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort
        self.monitor_all = monitor_all

    def install(self, exe):
        exe.set_monitor_callback(self.stat_helper, self.monitor_all)
        self.exes.append(exe)

    def stat_helper(self, name, arr):
        if not self.activated or not self.re_prog.match(name):
            return
        self.queue.append((self.step, name, self.stat_func(arr)))

    def tic(self):
        if self.step % self.interval == 0:
            for exe in self.exes:
                for o in exe.outputs:
                    o.wait_to_read()
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        for exe in self.exes:
            for o in exe.outputs:
                o.wait_to_read()
            # record all outputs (whole-graph jit means internals are fused
            # away; outputs + args are observable)
            for name, arr in list(exe.arg_dict.items()):
                if self.re_prog.match(name):
                    self.queue.append((self.step, name, self.stat_func(arr)))
            for name, o in zip(exe._symbol.list_outputs(), exe.outputs):
                if self.re_prog.match(name):
                    self.queue.append((self.step, name, self.stat_func(o)))
        self.activated = False
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v_list in self.queue:
            res.append((n, k, str(v_list)))
        self.queue = []
        return res

    def toc_print(self):
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k, v)
