"""Deployment / predict API.

TPU-native analog of the reference's standalone prediction stack
(ref: SURVEY §2 N20 `src/c_api/c_predict_api.cc` — load symbol+params, bind,
forward — and N35 amalgamation's predict-only build, plus N28's
TensorRT-as-inference-engine role).

Instead of a JSON graph re-executed by a runtime, the deployment artifact is
the **compiled program itself**: `jax.export` serializes the jitted forward
(StableHLO bytes) with the trained parameters, and `Predictor` replays it
with zero framework overhead — XLA AOT is the TPU's TensorRT.

Artifact layout for prefix `model`:
  model-predict.stablehlo   serialized StableHLO program (params are inputs)
  model-predict.npz         trained arg/aux params in call order
  model-symbol.json         the symbol graph (for inspection/retraining)
"""
from __future__ import annotations

import json
import os

import numpy as np

__all__ = ["export_predictor", "Predictor"]


def export_predictor(prefix, symbol, arg_params, aux_params, input_shapes,
                     dtype="float32"):
    """AOT-export a symbol + trained params as a standalone predict artifact.

    input_shapes: dict name -> shape for the data inputs (everything that is
    not a parameter). Mirrors `MXPredCreate`'s (symbol json, params, input
    shapes) triple (ref: c_predict_api.cc).
    """
    import jax
    import jax.numpy as jnp
    from jax import export as jexport

    names = symbol.list_arguments()
    aux_names = symbol.list_auxiliary_states()
    param_names = [n for n in names if n not in input_shapes]
    missing = [n for n in param_names if n not in arg_params]
    if missing:
        raise ValueError(f"missing params for export: {missing}")

    eval_fn = symbol.make_eval_fn()

    def forward(inputs, params, aux):
        args = {}
        args.update(params)
        args.update(inputs)
        outs, _ = eval_fn(args, aux, None, False)
        return tuple(outs)

    inputs_spec = {k: jax.ShapeDtypeStruct(tuple(v), jnp.dtype(dtype))
                   for k, v in input_shapes.items()}
    params_np = {k: np.asarray(arg_params[k].asnumpy()
                               if hasattr(arg_params[k], "asnumpy")
                               else arg_params[k]) for k in param_names}
    aux_np = {k: np.asarray(aux_params[k].asnumpy()
                            if hasattr(aux_params[k], "asnumpy")
                            else aux_params[k]) for k in aux_names}
    params_spec = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                   for k, v in params_np.items()}
    aux_spec = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                for k, v in aux_np.items()}

    exported = jexport.export(jax.jit(forward))(inputs_spec, params_spec,
                                                aux_spec)
    with open(prefix + "-predict.stablehlo", "wb") as f:
        f.write(exported.serialize())
    np.savez(prefix + "-predict.npz",
             **{f"arg:{k}": v for k, v in params_np.items()},
             **{f"aux:{k}": v for k, v in aux_np.items()},
             __meta__=np.frombuffer(json.dumps({
                 "input_shapes": {k: list(v) for k, v in input_shapes.items()},
                 "dtype": dtype,
                 "outputs": symbol.list_outputs(),
             }).encode(), dtype=np.uint8))
    symbol.save(prefix + "-symbol.json")
    return prefix + "-predict.stablehlo"


class Predictor:
    """Standalone predictor over an exported artifact
    (ref: c_predict_api.cc MXPredCreate/SetInput/Forward/GetOutput).

    Loads the AOT StableHLO program — no graph rebuild, no tracing; first
    call executes the precompiled computation directly.
    """

    def __init__(self, prefix):
        from jax import export as jexport

        with open(prefix + "-predict.stablehlo", "rb") as f:
            self._exported = jexport.deserialize(bytearray(f.read()))
        z = np.load(prefix + "-predict.npz")
        meta = json.loads(bytes(z["__meta__"]).decode())
        self._input_shapes = {k: tuple(v)
                              for k, v in meta["input_shapes"].items()}
        self._outputs_names = meta["outputs"]
        self._dtype = meta["dtype"]
        self._params = {k[4:]: z[k] for k in z.files if k.startswith("arg:")}
        self._aux = {k[4:]: z[k] for k in z.files if k.startswith("aux:")}
        self._inputs = {}
        self._out = None

    def set_input(self, name, data):
        if name not in self._input_shapes:
            raise KeyError(name)
        self._inputs[name] = np.asarray(data, self._dtype)

    def forward(self, **inputs):
        for k, v in inputs.items():
            self.set_input(k, v)
        got = {k: self._inputs[k] for k in self._input_shapes}
        self._out = self._exported.call(got, self._params, self._aux)
        return self._out

    def get_output(self, index=0):
        out = self._out[index] if isinstance(self._out, (list, tuple)) \
            else self._out
        return np.asarray(out)

    @property
    def output_names(self):
        return list(self._outputs_names)
