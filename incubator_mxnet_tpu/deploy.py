"""Deployment / predict API.

TPU-native analog of the reference's standalone prediction stack
(ref: SURVEY §2 N20 `src/c_api/c_predict_api.cc` — load symbol+params, bind,
forward — and N35 amalgamation's predict-only build, plus N28's
TensorRT-as-inference-engine role).

Instead of a JSON graph re-executed by a runtime, the deployment artifact is
the **compiled program itself**: `jax.export` serializes the jitted forward
(StableHLO bytes) with the trained parameters, and `Predictor` replays it
with zero framework overhead — XLA AOT is the TPU's TensorRT.

Artifact layout for prefix `model`:
  model-predict.stablehlo   serialized StableHLO program (params are inputs)
  model-predict.npz         trained arg/aux params in call order
  model-predict.mxp         single-file C-embedding artifact (StableHLO +
                            params) consumed by src/predict.cc over PJRT
  model-symbol.json         the symbol graph (for inspection/retraining)
"""
from __future__ import annotations

import json
import os

import numpy as np

__all__ = ["export_predictor", "Predictor"]


def export_predictor(prefix, symbol, arg_params, aux_params, input_shapes,
                     dtype="float32"):
    """AOT-export a symbol + trained params as a standalone predict artifact.

    input_shapes: dict name -> shape for the data inputs (everything that is
    not a parameter). Mirrors `MXPredCreate`'s (symbol json, params, input
    shapes) triple (ref: c_predict_api.cc).
    """
    import jax
    import jax.numpy as jnp
    from jax import export as jexport

    names = symbol.list_arguments()
    aux_names = symbol.list_auxiliary_states()
    param_names = [n for n in names if n not in input_shapes]
    missing = [n for n in param_names if n not in arg_params]
    if missing:
        raise ValueError(f"missing params for export: {missing}")

    eval_fn = symbol.make_eval_fn()

    def forward(inputs, params, aux):
        args = {}
        args.update(params)
        args.update(inputs)
        outs, _ = eval_fn(args, aux, None, False)
        return tuple(outs)

    inputs_spec = {k: jax.ShapeDtypeStruct(tuple(v), jnp.dtype(dtype))
                   for k, v in input_shapes.items()}
    params_np = {k: np.asarray(arg_params[k].asnumpy()
                               if hasattr(arg_params[k], "asnumpy")
                               else arg_params[k]) for k in param_names}
    aux_np = {k: np.asarray(aux_params[k].asnumpy()
                            if hasattr(aux_params[k], "asnumpy")
                            else aux_params[k]) for k in aux_names}
    params_spec = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                   for k, v in params_np.items()}
    aux_spec = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                for k, v in aux_np.items()}

    exported = jexport.export(jax.jit(forward))(inputs_spec, params_spec,
                                                aux_spec)
    with open(prefix + "-predict.stablehlo", "wb") as f:
        f.write(exported.serialize())
    np.savez(prefix + "-predict.npz",
             **{f"arg:{k}": v for k, v in params_np.items()},
             **{f"aux:{k}": v for k, v in aux_np.items()},
             __meta__=np.frombuffer(json.dumps({
                 "input_shapes": {k: list(v) for k, v in input_shapes.items()},
                 "dtype": dtype,
                 "outputs": symbol.list_outputs(),
             }).encode(), dtype=np.uint8))
    symbol.save(prefix + "-symbol.json")
    try:
        _write_mxp(prefix + "-predict.mxp", exported, input_shapes, dtype,
                   params_np, aux_np, symbol.list_outputs())
    except KeyError as e:  # dtype outside the C ABI's table
        import warnings

        warnings.warn(f"skipping C-embedding .mxp artifact: unsupported "
                      f"dtype {e}; the Python Predictor artifacts were "
                      f"written normally")
    return prefix + "-predict.stablehlo"


class Predictor:
    """Standalone predictor over an exported artifact
    (ref: c_predict_api.cc MXPredCreate/SetInput/Forward/GetOutput).

    Loads the AOT StableHLO program — no graph rebuild, no tracing; first
    call executes the precompiled computation directly.
    """

    def __init__(self, prefix):
        from jax import export as jexport

        with open(prefix + "-predict.stablehlo", "rb") as f:
            self._exported = jexport.deserialize(bytearray(f.read()))
        z = np.load(prefix + "-predict.npz")
        meta = json.loads(bytes(z["__meta__"]).decode())
        self._input_shapes = {k: tuple(v)
                              for k, v in meta["input_shapes"].items()}
        self._outputs_names = meta["outputs"]
        self._dtype = meta["dtype"]
        self._params = {k[4:]: z[k] for k in z.files if k.startswith("arg:")}
        self._aux = {k[4:]: z[k] for k in z.files if k.startswith("aux:")}
        self._inputs = {}
        self._out = None

    def set_input(self, name, data):
        if name not in self._input_shapes:
            raise KeyError(name)
        self._inputs[name] = np.asarray(data, self._dtype)

    def forward(self, **inputs):
        for k, v in inputs.items():
            self.set_input(k, v)
        got = {k: self._inputs[k] for k in self._input_shapes}
        self._out = self._exported.call(got, self._params, self._aux)
        return self._out

    def get_output(self, index=0):
        out = self._out[index] if isinstance(self._out, (list, tuple)) \
            else self._out
        return np.asarray(out)

    @property
    def output_names(self):
        return list(self._outputs_names)


# ---------------------------------------------------------------------------
# C embedding artifact (.mxp): single-file StableHLO + params consumed by
# src/predict.cc over the PJRT C API (ref role: c_predict_api.cc — the
# C/mobile/JVM load-and-run path; include/mxtpu_predict.h is the header)
# ---------------------------------------------------------------------------

_DTYPE_CODES = {"float32": 0, "float64": 1, "int32": 2, "int64": 3,
                "uint8": 4, "int8": 5, "bfloat16": 6, "float16": 7,
                "bool": 8, "uint32": 9, "uint64": 10, "int16": 11,
                "uint16": 12}


def _write_mxp(path, exported, input_shapes, in_dtype, params_np, aux_np,
               output_names):
    """Binary artifact: header + per-arg specs (in the program's flat
    calling order: sorted inputs, sorted params, sorted aux — jax flattens
    dicts in key order) + CompileOptionsProto + StableHLO + param data."""
    import struct

    from jax._src import compiler as _jc

    copts = _jc.get_compile_options(num_replicas=1,
                                    num_partitions=1).SerializeAsString()
    shlo = exported.mlir_module_serialized

    args = []  # (kind, name, np_dtype_name, shape, payload-or-None)
    for name in sorted(input_shapes):
        args.append((0, name, in_dtype, tuple(input_shapes[name]), None))
    for name in sorted(params_np):
        v = params_np[name]
        args.append((1, name, v.dtype.name, v.shape, v))
    for name in sorted(aux_np):
        v = aux_np[name]
        args.append((1, name, v.dtype.name, v.shape, v))

    # jax.export DCEs arguments the program never reads
    # (module_kept_var_idx); the artifact must list exactly the args the
    # compiled main accepts, or the C runtime passes too many buffers
    kept = getattr(exported, "module_kept_var_idx", None)
    if kept is not None:
        args = [args[i] for i in kept]

    outs = [(o.dtype.name if hasattr(o, "dtype") else "float32",
             tuple(getattr(o, "shape", ())), n)
            for o, n in zip(exported.out_avals, output_names)]

    with open(path, "wb") as f:
        f.write(b"MXTPU001")
        f.write(struct.pack("<IIQQ", len(args), len(outs),
                            len(copts), len(shlo)))
        for kind, name, dt, shape, payload in args:
            nb = np.dtype(dt).itemsize * int(np.prod(shape)) if shape else \
                np.dtype(dt).itemsize
            nm = name.encode()
            f.write(struct.pack("<BBBB", kind, _DTYPE_CODES[dt],
                                len(shape), 0))
            f.write(struct.pack("<I", len(nm)))
            f.write(nm)
            f.write(struct.pack(f"<{len(shape)}q", *shape))
            f.write(struct.pack("<Q", nb))
        for dt, shape, name in outs:
            nm = name.encode()
            f.write(struct.pack("<BBH", _DTYPE_CODES[dt], len(shape), 0))
            f.write(struct.pack("<I", len(nm)))
            f.write(nm)
            f.write(struct.pack(f"<{len(shape)}q", *shape))
        f.write(copts)
        f.write(shlo)
        for kind, _name, _dt, _shape, payload in args:
            if kind == 1:
                f.write(np.ascontiguousarray(payload).tobytes())
    return path
