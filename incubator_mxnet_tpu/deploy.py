"""Deployment / predict API.

TPU-native analog of the reference's standalone prediction stack
(ref: SURVEY §2 N20 `src/c_api/c_predict_api.cc` — load symbol+params, bind,
forward — and N35 amalgamation's predict-only build, plus N28's
TensorRT-as-inference-engine role).

Instead of a JSON graph re-executed by a runtime, the deployment artifact is
the **compiled program itself**: `jax.export` serializes the jitted forward
(StableHLO bytes) with the trained parameters, and `Predictor` replays it
with zero framework overhead — XLA AOT is the TPU's TensorRT.

Artifact layout for prefix `model`:
  model-predict.stablehlo   serialized StableHLO program (params are inputs)
  model-predict.npz         trained arg/aux params in call order
  model-predict.mxp         single-file C-embedding artifact (StableHLO +
                            params) consumed by src/predict.cc over PJRT
  model-symbol.json         the symbol graph (for inspection/retraining)
"""
from __future__ import annotations

import json
import os

import numpy as np

__all__ = ["export_predictor", "Predictor", "export_trainer",
           "TrainerArtifact"]


def export_predictor(prefix, symbol, arg_params, aux_params, input_shapes,
                     dtype="float32"):
    """AOT-export a symbol + trained params as a standalone predict artifact.

    input_shapes: dict name -> shape for the data inputs (everything that is
    not a parameter). Mirrors `MXPredCreate`'s (symbol json, params, input
    shapes) triple (ref: c_predict_api.cc).
    """
    import jax
    import jax.numpy as jnp
    from jax import export as jexport

    names = symbol.list_arguments()
    aux_names = symbol.list_auxiliary_states()
    param_names = [n for n in names if n not in input_shapes]
    missing = [n for n in param_names if n not in arg_params]
    if missing:
        raise ValueError(f"missing params for export: {missing}")

    eval_fn = symbol.make_eval_fn()

    def forward(inputs, params, aux):
        args = {}
        args.update(params)
        args.update(inputs)
        outs, _ = eval_fn(args, aux, None, False)
        return tuple(outs)

    inputs_spec = {k: jax.ShapeDtypeStruct(tuple(v), jnp.dtype(dtype))
                   for k, v in input_shapes.items()}
    params_np = {k: np.asarray(arg_params[k].asnumpy()
                               if hasattr(arg_params[k], "asnumpy")
                               else arg_params[k]) for k in param_names}
    aux_np = {k: np.asarray(aux_params[k].asnumpy()
                            if hasattr(aux_params[k], "asnumpy")
                            else aux_params[k]) for k in aux_names}
    params_spec = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                   for k, v in params_np.items()}
    aux_spec = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                for k, v in aux_np.items()}

    exported = jexport.export(jax.jit(forward))(inputs_spec, params_spec,
                                                aux_spec)
    with open(prefix + "-predict.stablehlo", "wb") as f:
        f.write(exported.serialize())
    np.savez(prefix + "-predict.npz",
             **{f"arg:{k}": v for k, v in params_np.items()},
             **{f"aux:{k}": v for k, v in aux_np.items()},
             __meta__=np.frombuffer(json.dumps({
                 "input_shapes": {k: list(v) for k, v in input_shapes.items()},
                 "dtype": dtype,
                 "outputs": symbol.list_outputs(),
             }).encode(), dtype=np.uint8))
    symbol.save(prefix + "-symbol.json")
    try:
        _write_mxp(prefix + "-predict.mxp", exported, input_shapes, dtype,
                   params_np, aux_np, symbol.list_outputs())
    except KeyError as e:  # dtype outside the C ABI's table
        import warnings

        warnings.warn(f"skipping C-embedding .mxp artifact: unsupported "
                      f"dtype {e}; the Python Predictor artifacts were "
                      f"written normally")
    return prefix + "-predict.stablehlo"


class Predictor:
    """Standalone predictor over an exported artifact
    (ref: c_predict_api.cc MXPredCreate/SetInput/Forward/GetOutput).

    Loads the AOT StableHLO program — no graph rebuild, no tracing; first
    call executes the precompiled computation directly.
    """

    def __init__(self, prefix):
        from jax import export as jexport

        with open(prefix + "-predict.stablehlo", "rb") as f:
            self._exported = jexport.deserialize(bytearray(f.read()))
        z = np.load(prefix + "-predict.npz")
        meta = json.loads(bytes(z["__meta__"]).decode())
        self._input_shapes = {k: tuple(v)
                              for k, v in meta["input_shapes"].items()}
        self._outputs_names = meta["outputs"]
        self._dtype = meta["dtype"]
        self._params = {k[4:]: z[k] for k in z.files if k.startswith("arg:")}
        self._aux = {k[4:]: z[k] for k in z.files if k.startswith("aux:")}
        self._inputs = {}
        self._out = None

    def set_input(self, name, data):
        if name not in self._input_shapes:
            raise KeyError(name)
        self._inputs[name] = np.asarray(data, self._dtype)

    def forward(self, **inputs):
        for k, v in inputs.items():
            self.set_input(k, v)
        got = {k: self._inputs[k] for k in self._input_shapes}
        self._out = self._exported.call(got, self._params, self._aux)
        return self._out

    def get_output(self, index=0):
        out = self._out[index] if isinstance(self._out, (list, tuple)) \
            else self._out
        return np.asarray(out)

    @property
    def output_names(self):
        return list(self._outputs_names)


# ---------------------------------------------------------------------------
# C embedding artifact (.mxp): single-file StableHLO + params consumed by
# src/predict.cc over the PJRT C API (ref role: c_predict_api.cc — the
# C/mobile/JVM load-and-run path; include/mxtpu_predict.h is the header)
# ---------------------------------------------------------------------------

_DTYPE_CODES = {"float32": 0, "float64": 1, "int32": 2, "int64": 3,
                "uint8": 4, "int8": 5, "bfloat16": 6, "float16": 7,
                "bool": 8, "uint32": 9, "uint64": 10, "int16": 11,
                "uint16": 12}


# ---------------------------------------------------------------------------
# Training artifact (.mxt): the ENTIRE train step — forward, backward,
# optimizer update — AOT-compiled as one StableHLO program, so a C caller
# trains by looping one executable with device-resident state buffers.
# This is the TPU-native answer to the reference's create/train C ABI
# (ref: src/c_api/c_api.cc NDArray/executor/KVStore entry points +
# cpp-package/example/mlp.cpp): instead of re-exposing a graph builder to
# C, the graph is built and differentiated in Python once, and C embeds
# the compiled result.  Consumed by src/train.cc (header: include/mxtpu.h).
# ---------------------------------------------------------------------------


def export_gluon_predictor(prefix, net, input_shapes, dtype="float32"):
    """One-call deployment export for a trained HybridBlock: traces the
    block to a Symbol (the SymbolBlock bridge), splits its parameters into
    arg/aux, and AOT-compiles the predict artifact.

    input_shapes: dict name -> shape, e.g. {"data": (1, 3, 224, 224)}.
    For multi-input blocks the dict's ITERATION ORDER is the positional
    order of the block's forward() arguments (names label the Predictor
    inputs; they do not reorder the trace).

    Returns what export_predictor returns: the `-predict.stablehlo` path;
    a single-file `-predict.mxp` is written alongside when every tensor
    dtype has a wire code (a warning is emitted otherwise)."""
    sym_out, arg_params, aux_params = net._symbol_and_params(
        *input_shapes.keys())
    return export_predictor(prefix, sym_out, arg_params, aux_params,
                            dict(input_shapes), dtype=dtype)


def export_trainer(prefix, net, loss_fn, optimizer, x_shape, y_shape,
                   dtype="float32", label_dtype="float32"):
    """AOT-export net+loss+optimizer as a standalone TRAINING artifact.

    Writes `prefix + "-train.mxt"` (single-file C-embedding artifact:
    StableHLO train step + initial param/optimizer-state payloads) and
    `prefix + "-train.stablehlo"` (jax.export serialization for the Python
    `TrainerArtifact` replay).  The program's signature is
        (states..., x, y, __seed, __lr, __t) -> (states'..., loss)
    where the first len(states) outputs carry the SAME names as the state
    args — the embedding runtime feeds each step's state outputs back as
    the next step's state inputs (the kvstore/optimizer round trip of the
    reference, collapsed into buffer rotation).
    """
    import jax
    import jax.numpy as jnp
    from jax import export as jexport

    from . import fused
    from .ndarray.ndarray import NDArray

    step = fused.GluonTrainStep(net, loss_fn, optimizer)
    x0 = NDArray(jnp.zeros(tuple(x_shape), jnp.dtype(dtype)))
    y0 = NDArray(jnp.zeros(tuple(y_shape), jnp.dtype(label_dtype)))
    step._build(x0, y0)

    # named flat state: every param, then every optimizer-state leaf
    state_names, state_vals = [], []
    for n, d in zip(step.names, step._params):
        state_names.append("param:" + n)
        state_vals.append(d)
    state_struct = []  # per-param recipe: None | -1 (single) | k (tuple)
    for n, s in zip(step.names, step._states):
        if s is None:
            state_struct.append(None)
        elif isinstance(s, tuple):
            state_struct.append(len(s))
            for j, e in enumerate(s):
                state_names.append(f"opt:{n}:{j}")
                state_vals.append(e)
        else:
            state_struct.append(-1)
            state_names.append("opt:" + n)
            state_vals.append(s)
    n_params = len(step.names)

    def flat_step(state, x, y, seed, lr, t):
        params = list(state[:n_params])
        it = iter(state[n_params:])
        states = []
        for spec in state_struct:
            if spec is None:
                states.append(None)
            elif spec == -1:
                states.append(next(it))
            else:
                states.append(tuple(next(it) for _ in range(spec)))
        key = jax.random.PRNGKey(seed)
        loss, new_params, new_states = step._step_fn(
            params, states, x, y, key, lr, t)
        out = list(new_params)
        for spec, st in zip(state_struct, new_states):
            if spec is None:
                continue
            if spec == -1:
                out.append(st)
            else:
                out.extend(st)
        return tuple(out) + (loss,)

    state_spec = tuple(jax.ShapeDtypeStruct(np.shape(v),
                                            np.asarray(v).dtype)
                       for v in state_vals)
    scalar = jax.ShapeDtypeStruct((), jnp.float32)
    exported = jexport.export(jax.jit(flat_step))(
        state_spec,
        jax.ShapeDtypeStruct(tuple(x_shape), jnp.dtype(dtype)),
        jax.ShapeDtypeStruct(tuple(y_shape), jnp.dtype(label_dtype)),
        jax.ShapeDtypeStruct((), jnp.uint32), scalar, scalar)

    with open(prefix + "-train.stablehlo", "wb") as f:
        f.write(exported.serialize())
    np.savez(prefix + "-train.npz",
             **{f"state:{n}": np.asarray(v)
                for n, v in zip(state_names, state_vals)},
             __meta__=np.frombuffer(json.dumps({
                 "state_names": state_names,
                 "x_shape": list(x_shape), "y_shape": list(y_shape),
                 "dtype": dtype, "label_dtype": label_dtype,
                 "lr": float(getattr(optimizer, "lr", 0.01)),
             }).encode(), dtype=np.uint8))
    _write_mxt(prefix + "-train.mxt", exported, state_names, state_vals,
               {"x": (tuple(x_shape), dtype),
                "y": (tuple(y_shape), label_dtype)},
               float(getattr(optimizer, "lr", 0.01)))
    return prefix + "-train.mxt"


def _write_mxt(path, exported, state_names, state_vals, input_specs,
               default_lr):
    """MXTPU002 single-file training artifact: like .mxp, plus a default
    learning rate and named outputs wiring state feedback (output name ==
    state arg name)."""
    import struct

    from jax._src import compiler as _jc

    copts = _jc.get_compile_options(num_replicas=1,
                                    num_partitions=1).SerializeAsString()
    shlo = exported.mlir_module_serialized

    args = []  # (kind, name, dtype_name, shape, payload-or-None)
    for name, v in zip(state_names, state_vals):
        v = np.asarray(v)
        args.append((1, name, v.dtype.name, v.shape, v))
    for name, (shape, dt) in input_specs.items():
        args.append((0, name, dt, shape, None))
    for name, dt in (("__seed", "uint32"), ("__lr", "float32"),
                     ("__t", "float32")):
        args.append((0, name, dt, (), None))

    kept = getattr(exported, "module_kept_var_idx", None)
    if kept is not None:
        args = [args[i] for i in kept]

    out_names = list(state_names) + ["__loss"]
    outs = [(o.dtype.name if hasattr(o, "dtype") else "float32",
             tuple(getattr(o, "shape", ())), n)
            for o, n in zip(exported.out_avals, out_names)]

    with open(path, "wb") as f:
        f.write(b"MXTPU002")
        f.write(struct.pack("<IIQQ", len(args), len(outs),
                            len(copts), len(shlo)))
        f.write(struct.pack("<fI", default_lr, 0))
        for kind, name, dt, shape, payload in args:
            nb = np.dtype(dt).itemsize * int(np.prod(shape)) if shape else \
                np.dtype(dt).itemsize
            nm = name.encode()
            f.write(struct.pack("<BBBB", kind, _DTYPE_CODES[dt],
                                len(shape), 0))
            f.write(struct.pack("<I", len(nm)))
            f.write(nm)
            f.write(struct.pack(f"<{len(shape)}q", *shape))
            f.write(struct.pack("<Q", nb))
        for dt, shape, name in outs:
            nm = name.encode()
            f.write(struct.pack("<BBH", _DTYPE_CODES[dt], len(shape), 0))
            f.write(struct.pack("<I", len(nm)))
            f.write(nm)
            f.write(struct.pack(f"<{len(shape)}q", *shape))
        f.write(copts)
        f.write(shlo)
        for kind, _name, _dt, _shape, payload in args:
            if kind == 1:
                f.write(np.ascontiguousarray(payload).tobytes())
    return path


class TrainerArtifact:
    """Python replay of an exported training artifact — the same program a
    C embedder runs (src/train.cc), driven through jax.export.  Used to
    validate artifacts without a PJRT plugin and as the reference
    implementation for the C runtime's step loop."""

    def __init__(self, prefix):
        from jax import export as jexport

        with open(prefix + "-train.stablehlo", "rb") as f:
            self._exported = jexport.deserialize(bytearray(f.read()))
        z = np.load(prefix + "-train.npz")
        meta = json.loads(bytes(z["__meta__"]).decode())
        self.state_names = meta["state_names"]
        self._state = [np.asarray(z["state:" + n]) for n in self.state_names]
        self.lr = float(meta["lr"])
        self._t = 0

    def step(self, x, y, seed=None):
        self._t += 1
        out = self._exported.call(
            tuple(self._state), np.asarray(x), np.asarray(y),
            np.uint32(self._t if seed is None else seed),
            np.float32(self.lr), np.float32(self._t))
        self._state = [np.asarray(o) for o in out[:len(self._state)]]
        return float(out[-1])

    def get_state(self, name):
        return self._state[self.state_names.index(name)]


def _write_mxp(path, exported, input_shapes, in_dtype, params_np, aux_np,
               output_names):
    """Binary artifact: header + per-arg specs (in the program's flat
    calling order: sorted inputs, sorted params, sorted aux — jax flattens
    dicts in key order) + CompileOptionsProto + StableHLO + param data."""
    import struct

    from jax._src import compiler as _jc

    copts = _jc.get_compile_options(num_replicas=1,
                                    num_partitions=1).SerializeAsString()
    shlo = exported.mlir_module_serialized

    args = []  # (kind, name, np_dtype_name, shape, payload-or-None)
    for name in sorted(input_shapes):
        args.append((0, name, in_dtype, tuple(input_shapes[name]), None))
    for name in sorted(params_np):
        v = params_np[name]
        args.append((1, name, v.dtype.name, v.shape, v))
    for name in sorted(aux_np):
        v = aux_np[name]
        args.append((1, name, v.dtype.name, v.shape, v))

    # jax.export DCEs arguments the program never reads
    # (module_kept_var_idx); the artifact must list exactly the args the
    # compiled main accepts, or the C runtime passes too many buffers
    kept = getattr(exported, "module_kept_var_idx", None)
    if kept is not None:
        args = [args[i] for i in kept]

    outs = [(o.dtype.name if hasattr(o, "dtype") else "float32",
             tuple(getattr(o, "shape", ())), n)
            for o, n in zip(exported.out_avals, output_names)]

    with open(path, "wb") as f:
        f.write(b"MXTPU001")
        f.write(struct.pack("<IIQQ", len(args), len(outs),
                            len(copts), len(shlo)))
        for kind, name, dt, shape, payload in args:
            nb = np.dtype(dt).itemsize * int(np.prod(shape)) if shape else \
                np.dtype(dt).itemsize
            nm = name.encode()
            f.write(struct.pack("<BBBB", kind, _DTYPE_CODES[dt],
                                len(shape), 0))
            f.write(struct.pack("<I", len(nm)))
            f.write(nm)
            f.write(struct.pack(f"<{len(shape)}q", *shape))
            f.write(struct.pack("<Q", nb))
        for dt, shape, name in outs:
            nm = name.encode()
            f.write(struct.pack("<BBH", _DTYPE_CODES[dt], len(shape), 0))
            f.write(struct.pack("<I", len(nm)))
            f.write(nm)
            f.write(struct.pack(f"<{len(shape)}q", *shape))
        f.write(copts)
        f.write(shlo)
        for kind, _name, _dt, _shape, payload in args:
            if kind == 1:
                f.write(np.ascontiguousarray(payload).tobytes())
    return path
