"""incubator_mxnet_tpu: a TPU-native deep learning framework.

A ground-up re-design of the capabilities of Apache MXNet (incubating) for
TPU hardware: JAX/XLA is the compute substrate (MXU matmuls/convs, ICI
collectives, XLA fusion in place of the dependency engine + cuDNN/MKL-DNN
backends), Pallas for custom kernels, pjit/shard_map over device meshes for
data/model/sequence parallelism.

Usage mirrors the reference's `import mxnet as mx`:

    import incubator_mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu())
"""
from __future__ import annotations

__version__ = "0.1.0"

from .base import MXNetError  # noqa: F401
from .context import Context, cpu, cpu_pinned, gpu, tpu, current_context, num_gpus, num_tpus  # noqa: F401
from . import base  # noqa: F401
from . import config  # noqa: F401
from . import ops  # noqa: F401
from . import ndarray  # noqa: F401
from . import ndarray as nd  # noqa: F401
from . import autograd  # noqa: F401
from . import random  # noqa: F401

# populated as subsystems land (symbol, module, gluon, optimizer, kvstore, io,
# metric, initializer, parallel, profiler, ...)
from . import symbol  # noqa: F401  # isort: skip
from . import symbol as sym  # noqa: F401
from .symbol import Symbol  # noqa: F401
from . import initializer  # noqa: F401
from .initializer import init  # noqa: F401
from . import optimizer  # noqa: F401
from . import optimizer as opt  # noqa: F401
from . import lr_scheduler  # noqa: F401
from . import metric  # noqa: F401
from . import kvstore as kv  # noqa: F401
from . import kvstore  # noqa: F401
from . import io  # noqa: F401
from . import recordio  # noqa: F401
from . import image  # noqa: F401
from . import callback  # noqa: F401
from . import monitor  # noqa: F401
from . import model  # noqa: F401
from . import module  # noqa: F401
from . import rnn  # noqa: F401
from . import name  # noqa: F401
from . import attribute  # noqa: F401
from .attribute import AttrScope  # noqa: F401
from . import kvstore_server  # noqa: F401

# a process launched in the server role serves until the job ends, then
# exits — same import-time contract as the reference (kvstore_server.py:92)
kvstore_server._init_kvstore_server_module()
from . import gluon  # noqa: F401
from . import executor  # noqa: F401
from . import engine  # noqa: F401
from . import profiler  # noqa: F401
from . import telemetry  # noqa: F401
from . import runtime  # noqa: F401
from . import parallel  # noqa: F401
from . import test_utils  # noqa: F401
from . import visualization  # noqa: F401
from . import visualization as viz  # noqa: F401
from .util import is_np_array  # noqa: F401
from . import operator  # noqa: F401
from . import contrib  # noqa: F401
from . import fused  # noqa: F401
from . import rtc  # noqa: F401
from . import deploy  # noqa: F401
from . import distributed  # noqa: F401
