"""Module: symbolic training on one or many TPU chips.

TPU-native re-design of the reference Module + DataParallelExecutorGroup
(ref: python/mxnet/module/module.py:40-644, executor_group.py:143). The
reference splits the batch into per-GPU executors and all-reduces grads via
kvstore; here there is ONE executor whose arrays are sharded over a
`jax.sharding.Mesh` of the given contexts — batch dim sharded for data,
params replicated — and XLA GSPMD inserts the ICI all-reduce during the
backward pass (the kvstore='device' analog).
"""
from __future__ import annotations

import logging

import numpy as np
import jax
import jax.numpy as jnp

from .. import optimizer as opt
from ..context import Context, cpu
from ..initializer import InitDesc, Uniform
from ..io import DataDesc
from ..model import save_checkpoint, load_checkpoint
from ..ndarray.ndarray import NDArray
from .base_module import BaseModule

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        if context is None:
            context = cpu()
        self._context = context if isinstance(context, (list, tuple)) else [context]
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        self._state_names = list(state_names or [])
        arg_names = symbol.list_arguments()
        input_names = self._data_names + self._label_names + self._state_names
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()
        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._exec = None
        self._data_shapes = None
        self._label_shapes = None
        self._grad_req = None
        self._mesh = None
        self._preload_opt_states = None
        if len(self._context) > 1:
            from ..parallel import make_mesh

            self._mesh = make_mesh(self._context, axis_names=("data",))

    # -- introspection -----------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        _, out_shapes, _ = self._symbol.infer_shape(
            **{d.name: d.shape for d in self._data_shapes + (self._label_shapes or [])}
        )
        return list(zip(self._output_names, out_shapes))

    # -- bind --------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """(ref: module.py:364 bind -> simple_bind per ctx; here one sharded
        executor)"""
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        self._data_shapes = [
            d if isinstance(d, DataDesc) else DataDesc(*d) for d in data_shapes
        ]
        self._label_shapes = (
            [d if isinstance(d, DataDesc) else DataDesc(*d) for d in label_shapes]
            if label_shapes else []
        )
        shapes = {d.name: tuple(d.shape) for d in self._data_shapes + self._label_shapes}

        reqs = {}
        for n in self._symbol.list_arguments():
            if not for_training:
                reqs[n] = "null"
            elif n in self._data_names:
                reqs[n] = grad_req if inputs_need_grad else "null"
            elif n in self._label_names or n in self._fixed_param_names:
                reqs[n] = "null"
            else:
                reqs[n] = grad_req
        self._grad_req = reqs
        self._exec = self._symbol.simple_bind(
            ctx=self._context[0], grad_req=reqs, **shapes
        )
        if self._mesh is not None:
            self._apply_shardings()
        if shared_module is not None and shared_module._arg_params is not None:
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
            self._exec.copy_params_from(self._arg_params, self._aux_params)
            self.params_initialized = True

    def _apply_shardings(self):
        """Replicate params, shard data on the batch axis over the mesh."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self._mesh
        rep = NamedSharding(mesh, P())
        for name, arr in self._exec.arg_dict.items():
            if name in self._param_names:
                arr._data = jax.device_put(arr._data, rep)
        for arr in self._exec.aux_dict.values():
            arr._data = jax.device_put(arr._data, rep)
        for arr in self._exec.grad_dict.values():
            arr._data = jax.device_put(arr._data, rep)

    def _shard_input(self, data):
        if self._mesh is None:
            return data
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P("data") if data.ndim >= 1 else P()
        return jax.device_put(data, NamedSharding(self._mesh, spec))

    # -- params ------------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        """(ref: module.py init_params)"""
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before init_params"
        if initializer is None and not self.params_initialized:
            initializer = Uniform(0.01)

        attrs = self._symbol.attr_dict()

        def _impl(name, arr, cache):
            if cache is not None and name in cache:
                src = cache[name]
                arr._data = jnp.asarray(
                    src._data if isinstance(src, NDArray) else src, dtype=arr._data.dtype
                ).reshape(arr.shape)
            elif cache is not None and not allow_missing:
                raise RuntimeError(f"{name} is not presented")
            elif initializer is not None:
                initializer(InitDesc(name, attrs.get(name)), arr)

        for name in self._param_names:
            _impl(name, self._exec.arg_dict[name], arg_params)
        for name, arr in self._exec.aux_dict.items():
            _impl(name, arr, aux_params)

        self._arg_params = {n: self._exec.arg_dict[n] for n in self._param_names}
        self._aux_params = dict(self._exec.aux_dict)
        self.params_initialized = True
        self._params_dirty = False
        if self._mesh is not None:
            self._apply_shardings()

    def get_params(self):
        """(ref: module.py get_params) — returns host-synced copies."""
        assert self.binded and self.params_initialized
        arg = {n: NDArray(self._exec.arg_dict[n]._data) for n in self._param_names}
        aux = {n: NDArray(a._data) for n, a in self._exec.aux_dict.items()}
        return arg, aux

    # -- optimizer ---------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),), force_init=False):
        """(ref: module.py init_optimizer + model._create_kvstore)"""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        from .. import kvstore as kvs

        batch_size = self._data_shapes[0].shape[0]
        optimizer_params = dict(optimizer_params)
        if isinstance(optimizer, str):
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = 1.0 / batch_size
            optimizer = opt.create(
                optimizer, sym=self._symbol, param_idx2name=idx2name, **optimizer_params
            )
        self._optimizer = optimizer
        self._optimizer.set_lr_mult({})
        self._optimizer.set_wd_mult({})

        kvstore_obj, update_on_kvstore = kvs.create_kvstore_for_module(
            kvstore, len(self._context), self._arg_params
        )
        self._kvstore = kvstore_obj
        self._update_on_kvstore = update_on_kvstore
        if kvstore_obj is not None and update_on_kvstore:
            # the store holds the authoritative weights only when the
            # optimizer runs inside it (ref: kvstore_dist_server's updater);
            # in allreduce mode the store is a transient merge buffer
            kvstore_obj.set_optimizer(self._optimizer)
            for i, name in enumerate(self._param_names):
                kvstore_obj.init(name, self._arg_params[name])
        if not update_on_kvstore or kvstore_obj is None:
            self._updater = opt.get_updater(self._optimizer)
        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def borrow_optimizer(self, shared_module):
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True

    # -- compute -----------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        feed = {}
        for name, arr in zip(self._data_names, data_batch.data):
            a = arr._data if isinstance(arr, NDArray) else jnp.asarray(arr)
            feed[name] = self._shard_input(a)
        if data_batch.label is not None:
            for name, arr in zip(self._label_names, data_batch.label):
                if name in self._exec.arg_dict:
                    a = arr._data if isinstance(arr, NDArray) else jnp.asarray(arr)
                    feed[name] = self._shard_input(a)
        self._exec.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def update(self):
        """(ref: module.py:644 update -> updater / kvstore push+pull)"""
        assert self.binded and self.params_initialized and self.optimizer_initialized
        self._params_dirty = True
        if self._kvstore is not None and self._update_on_kvstore:
            for i, name in enumerate(self._param_names):
                w = self._exec.arg_dict[name]
                g = self._exec.grad_dict.get(name)
                if g is None:
                    continue
                self._kvstore.push(name, g)
                self._kvstore.pull(name, out=w)
        else:
            if self._kvstore is not None:
                for i, name in enumerate(self._param_names):
                    g = self._exec.grad_dict.get(name)
                    if g is None:
                        continue
                    # one-shot allreduce: merge-and-reset, NOT accumulate
                    # (the store must not carry grads across steps)
                    self._kvstore.pushpull(name, g, out=g)
            for i, name in enumerate(self._param_names):
                w = self._exec.arg_dict[name]
                g = self._exec.grad_dict.get(name)
                if g is None:
                    continue
                self._updater(i, g, w)

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        return [self._exec.grad_dict[n] for n in self._data_names if n in self._exec.grad_dict]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        if self._label_names:
            eval_metric.update_dict(
                dict(zip(self._label_names, labels or [])),
                dict(zip(self._output_names, self._exec.outputs)),
            )
        else:
            eval_metric.update_dict({}, dict(zip(self._output_names, self._exec.outputs)))

    # -- states / checkpoints ----------------------------------------------
    def get_states(self, merge_multi_context=True):
        return [self._exec.arg_dict[n] for n in self._state_names]

    def set_states(self, states=None, value=None):
        for name in self._state_names:
            if value is not None:
                self._exec.arg_dict[name]._data = jnp.full(
                    self._exec.arg_dict[name].shape, value,
                    dtype=self._exec.arg_dict[name]._data.dtype,
                )
        if states is not None:
            for name, s in zip(self._state_names, states):
                self._exec.arg_dict[name]._data = s._data

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as f:
                f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False, remove_amp_cast=True):
        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg_params, aux_params)
        if save_optimizer_states:
            self.save_optimizer_states(f"{prefix}-{epoch:04d}.states")

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = f"{prefix}-{epoch:04d}.states"
        # defer copying into executors until bind
        orig_bind = mod.bind

        def bind_and_set(*a, **kw):
            orig_bind(*a, **kw)
            mod._exec.copy_params_from(args, auxs, allow_extra_params=True)
            mod._arg_params = {n: mod._exec.arg_dict[n] for n in mod._param_names}
            mod._aux_params = dict(mod._exec.aux_dict)

        mod.bind = bind_and_set
        return mod

    def reshape(self, data_shapes, label_shapes=None):
        self.bind(data_shapes, label_shapes, for_training=self.for_training,
                  inputs_need_grad=self.inputs_need_grad, force_rebind=True)

    def install_monitor(self, mon):
        mon.install(self._exec)

    def prepare(self, data_batch, sparse_row_id_fn=None):
        pass
