"""BucketingModule: variable-length training via per-bucket executors
sharing parameters (ref: python/mxnet/module/bucketing_module.py:36).

TPU-native note: each bucket is a shape-specialized XLA compilation of the
same functions; parameters are shared NDArray objects so all bucket
executors see updates — the same arrays, not copies, exactly like the
reference's shared executor memory.
"""
from __future__ import annotations

import logging

from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._default_bucket_key = default_bucket_key
        self._sym_gen = sym_gen
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._monitor = None
        self._grad_req = None

    @property
    def symbol(self):
        return self._curr_module.symbol

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        sym, data_names, _ = self._call_sym_gen(self._default_bucket_key)
        return data_names

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        sym, _, _ = self._call_sym_gen(self._default_bucket_key)
        return sym.list_outputs()

    @property
    def data_shapes(self):
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        return self._curr_module.output_shapes

    def _call_sym_gen(self, bucket_key):
        return self._sym_gen(bucket_key)

    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._call_sym_gen(bucket_key)
        return Module(
            sym, data_names, label_names, logger=self.logger, context=self._context,
            fixed_param_names=self._fixed_param_names, state_names=self._state_names,
        )

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        self._grad_req = grad_req
        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                    force_rebind=False, grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """(ref: bucketing_module.py switch_bucket) — shape-specialized
        recompile, shared parameter arrays."""
        assert self.binded
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            default_mod = self._buckets[self._default_bucket_key]
            module.bind(data_shapes, label_shapes, self.for_training,
                        self.inputs_need_grad, force_rebind=False, grad_req=self._grad_req)
            if default_mod.params_initialized:
                arg, aux = default_mod._arg_params, default_mod._aux_params
                module.init_params(arg_params=arg, aux_params=aux, allow_missing=False)
                # share the SAME NDArray objects (updates propagate)
                for n in module._param_names:
                    if n in arg:
                        module._exec.arg_dict[n]._data = arg[n]._data
                        module._arg_params[n] = arg[n]
                        module._exec.arg_dict[n] = arg[n]
                for n, a in aux.items():
                    if n in module._exec.aux_dict:
                        module._exec.aux_dict[n] = a
                        module._aux_params[n] = a
            if default_mod.optimizer_initialized:
                module.borrow_optimizer(default_mod)
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        self._curr_module.init_params(
            initializer=initializer, arg_params=arg_params, aux_params=aux_params,
            allow_missing=allow_missing, force_init=force_init, allow_extra=allow_extra,
        )
        self.params_initialized = True

    def get_params(self):
        return self._curr_module.get_params()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self._curr_module.set_params(arg_params, aux_params, allow_missing,
                                     force_init, allow_extra)
        self.params_initialized = True

    def init_optimizer(self, **kwargs):
        self._curr_module.init_optimizer(**kwargs)
        self.optimizer_initialized = True
        for mod in self._buckets.values():
            if mod is not self._curr_module and mod.optimizer_initialized is False:
                pass

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        bucket_key = data_batch.bucket_key
        if bucket_key is None:
            bucket_key = self._curr_bucket_key
        self.switch_bucket(bucket_key, data_batch.provide_data, data_batch.provide_label)
        if not self._curr_module.params_initialized:
            default_mod = self._buckets[self._default_bucket_key]
            arg, aux = default_mod._arg_params, default_mod._aux_params
            self._curr_module.init_params(arg_params=arg, aux_params=aux)
        if not self._curr_module.optimizer_initialized and self.optimizer_initialized:
            self._curr_module.borrow_optimizer(self._buckets[self._default_bucket_key])
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()
        # propagate updated params to other bucket executors (same arrays)
        cur = self._curr_module
        for key, mod in self._buckets.items():
            if mod is cur or not mod.params_initialized:
                continue
            for n in mod._param_names:
                if n in cur._exec.arg_dict:
                    mod._exec.arg_dict[n]._data = cur._exec.arg_dict[n]._data
            for n in mod._exec.aux_dict:
                if n in cur._exec.aux_dict:
                    mod._exec.aux_dict[n]._data = cur._exec.aux_dict[n]._data

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels, pre_sliced)

    def install_monitor(self, mon):
        self._monitor = mon
        for mod in self._buckets.values():
            mod.install_monitor(mon)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        self._curr_module.save_checkpoint(prefix, epoch, save_optimizer_states)

    def prepare(self, data_batch, sparse_row_id_fn=None):
        pass
