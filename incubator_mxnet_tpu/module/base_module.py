"""BaseModule: the high-level train/score/predict API
(ref: python/mxnet/module/base_module.py — fit:409, forward_backward:193,
score:213, predict:321).
"""
from __future__ import annotations

import logging
import time

import numpy as np

from .. import metric as _metric
from ..model import BatchEndParam
from ..ndarray.ndarray import NDArray

__all__ = ["BaseModule"]


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # -- abstract interface ------------------------------------------------
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        raise NotImplementedError

    def bind(self, *args, **kwargs):
        raise NotImplementedError

    def init_params(self, *args, **kwargs):
        raise NotImplementedError

    def init_optimizer(self, *args, **kwargs):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    # -- conveniences ------------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    def forward_backward(self, data_batch):
        """(ref: base_module.py:193)"""
        self.forward(data_batch, is_train=True)
        self.backward()

    def _eval_batches(self, eval_data, num_batch, reset):
        """Inference-mode batch stream shared by score/predict/iter_predict:
        reset, cap at num_batch, forward each batch with is_train=False."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                return
            self.forward(batch, is_train=False)
            yield nbatch, batch

    def score(self, eval_data, eval_metric, num_batch=None, batch_end_callback=None,
              score_end_callback=None, reset=True, epoch=0, sparse_row_id_fn=None):
        """Run eval_data through the net and return metric name/value pairs
        (ref: base_module.py:213)."""
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        seen = 0
        for nbatch, batch in self._eval_batches(eval_data, num_batch, reset):
            self.update_metric(eval_metric, batch.label)
            for cb in _as_list(batch_end_callback or []):
                cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                 eval_metric=eval_metric, locals=locals()))
            seen = nbatch + 1
        for cb in _as_list(score_end_callback or []):
            cb(BatchEndParam(epoch=epoch, nbatch=seen,
                             eval_metric=eval_metric, locals=locals()))
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        """Yield (outputs, nbatch, batch) per eval batch (ref: iter_predict)."""
        for nbatch, batch in self._eval_batches(eval_data, num_batch, reset):
            yield self.get_outputs(), nbatch, batch

    def predict(self, eval_data, num_batch=None, merge_batches=True, reset=True,
                always_output_list=False, sparse_row_id_fn=None):
        """Collect per-batch outputs, pad-stripped; merged along the batch
        axis unless merge_batches=False (ref: base_module.py:321)."""
        per_batch = []
        for _, batch in self._eval_batches(eval_data, num_batch, reset):
            pad = batch.pad or 0
            per_batch.append([o[:o.shape[0] - pad] if pad else o
                              for o in self.get_outputs()])
        if not per_batch:
            return per_batch
        if not merge_batches:
            return per_batch
        from ..ndarray import concatenate

        merged = [concatenate([outs[i] for outs in per_batch], axis=0)
                  for i in range(len(per_batch[0]))]
        return (merged[0] if len(merged) == 1 and not always_output_list
                else merged)

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None, monitor=None,
            sparse_row_id_fn=None):
        """The canonical training loop (ref: base_module.py:409 — same
        contract: bind/init/optimize once, then per epoch run train batches
        with one-batch lookahead for sparse prepare, log train metrics,
        fire callbacks, score eval_data).

        Structure here is setup (`_fit_setup`) + per-epoch body
        (`_fit_one_epoch`) rather than one long loop.
        """
        assert num_epoch is not None, "please specify number of epochs"
        eval_metric, validation_metric = self._fit_setup(
            train_data, eval_metric, validation_metric, initializer,
            arg_params, aux_params, allow_missing, force_rebind, force_init,
            kvstore, optimizer, optimizer_params, monitor)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            self._fit_one_epoch(epoch, train_data, eval_metric, monitor,
                                batch_end_callback, sparse_row_id_fn)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)

            # sync params out of the executors, then epoch-end hooks
            arg_p, aux_p = self.get_params()
            self.set_params(arg_p, aux_p, allow_missing=False,
                            force_init=True, allow_extra=False)
            for cb in _as_list(epoch_end_callback or []):
                cb(epoch, self.symbol, arg_p, aux_p)

            if eval_data is not None:
                res = self.score(
                    eval_data, validation_metric,
                    score_end_callback=eval_end_callback,
                    batch_end_callback=eval_batch_end_callback, epoch=epoch,
                )
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)
            train_data.reset()

    def _fit_setup(self, train_data, eval_metric, validation_metric,
                   initializer, arg_params, aux_params, allow_missing,
                   force_rebind, force_init, kvstore, optimizer,
                   optimizer_params, monitor):
        """bind -> monitor -> params -> optimizer -> metrics, once."""
        from ..initializer import Uniform

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer or Uniform(0.01),
                         arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        return eval_metric, validation_metric or eval_metric

    def _fit_one_epoch(self, epoch, train_data, eval_metric, monitor,
                       batch_end_callback, sparse_row_id_fn):
        """One pass over train_data with one-batch lookahead: the NEXT
        batch is fetched (and sparse rows prepared) while the current
        batch's async compute is in flight."""
        eval_metric.reset()
        data_iter = iter(train_data)
        batch = next(data_iter)
        nbatch = 0
        while batch is not None:
            if monitor is not None:
                monitor.tic()
            self.forward_backward(batch)
            self.update()
            if isinstance(batch, list):  # pre-sliced multi-device batch
                self.update_metric(eval_metric, [b.label for b in batch],
                                   pre_sliced=True)
            else:
                self.update_metric(eval_metric, batch.label)
            nxt = next(data_iter, None)
            if nxt is not None:
                self.prepare(nxt, sparse_row_id_fn=sparse_row_id_fn)
            if monitor is not None:
                monitor.toc_print()
            if nxt is None:
                # read the epoch metrics before callbacks may reset them
                for name, val in eval_metric.get_name_value():
                    self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            for cb in _as_list(batch_end_callback or []):
                cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                 eval_metric=eval_metric, locals=locals()))
            nbatch += 1
            batch = nxt

    # -- misc helpers ------------------------------------------------------
    def get_params(self):
        raise NotImplementedError

    def set_params(self, arg_params, aux_params, allow_missing=False, force_init=True,
                   allow_extra=False):
        self.init_params(
            initializer=None, arg_params=arg_params, aux_params=aux_params,
            allow_missing=allow_missing, force_init=force_init, allow_extra=allow_extra,
        )

    def save_params(self, fname):
        arg_params, aux_params = self.get_params()
        save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
        save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
        from ..ndarray import save

        save(fname, save_dict)

    def load_params(self, fname):
        from ..ndarray import load

        save_dict = load(fname)
        arg_params, aux_params = {}, {}
        for k, value in save_dict.items():
            arg_type, name = k.split(":", 1)
            if arg_type == "arg":
                arg_params[name] = value
            elif arg_type == "aux":
                aux_params[name] = value
        self.set_params(arg_params, aux_params)

    def install_monitor(self, mon):
        raise NotImplementedError

    def prepare(self, data_batch, sparse_row_id_fn=None):
        pass

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError


def _as_list(obj):
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]
