"""SequentialModule: chain independently-bound modules into one pipeline
(ref: python/mxnet/module/sequential_module.py:28 — add/bind wire each
sub-module's outputs to the next one's data; backward threads
get_input_grads() in reverse).

TPU-native shape: each sub-module owns its own jitted executor (its own XLA
program); the chain is a host-side container. Activations between stages
stay on-device (`jax.Array` hand-off, no host sync), so the cost of the
split vs one fused program is only the lost cross-stage fusion — which is
the documented trade of this "handy utility" container in the reference
too. The same container is what module-granular pipeline composition looks
like before graduating to `parallel/pipeline.py`'s shard_map version.
"""
from __future__ import annotations

import copy
import logging

from .base_module import BaseModule

__all__ = ["SequentialModule"]


class SequentialModule(BaseModule):
    """Container chaining multiple modules; data flows first->last, input
    gradients flow last->first."""

    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._data_shapes = None
        self._label_shapes = None
        self._meta_keys = {self.META_TAKE_LABELS, self.META_AUTO_WIRING}

    def add(self, module, **kwargs):
        """Append `module`; meta kwargs: take_labels (this stage also sees
        the batch labels), auto_wiring (rename incoming data to the stage's
        own data_names). Returns self for chaining."""
        for key in kwargs:
            if key not in self._meta_keys:
                raise ValueError(f'Unknown meta "{key}", a typo?')
        self._modules.append(module)
        self._metas.append(dict(kwargs))
        # adding a stage invalidates any previous bind/init
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    # -- introspection -----------------------------------------------------
    @property
    def data_names(self):
        return self._modules[0].data_names if self._modules else []

    @property
    def output_names(self):
        return self._modules[-1].output_names if self._modules else []

    @property
    def data_shapes(self):
        assert self.binded
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._modules[-1].output_shapes

    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params, aux_params = {}, {}
        for module in self._modules:
            arg, aux = module.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return arg_params, aux_params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        for module in self._modules:
            module.init_params(initializer=initializer, arg_params=arg_params,
                               aux_params=aux_params,
                               allow_missing=allow_missing,
                               force_init=force_init, allow_extra=allow_extra)
        self._check_duplicate_names()
        self.params_initialized = True

    def _check_duplicate_names(self):
        """A parameter name may appear in at most one stage — a duplicate
        would make get_params/set_params silently pick one of the two."""
        owner = {}
        for i, module in enumerate(self._modules):
            arg, aux = module.get_params()
            for name in list(arg) + list(aux):
                if name in owner:
                    raise ValueError(
                        f'Duplicated parameter name "{name}": layer {i} '
                        f"({type(module).__name__}) reuses a name already in "
                        f"layer {owner[name]}")
                owner[name] = i

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """Bind every stage; stage i>0's data shapes are stage i-1's output
        shapes, and every stage after the first is bound with
        inputs_need_grad so backward can chain."""
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        if inputs_need_grad:
            assert for_training
        assert shared_module is None, "Shared module is not supported"
        assert self._modules, "Attempting to bind an empty SequentialModule"
        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._label_shapes = label_shapes

        my_data_shapes = data_shapes
        anybody_needs_label = False
        for i, (module, meta) in enumerate(zip(self._modules, self._metas)):
            take_labels = bool(meta.get(self.META_TAKE_LABELS))
            anybody_needs_label |= take_labels
            if meta.get(self.META_AUTO_WIRING):
                names = module.data_names
                assert len(names) == len(my_data_shapes)
                my_data_shapes = [
                    (new_name, tuple(d[1] if isinstance(d, tuple) else d.shape))
                    for new_name, d in zip(names, my_data_shapes)]
            module.bind(
                data_shapes=my_data_shapes,
                label_shapes=label_shapes if take_labels else None,
                for_training=for_training,
                inputs_need_grad=bool(inputs_need_grad or
                                      (for_training and i > 0)),
                force_rebind=force_rebind, shared_module=None,
                grad_req=grad_req)
            my_data_shapes = module.output_shapes
        if not anybody_needs_label:
            self._label_shapes = None

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring.")
            return
        for module in self._modules:
            module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                  optimizer_params=optimizer_params,
                                  force_init=force_init)
        self.optimizer_initialized = True

    # -- compute -----------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        batch = copy.copy(data_batch)  # keep pad/bucket_key, rewire data
        for i, module in enumerate(self._modules):
            module.forward(batch, is_train=is_train)
            if i + 1 == len(self._modules):
                break
            batch.data = module.get_outputs()
            if hasattr(batch, "provide_data"):
                names = [n for n, _ in module.output_shapes]
                batch.provide_data = [(n, x.shape)
                                      for n, x in zip(names, batch.data)]

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        for i in reversed(range(len(self._modules))):
            self._modules[i].backward(out_grads=out_grads)
            if i == 0:
                break
            out_grads = self._modules[i].get_input_grads()

    def update(self):
        assert (self.binded and self.params_initialized
                and self.optimizer_initialized)
        for module in self._modules:
            module.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._modules[-1].get_outputs(
            merge_multi_context=merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert (self.binded and self.params_initialized
                and self.inputs_need_grad)
        return self._modules[0].get_input_grads(
            merge_multi_context=merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        assert self.binded and self.params_initialized
        for meta, module in zip(self._metas, self._modules):
            if meta.get(self.META_TAKE_LABELS):
                module.update_metric(eval_metric, labels, pre_sliced)

    def install_monitor(self, mon):
        assert self.binded
        for module in self._modules:
            module.install_monitor(mon)
