"""PythonModule / PythonLossModule: module-granularity host-side stages
(ref: python/mxnet/module/python_module.py:28,243 — a BaseModule whose
computation is arbitrary Python; the loss variant caches scores and turns a
user `grad_func(scores, labels)` into the chain's input gradients).

TPU-native shape: this is the module-level analog of `operator.CustomOp`'s
`pure_callback` bridge — the stage runs on the host between the
neighbouring stages' XLA programs. Use it for glue (custom losses, metrics
probes, debugging) inside a `SequentialModule`; anything hot belongs in a
jitted stage instead.
"""
from __future__ import annotations

import logging

from .base_module import BaseModule
from ..ndarray.ndarray import NDArray
from .. import ndarray as nd

__all__ = ["PythonModule", "PythonLossModule"]


class PythonModule(BaseModule):
    """Base for modules implemented as plain Python: parameter-free by
    default, with bind() reduced to shape bookkeeping. Subclasses override
    forward/backward (and _compute_output_shapes for a non-identity
    output signature)."""

    def __init__(self, data_names, label_names, output_names, logger=logging):
        super().__init__(logger=logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    # -- introspection -----------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # -- parameters: none by default ---------------------------------------
    def get_params(self):
        return {}, {}

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        self.params_initialized = True

    def update(self):
        pass

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        pass

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

        def norm(shapes):
            return [(d[0], tuple(d[1])) for d in (tuple(x) for x in shapes)]

        self._data_shapes = norm(data_shapes)
        self._label_shapes = norm(label_shapes) if label_shapes else None
        self._output_shapes = self._compute_output_shapes()

    def _compute_output_shapes(self):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self.optimizer_initialized = True

    def install_monitor(self, mon):
        raise NotImplementedError()


class PythonLossModule(PythonModule):
    """Terminal loss stage: forward caches the incoming scores (and labels
    when training); backward calls `grad_func(scores, labels) -> d(scores)`
    and exposes it via get_input_grads for the upstream stage."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        if len(data_names) != 1 or len(label_names) != 1:
            raise ValueError("PythonLossModule takes exactly one data and "
                             "one label input")
        super().__init__(data_names, label_names, [name + "_output"],
                         logger=logger)
        self._name = name
        if grad_func is not None and not callable(grad_func):
            raise TypeError("grad_func must be callable")
        self._grad_func = grad_func
        self._scores = None
        self._labels = None
        self._scores_grad = None

    def _compute_output_shapes(self):
        # a loss stage passes its scores through unchanged
        return [(self._name + "_output", self._data_shapes[0][1])]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        training = self.for_training if is_train is None else is_train
        if training:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        assert merge_multi_context, "single-context stage"
        return [self._scores]

    def backward(self, out_grads=None):
        if out_grads is not None:
            raise ValueError("a loss stage is terminal; out_grads must be "
                             "None")
        if not self.for_training:
            raise RuntimeError("backward() on a module bound with "
                               "for_training=False")
        self._backward_impl()

    def _backward_impl(self):
        """Compute d(loss)/d(scores) into self._scores_grad (the contract
        subclasses override). The grad_func= constructor argument is the
        no-subclass shortcut."""
        if self._grad_func is None:
            raise NotImplementedError(
                "PythonLossModule needs a grad_func or a _backward_impl "
                "override")
        g = self._grad_func(self._scores, self._labels)
        self._scores_grad = g if isinstance(g, NDArray) else nd.array(g)

    def get_input_grads(self, merge_multi_context=True):
        assert merge_multi_context, "single-context stage"
        return [self._scores_grad]

    def install_monitor(self, mon):
        raise NotImplementedError()
