"""Imperative autograd: tape + record/pause scopes + backward.

TPU-native equivalent of the reference's imperative runtime & autograd tape
(ref: src/imperative/imperative.cc — RecordOp:191, Backward:278;
python/mxnet/autograd.py). Where the reference re-runs an nnvm gradient pass
over recorded nodes, here every recorded op carries a `jax.vjp` closure; the
backward pass walks the tape in reverse topological order and accumulates
cotangents. XLA executes each vjp asynchronously, which preserves the
reference engine's compute/transfer overlap without an explicit dependency
scheduler.
"""
from __future__ import annotations

import threading
from typing import Optional

import jax
import jax.numpy as jnp

from . import random as _random

_NDArray = None


def _nd_cls():
    """NDArray class, cached after the first call (ndarray imports autograd,
    so a top-level import here would be circular; a per-call `from ...
    import` in the eager dispatcher costs ~5us/op in importlib locks)."""
    global _NDArray
    if _NDArray is None:
        from .ndarray.ndarray import NDArray as cls

        _NDArray = cls
    return _NDArray

__all__ = [
    "record",
    "pause",
    "train_mode",
    "predict_mode",
    "is_recording",
    "is_training",
    "set_recording",
    "set_training",
    "mark_variables",
    "backward",
    "grad",
    "get_symbol",
]

_STATE = threading.local()


def _state():
    if not hasattr(_STATE, "recording"):
        _STATE.recording = False
        _STATE.training = False
    return _STATE


def is_recording():
    return _state().recording


def is_training():
    return _state().training


def set_recording(is_record):
    prev = _state().recording
    _STATE.recording = bool(is_record)
    return prev


def set_training(train_mode_):
    prev = _state().training
    _STATE.training = bool(train_mode_)
    return prev


def _record_gen():
    """Per-thread record()-scope generation, used by the gradient-overwrite
    warning. Lives in the same thread-local as the recording flag so
    concurrent record() scopes on other threads can neither trigger nor
    suppress it."""
    return getattr(_state(), "record_gen", 0)


class _AutogradScope:
    def __init__(self, recording=None, training=None):
        self._recording = recording
        self._training = training

    def __enter__(self):
        if self._recording:
            _state().record_gen = _record_gen() + 1
        if self._recording is not None:
            self._prev_rec = set_recording(self._recording)
        if self._training is not None:
            self._prev_train = set_training(self._training)
        return self

    def __exit__(self, *exc):
        if self._recording is not None:
            set_recording(self._prev_rec)
        if self._training is not None:
            set_training(self._prev_train)


def record(train_mode=True):  # noqa: A002 - reference API name
    """Scope: record ops for autograd (ref: python/mxnet/autograd.py:93)."""
    return _AutogradScope(recording=True, training=train_mode)


def pause(train_mode=False):
    return _AutogradScope(recording=False, training=train_mode)


def train_mode():
    return _AutogradScope(training=True)


def predict_mode():
    return _AutogradScope(training=False)


# ---------------------------------------------------------------------------
# Tape
# ---------------------------------------------------------------------------


class TapeNode:
    """One recorded op: vjp closure + graph links (ref: Imperative::RecordOp)."""

    __slots__ = ("vjp", "fn", "inputs", "n_outputs", "out_avals", "name",
                 "saved")

    def __init__(self, vjp, inputs, n_outputs, out_avals, name="", fn=None):
        self.vjp = vjp
        self.fn = fn          # primal fn (tuple-returning); enables
        self.inputs = inputs  # grad-of-grad by re-deriving the vjp with
        self.n_outputs = n_outputs  # primals as explicit inputs
        self.out_avals = out_avals  # [(shape, dtype)] per output
        self.name = name


def _attach_outputs(node, outputs):
    for i, o in enumerate(outputs):
        o._node = node
        o._node_index = i


def invoke_recorded(fn, input_arrays, name=""):
    """Run `fn(*jax_arrays) -> array | tuple` with optional tape recording.

    Central eager dispatcher used by every generated nd.* function.
    Always returns a list of NDArrays.
    """
    NDArray = _nd_cls()

    datas = [a._data if isinstance(a, NDArray) else a for a in input_arrays]
    nd_inputs = [a for a in input_arrays if isinstance(a, NDArray)]
    recording = is_recording() and len(nd_inputs) > 0

    if not recording:
        out = fn(*datas)
        outs = out if isinstance(out, tuple) else (out,)
        return [NDArray._from_data(o) for o in outs]

    def tuple_fn(*xs):
        out = fn(*xs)
        return out if isinstance(out, tuple) else (out,)

    outs, vjp_fn = jax.vjp(tuple_fn, *datas)
    res = [NDArray._from_data(o) for o in outs]
    node = TapeNode(
        vjp=vjp_fn,
        inputs=list(input_arrays),
        n_outputs=len(res),
        out_avals=[(o.shape, o.dtype) for o in outs],
        name=name,
        fn=tuple_fn,
    )
    _attach_outputs(node, res)
    return res


def sparse_embedding(x, weight, input_dim, output_dim):
    """Embedding lookup whose recorded weight-cotangent is ROW-SPARSE
    (ref: src/operator/tensor/indexing_op.cc Embedding with
    grad_stype=row_sparse — only rows a batch touches appear in the grad).

    Eager-tape only: the row set is data-dependent, so under jit tracing
    embeddings fall back to the dense gather/scatter path (XLA fuses that
    fine on-chip; sparsity pays off on the host/optimizer/wire side).
    Duplicate ids within the batch are pre-aggregated with a segment-sum.
    """
    import numpy as np

    from .ndarray.ndarray import NDArray
    from .ndarray.sparse import RowSparseNDArray

    xd = x._data if isinstance(x, NDArray) else jnp.asarray(x)
    wd = weight._data
    idx = jnp.asarray(xd).astype(jnp.int32)
    out = jnp.take(wd, idx.ravel(), axis=0).reshape(
        tuple(idx.shape) + (int(output_dim),))
    res = NDArray._from_data(out)
    # record whenever the tape is on: whether a grad buffer exists is
    # backward()'s concern (autograd.grad attaches buffers post-forward)
    if not is_recording():
        return res

    # row-sparse grad has data-dependent nnz: np.unique cannot stay on
    # device under jit, so this sync is the cost of the sparse format
    host_idx = np.asarray(idx).ravel()  # mxlint: disable=MXL005
    uniq, inv = np.unique(host_idx, return_inverse=True)
    inv = jnp.asarray(inv)

    def vjp(cts):
        ct = jnp.asarray(cts[0]).reshape(-1, int(output_dim))
        rows = jnp.zeros((uniq.shape[0], int(output_dim)),
                         ct.dtype).at[inv].add(ct)
        gw = RowSparseNDArray(NDArray._from_data(rows),
                              NDArray(uniq.astype(np.int64)),
                              (int(input_dim), int(output_dim)))
        return (gw,)

    node = TapeNode(vjp=vjp, inputs=[weight], n_outputs=1,
                    out_avals=[(res.shape, res.dtype)],
                    name="sparse_embedding")
    _attach_outputs(node, [res])
    return res


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers (ref: MXAutogradMarkVariables)."""
    if not isinstance(variables, (list, tuple)):
        variables, gradients = [variables], [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req


def _topo_order(head_nodes):
    """Post-order (children-first) node order via iterative DFS."""
    order, visited, stack = [], set(), []
    for root in head_nodes:
        if id(root) in visited:
            continue
        stack.append((root, False))
        while stack:
            node, processed = stack.pop()
            if processed:
                order.append(node)
                continue
            if id(node) in visited:
                continue
            visited.add(id(node))
            stack.append((node, True))
            for inp in node.inputs:
                n = getattr(inp, "_node", None)
                if n is not None and id(n) not in visited:
                    stack.append((n, False))
    return order


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):  # noqa: A002
    """Compute gradients of heads w.r.t. marked variables.

    (ref: Imperative::Backward imperative.cc:278)
    """
    from .ndarray.ndarray import NDArray

    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]

    # cotangent accumulator: id(node) -> [cotangent per output]
    cotangents: dict[int, list] = {}
    # within-call gradient accumulator for marked variables: id(arr) -> ct
    var_cts: dict[int, object] = {}
    var_by_id: dict[int, object] = {}

    def _accum_var(arr, ct):
        if getattr(arr, "_grad", None) is None or getattr(arr, "_grad_req", "write") == "null":
            return
        k = id(arr)
        var_by_id[k] = arr
        if k not in var_cts:
            var_cts[k] = ct
            return
        prev = var_cts[k]
        from .ndarray.sparse import BaseSparseNDArray, add as sparse_add

        if isinstance(prev, BaseSparseNDArray) or isinstance(ct, BaseSparseNDArray):
            # rsp+rsp stays sparse; mixed falls back to dense NDArray
            var_cts[k] = sparse_add(prev, ct)
        else:
            var_cts[k] = prev + ct

    head_nodes = []
    for h, hg in zip(heads, head_grads):
        g = hg._data if isinstance(hg, NDArray) else (
            jnp.ones(h.shape, h.dtype) if hg is None else jnp.asarray(hg)
        )
        node = getattr(h, "_node", None)
        if node is None:
            _accum_var(h, g)
            continue
        head_nodes.append(node)
        slot = cotangents.setdefault(id(node), [None] * node.n_outputs)
        idx = h._node_index
        slot[idx] = g if slot[idx] is None else slot[idx] + g
        if getattr(h, "_grad", None) is not None:
            _accum_var(h, g)

    order = _topo_order(head_nodes)
    for node in reversed(order):
        cts = cotangents.pop(id(node), None)
        if cts is None:
            continue
        full = tuple(
            ct if ct is not None else jnp.zeros(shape, dtype)
            for ct, (shape, dtype) in zip(cts, node.out_avals)
        )
        in_cts = node.vjp(full)
        for inp, ct in zip(node.inputs, in_cts):
            if ct is None or not isinstance(inp, NDArray):
                continue
            if hasattr(ct, "dtype") and ct.dtype == jax.dtypes.float0:
                continue
            sub = getattr(inp, "_node", None)
            if sub is not None:
                slot = cotangents.setdefault(id(sub), [None] * sub.n_outputs)
                i = inp._node_index
                slot[i] = ct if slot[i] is None else slot[i] + ct
                # an INTERMEDIATE with an attached grad buffer collects its
                # per-consumer partials here (summing to the full cotangent)
                _accum_var(inp, ct)
            else:
                _accum_var(inp, ct)
        if not retain_graph:
            node.vjp = None  # free residuals

    # write accumulated cotangents into grad buffers per grad_req
    from .ndarray.sparse import BaseSparseNDArray

    for k, ct in var_cts.items():
        arr = var_by_id[k]
        grad = arr._grad
        req = getattr(arr, "_grad_req", "write")
        if isinstance(ct, BaseSparseNDArray):
            # sparse cotangent (e.g. sparse_embedding): the grad buffer
            # BECOMES the sparse array so optimizers hit their lazy paths
            if req == "add" and isinstance(grad, BaseSparseNDArray):
                arr._grad = grad + ct
            elif req == "add" and grad is not None:
                grad._data = grad._data + ct.todense()._data.astype(grad.dtype)
            else:
                arr._grad = ct
            continue
        if isinstance(ct, NDArray):  # mixed sparse+dense accumulation
            ct = ct._data
        if isinstance(grad, BaseSparseNDArray):
            # a dense cotangent displaces last step's sparse buffer; reuse
            # the parameter's original dense buffer so Parameter._grad
            # identity survives (see Parameter._attach_grad)
            prev = grad.todense()._data if req == "add" else None
            grad = getattr(arr, "_dense_grad_buf", None)
            if grad is None:
                grad = NDArray._from_data(jnp.zeros(arr.shape, arr.dtype))
            grad._data = (prev if prev is not None
                          else jnp.zeros(arr.shape, arr.dtype))
            arr._grad = grad
        if req == "add":
            grad._data = grad._data + ct.astype(grad.dtype)
        else:
            if getattr(arr, "_grad_gen", None) == _record_gen():
                # a second backward() in the SAME record scope is about to
                # overwrite this grad. The reference's multi-device pattern
                # (`for l in losses: l.backward()`) writes per-ctx buffers;
                # here params have ONE logical buffer, so that port would
                # silently keep only the last shard's gradient.
                import warnings

                warnings.warn(
                    "gradient overwritten by a second backward() in the "
                    "same record() scope; for sharded losses use "
                    "autograd.backward([loss1, loss2, ...]) (accumulates "
                    "in one pass) or attach_grad(grad_req='add')",
                    RuntimeWarning, stacklevel=2)
            grad._data = jnp.asarray(ct, dtype=grad.dtype).reshape(grad.shape)
            arr._grad_gen = _record_gen()

    if not retain_graph:
        for h in heads:
            if getattr(h, "_node", None) is not None:
                h._node = None


def _grad_taped(heads, variables, head_grads):
    """Cotangent propagation with every vjp call and accumulation RECORDED
    on the tape (create_graph=True): the returned gradients carry tape
    nodes, so a second backward() differentiates through them
    (ref: autograd.grad create_graph — grad-of-grad).

    Deliberately mirrors backward()'s propagation loop rather than sharing
    it: this path re-derives vjps from primal fns and works in NDArray
    (taped) arithmetic, while backward() consumes stored vjp closures over
    raw buffers with sparse-cotangent write-back. Behavioral rules (head
    accumulation, intermediate-variable accumulation, non-diff masking)
    must be kept in sync — see the matching comments in backward().
    """
    from .ndarray.ndarray import NDArray

    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]

    cot: dict[int, list] = {}       # id(node) -> [NDArray|None per output]
    var_ct: dict[int, object] = {}  # id(arr) -> NDArray cotangent
    var_ids = {id(v) for v in variables}

    def accum_var(arr, ct):
        k = id(arr)
        if k not in var_ids:
            return
        var_ct[k] = ct if k not in var_ct else var_ct[k] + ct

    head_nodes = []
    for h, hg in zip(heads, head_grads):
        g = hg if isinstance(hg, NDArray) else NDArray._from_data(
            jnp.ones(h.shape, h.dtype) if hg is None else jnp.asarray(hg))
        node = getattr(h, "_node", None)
        accum_var(h, g)  # a head may itself be a requested variable
        if node is None:
            continue
        head_nodes.append(node)
        slot = cot.setdefault(id(node), [None] * node.n_outputs)
        idx = h._node_index
        slot[idx] = g if slot[idx] is None else slot[idx] + g

    order = _topo_order(head_nodes)
    with _AutogradScope(recording=True):
        for node in reversed(order):
            cts = cot.pop(id(node), None)
            if cts is None:
                continue
            if node.vjp is None:
                raise RuntimeError(
                    f"tape for {node.name!r} was already consumed; call the "
                    "earlier backward() with retain_graph=True before "
                    "grad(create_graph=True)")
            if node.fn is None:
                raise NotImplementedError(
                    f"grad(create_graph=True) through custom-vjp node "
                    f"{node.name!r} is not supported")
            full = [ct if ct is not None else NDArray._from_data(
                        jnp.zeros(shape, dtype))
                    for ct, (shape, dtype) in zip(cts, node.out_avals)]
            # re-derive the vjp with the PRIMALS as explicit inputs: the
            # original vjp closure treats them as constants, which would
            # sever d(grad)/d(primal) in the second-order graph
            primal_fn = node.fn
            n_in = len(node.inputs)
            # non-differentiable inputs (int/bool primals) get float0
            # cotangents from jax; mask them STATICALLY by dtype so no
            # shape heuristic ever confuses a real scalar cotangent
            def _dt(a):
                return a.dtype if hasattr(a, "dtype") else jnp.asarray(a).dtype

            diff_mask = [jnp.issubdtype(_dt(a), jnp.floating)
                         for a in node.inputs]

            def vjp_call(*args, _fn=primal_fn, _n=n_in, _mask=tuple(diff_mask)):
                primals, cs = args[:_n], args[_n:]
                _, vjp_fn = jax.vjp(_fn, *primals)
                raw = vjp_fn(tuple(cs))[:_n]
                return tuple(
                    x if m else jnp.zeros(())
                    for x, m in zip(raw, _mask))

            in_cts = invoke_recorded(
                vjp_call, list(node.inputs) + full, name=f"vjp:{node.name}")
            for inp, ct, m in zip(node.inputs, in_cts, diff_mask):
                if not m or not isinstance(inp, NDArray):
                    continue
                sub = getattr(inp, "_node", None)
                if sub is not None:
                    slot = cot.setdefault(id(sub), [None] * sub.n_outputs)
                    i = inp._node_index
                    slot[i] = ct if slot[i] is None else slot[i] + ct
                if id(inp) in var_ids:
                    accum_var(inp, ct)
    out = []
    for v in variables:
        ct = var_ct.get(id(v))
        if ct is None:
            ct = NDArray._from_data(jnp.zeros(v.shape, v.dtype))
        out.append(ct)
    return out


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False, train_mode=True):  # noqa: A002
    """Return grads of heads w.r.t. variables (ref: autograd.grad;
    create_graph=True keeps the gradient computation on the tape so it can
    be differentiated again)."""
    from .ndarray.ndarray import NDArray

    if create_graph:
        single = isinstance(variables, NDArray)
        outs = _grad_taped(heads, [variables] if single else list(variables),
                           head_grads)
        return outs[0] if single else outs
    single = isinstance(variables, NDArray)
    if single:
        variables = [variables]
    saved = [(getattr(v, "_grad", None), getattr(v, "_grad_req", "write")) for v in variables]
    zeros = []
    for v in variables:
        z = NDArray._from_data(jnp.zeros(v.shape, v.dtype))
        zeros.append(z)
        v._grad = z
        v._grad_req = "add"
    backward(heads, head_grads, retain_graph=bool(retain_graph), train_mode=train_mode)
    outs = [v._grad for v in variables]
    for v, (g, r) in zip(variables, saved):
        v._grad, v._grad_req = g, r
    return outs[0] if single else outs


def get_symbol(x):
    raise NotImplementedError("tracing an eager tape to a Symbol is not supported; use hybridize")
