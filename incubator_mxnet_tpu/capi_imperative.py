"""Python half of the imperative C/C++ embedding API.

The reference exposes op-level imperative execution to non-Python frontends
through `MXImperativeInvokeEx` (ref: src/c_api/c_api_ndarray.cc:54 — the
entry point cpp-package's generated `op.h` wrappers call). The TPU-native
analog keeps the op registry, autograd tape, and XLA dispatch in-process by
EMBEDDING the interpreter: `src/imperative.cc` (libmxtpu_imperative.so)
hosts CPython, imports this module once, and funnels every C call through
the small, C-friendly functions below (plain handles in, plain handles
out). C++ users get the real framework — all registered ops, the real
autograd tape, real XLA CPU/TPU execution — not a host-side re-implementation.

Everything here works on NDArray objects; the C side holds them as opaque
PyObject* handles with ownership managed by Py_INCREF/DECREF.
"""
from __future__ import annotations

import json

import numpy as np

from . import autograd
from .deploy import _DTYPE_CODES
from .ndarray.ndarray import NDArray
from .ndarray.register import invoke_by_name
from .ops.registry import OP_REGISTRY

_CODE_TO_DTYPE = {v: k for k, v in _DTYPE_CODES.items()}


def nd_from_buffer(dtype_code, shape, data):
    """data: bytes (C-order) or None for zeros."""
    dt = np.dtype(_CODE_TO_DTYPE[int(dtype_code)])
    shape = tuple(int(s) for s in shape)
    if data is None:
        arr = np.zeros(shape, dt)
    else:
        arr = np.frombuffer(data, dtype=dt).reshape(shape).copy()
    return NDArray(arr)


def nd_to_bytes(nd):
    return np.ascontiguousarray(nd.asnumpy()).tobytes()


def nd_shape(nd):
    return tuple(int(s) for s in nd.shape)


def nd_dtype_code(nd):
    return _DTYPE_CODES[str(np.dtype(nd._data.dtype))]


def invoke(name, inputs, attrs_json):
    """Run one registered op; returns a LIST of NDArray outputs.

    attrs_json: JSON object string; null values are dropped (= use the
    op's default), arrays become tuples (shape-like attrs)."""
    if name not in OP_REGISTRY:
        raise KeyError(f"unknown op '{name}' (see ops.list_ops())")
    kwargs = {}
    if attrs_json:
        for k, v in json.loads(attrs_json).items():
            if v is None:
                continue
            if isinstance(v, list):
                v = tuple(v)
            kwargs[k] = v
    out = invoke_by_name(name, list(inputs), kwargs)
    return list(out) if isinstance(out, (list, tuple)) else [out]


def attach_grad(nd):
    nd.attach_grad()


def grad_of(nd):
    g = nd.grad
    if g is None:
        raise ValueError("no gradient recorded (attach_grad + record first)")
    return g


# LIFO of (prev_recording, prev_training) so C++ AutogradRecord scopes nest
# and restore enclosing state exactly like autograd.record()'s context
# manager (clobbering to False would silently un-tape an outer scope).
# NOTE: autograd state is thread-local — begin/invoke/backward must run on
# the same OS thread (documented on the C ABI).
_REC_STACK = []


def record_begin(train_mode):
    prev_rec = autograd.set_recording(True)
    prev_train = autograd.set_training(bool(train_mode))
    _REC_STACK.append((prev_rec, prev_train))


def record_end():
    prev_rec, prev_train = _REC_STACK.pop() if _REC_STACK else (False, False)
    autograd.set_recording(prev_rec)
    autograd.set_training(prev_train)


def backward(loss):
    loss.backward()


def op_list():
    return "\n".join(sorted(OP_REGISTRY))


# -- graph-level execution (ref: c_api_executor.cc MXExecutorSimpleBind /
# GraphExecutor — the whole symbol runs as ONE jitted XLA program, unlike
# the per-op `invoke` path above) -------------------------------------------


def sym_bind(symbol_json, names, arrays, grad_names):
    """Bind a serialized symbol over named argument arrays -> Executor.

    `grad_names` selects the arguments that accumulate gradients during
    exec_backward (grad_req='write'); everything else binds 'null'."""
    from .symbol import symbol as sym_mod

    s = sym_mod.load_json(symbol_json)
    wanted = s.list_arguments()
    # None = a null C handle: treat as not supplied (clean error below)
    args = {n: a for n, a in zip(list(names), list(arrays)) if a is not None}
    missing = [n for n in wanted if n not in args]
    if missing:
        raise ValueError(f"sym_bind: missing arguments {missing}")
    gset = set(grad_names)
    unknown = sorted(gset - set(wanted))
    if unknown:
        raise ValueError(f"sym_bind: grad names {unknown} are not "
                         f"arguments of the symbol")
    reqs = {n: ("write" if n in gset else "null") for n in wanted}
    return s.bind(args={n: args[n] for n in wanted}, grad_req=reqs)


def exec_set_arg(ex, name, nd):
    """Feed new data into a bound argument (dtype-preserving, the
    Executor.forward(**kwargs) semantics)."""
    if name not in ex.arg_dict:
        raise KeyError(f"exec_set_arg: unknown argument '{name}'")
    data = nd._data
    slot = ex.arg_dict[name]._data
    if data.dtype != slot.dtype:
        data = data.astype(slot.dtype)
    ex.arg_dict[name]._data = data


def exec_forward(ex, is_train):
    """Run the compiled graph; returns the output NDArrays."""
    return list(ex.forward(is_train=bool(is_train)))


def exec_backward(ex):
    """Ones-seeded backward into the bound gradient arrays."""
    ex.backward()


def exec_grad(ex, name):
    g = ex.grad_dict.get(name)
    if g is None:
        raise KeyError(f"exec_grad: no gradient bound for '{name}'")
    return g


# -- kvstore (ref: src/c_api/c_api.cc MXKVStoreCreate/Init/PushEx/PullEx +
# scala-package core KVStore — the surface the reference's spark/
# integration trains through). Handles are KVStore objects; dist types
# bootstrap jax.distributed from the MXTPU_* launcher env exactly like the
# Python frontend (kvstore.create), so a C++/JVM worker process launched by
# tools/launch.py joins the same communicator as a Python one. ------------


def kv_create(kv_type):
    from . import kvstore as kvstore_mod

    return kvstore_mod.create(kv_type)


def kv_type(kv):
    return kv.type


def kv_init(kv, key, nd):
    kv.init(key, nd)


def kv_push(kv, key, nd):
    kv.push(key, nd)


def kv_pull(kv, key, out_nd):
    kv.pull(key, out=out_nd)


def kv_pushpull(kv, key, nd, out_nd):
    kv.pushpull(key, nd, out=out_nd)


def kv_set_optimizer(kv, name, params_json):
    """Build a registered optimizer from (name, params JSON) and install it
    as the store-side updater — push then APPLIES updates to the stored
    weight instead of accumulating (ref: kvstore.py set_optimizer, which
    pickles the optimizer to the dist servers)."""
    from . import optimizer as opt_mod

    kwargs = json.loads(params_json) if params_json else {}
    kv.set_optimizer(opt_mod.create(name, **kwargs))


def kv_rank_size(kv):
    return (int(kv.rank), int(kv.num_workers))


def kv_barrier(kv):
    kv.barrier()


def kv_num_dead(kv):
    return int(kv.num_dead_node)
