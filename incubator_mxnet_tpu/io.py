"""Data iterators (ref: python/mxnet/io/io.py — DataIter:178, NDArrayIter:489;
C++ prefetch pipeline ref: src/io/iter_prefetcher.h:47).

TPU-native notes: batches are assembled host-side in numpy and transferred
async via jax device_put (the engine-scheduled CopyFromTo analog);
PrefetchingIter double-buffers on a worker thread exactly like the
reference's PrefetcherIter.
"""
from __future__ import annotations

import collections
import queue as _queue
import threading

import numpy as np

from .ndarray.ndarray import NDArray
from .ndarray import array as nd_array

__all__ = [
    "DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
    "PrefetchingIter", "CSVIter", "MXDataIter",
]


class DataDesc(collections.namedtuple("DataDesc", ["name", "shape"])):
    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return f"DataDesc[{self.name},{self.shape},{self.dtype},{self.layout}]"

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """(ref: io.py DataBatch)"""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        shapes = [d.shape for d in self.data] if self.data else []
        return f"DataBatch: data shapes: {shapes}"


class DataIter:
    """(ref: io.py:178 DataIter)"""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(
                data=self.getdata(), label=self.getlabel(),
                pad=self.getpad(), index=self.getindex(),
            )
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize to list of (name, numpy array) (ref: io.py _init_data)."""
    if data is None:
        return []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty and len(data) == 0:
            raise ValueError(f"{default_name} cannot be empty")
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    out = []
    for k, v in data.items():
        v = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
        if v.dtype == np.float64:
            v = v.astype(np.float32)
        out.append((k, v))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (ref: io.py:489 NDArrayIter)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        self.idx = np.arange(self.num_data)
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        if last_batch_handle == "discard":
            self.num_batches = self.num_data // batch_size
        else:
            self.num_batches = (self.num_data + batch_size - 1) // batch_size
        self.reset()

    @property
    def provide_data(self):
        return [
            DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype) for k, v in self.data
        ]

    @property
    def provide_label(self):
        return [
            DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype) for k, v in self.label
        ]

    def reset(self):
        if self.shuffle:
            np.random.shuffle(self.idx)
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _take(self, arrays):
        lo = self.cursor
        hi = min(self.cursor + self.batch_size, self.num_data)
        sel = self.idx[lo:hi]
        pad = self.batch_size - (hi - lo)
        out = []
        for _, v in arrays:
            chunk = v[sel]
            if pad:
                if self.last_batch_handle == "pad":
                    wrap = v[self.idx[:pad]]
                    chunk = np.concatenate([chunk, wrap], axis=0)
                elif self.last_batch_handle == "roll_over":
                    pass
            out.append(nd_array(chunk))
        return out

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        hi = self.cursor + self.batch_size
        if self.last_batch_handle == "pad" and hi > self.num_data:
            return hi - self.num_data
        return 0

    def getindex(self):
        lo = self.cursor
        hi = min(self.cursor + self.batch_size, self.num_data)
        return self.idx[lo:hi]


class ResizeIter(DataIter):
    """Resize the epoch length of an iterator (ref: io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Double-buffering prefetcher on worker threads
    (ref: src/io/iter_prefetcher.h:47 PrefetcherIter)."""

    def __init__(self, iters, rename_data=None, rename_label=None, prefetch_depth=2):
        if not isinstance(iters, list):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self._depth = prefetch_depth
        self._queue = _queue.Queue(maxsize=prefetch_depth)
        self._stop = threading.Event()
        self._thread = None
        self._start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum(
            [
                [DataDesc(r.get(d.name, d.name), d.shape, d.dtype) for d in i.provide_data]
                for r, i in zip(self.rename_data, self.iters)
            ],
            [],
        )

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum(
            [
                [DataDesc(r.get(d.name, d.name), d.shape, d.dtype) for d in i.provide_label]
                for r, i in zip(self.rename_label, self.iters)
            ],
            [],
        )

    def _worker(self):
        while not self._stop.is_set():
            try:
                batches = [i.next() for i in self.iters]
            except StopIteration:
                self._queue.put(None)
                return
            batch = DataBatch(
                data=sum([b.data for b in batches], []),
                label=sum([(b.label or []) for b in batches], []),
                pad=batches[0].pad,
                index=batches[0].index,
            )
            while not self._stop.is_set():
                try:
                    self._queue.put(batch, timeout=0.1)
                    break
                except _queue.Full:
                    continue

    def _start(self):
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="mxtpu-io-prefetch")
        self._thread.start()

    def reset(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
        while not self._queue.empty():
            self._queue.get_nowait()
        for i in self.iters:
            i.reset()
        self._stop = threading.Event()
        self._queue = _queue.Queue(maxsize=self._depth)
        self._start()

    def next(self):
        batch = self._queue.get()
        if batch is None:
            raise StopIteration
        return batch

    def iter_next(self):
        raise NotImplementedError

    def __del__(self):
        self._stop.set()


class CSVIter(DataIter):
    """CSV reader (ref: src/io/iter_csv.cc) — host-side parse + batch."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = (
            np.loadtxt(label_csv, delimiter=",", dtype=np.float32).reshape((-1,) + tuple(label_shape))
            if label_csv else np.zeros((data.shape[0],) + tuple(label_shape), np.float32)
        )
        self._inner = NDArrayIter(
            {"data": data}, {"label": label}, batch_size,
            last_batch_handle="pad" if round_batch else "discard",
            label_name="label",
        )

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


def MXDataIter(*args, **kwargs):  # pragma: no cover - parity shim
    raise NotImplementedError(
        "C++-registered iterators surface as ImageRecordIter in the io package"
    )


# C++-backed record iterators live in io_record.py to keep this module the
# pure-Python DataIter layer (mirrors the reference's python/mxnet/io/ vs
# src/io/ split); surface them here like the reference's registry does.
from .io_record import ImageRecordIter, MNISTIter, LibSVMIter  # noqa: E402,F401

__all__ += ["ImageRecordIter", "MNISTIter", "LibSVMIter"]
