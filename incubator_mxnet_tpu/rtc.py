"""Runtime-compiled custom kernels.

TPU-native equivalent of the reference's NVRTC bridge (ref: src/common/rtc.cc
:31-69, python/mxnet/rtc.py CudaModule): where the reference compiles CUDA C
source at runtime, here user kernels are Pallas functions compiled by Mosaic
for the TPU's VMEM/MXU/VPU. `PallasModule` keeps the CudaModule UX: wrap a
kernel, get a callable with declared signature.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .ndarray.ndarray import NDArray

__all__ = ["PallasModule", "CudaModule"]


class PallasModule:
    """Hold a user Pallas kernel and expose launchable functions
    (API shape ref: python/mxnet/rtc.py CudaModule.get_kernel)."""

    def __init__(self, kernel_fn, interpret=None):
        self._kernel = kernel_fn
        if interpret is None:
            interpret = jax.default_backend() == "cpu"
        self._interpret = interpret

    def get_kernel(self, out_shape, out_dtype="float32", grid=None,
                   in_specs=None, out_specs=None, **pallas_kwargs):
        from jax.experimental import pallas as pl

        def launch(*arrays):
            datas = [a._data if isinstance(a, NDArray) else jnp.asarray(a) for a in arrays]
            kw = dict(pallas_kwargs)
            if grid is not None:
                kw["grid"] = grid
            if in_specs is not None:
                kw["in_specs"] = in_specs
            if out_specs is not None:
                kw["out_specs"] = out_specs
            call = pl.pallas_call(
                self._kernel,
                out_shape=jax.ShapeDtypeStruct(tuple(out_shape), np.dtype(out_dtype)),
                interpret=self._interpret,
                **kw,
            )
            return NDArray._from_data(call(*datas))

        return launch


def CudaModule(*args, **kwargs):  # pragma: no cover - parity shim
    raise NotImplementedError(
        "CUDA runtime compilation does not exist on TPU; use rtc.PallasModule "
        "to author runtime-compiled Pallas kernels"
    )
