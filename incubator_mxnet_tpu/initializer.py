"""Weight initializers (ref: python/mxnet/initializer.py).

Same registry + `InitDesc`-style name-pattern dispatch as the reference;
values are produced with jax PRNG through the global seed state.
"""
from __future__ import annotations

import json
import math
import re

import numpy as np

import jax
import jax.numpy as jnp

from . import random as _global_random
from .ndarray.ndarray import NDArray

__all__ = [
    "Initializer", "init", "register", "create", "Zero", "One", "Constant",
    "Uniform", "Normal", "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear",
    "LSTMBias", "FusedRNN", "Mixed", "Load", "InitDesc",
]

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    if isinstance(name, (list, tuple)):
        # the dumps() wire form after json parsing: [name, kwargs]
        name, kwargs = name[0], {**(name[1] or {}), **kwargs}
    elif isinstance(name, str) and name.startswith("["):
        # a dumps() string as-is (e.g. an __init__ attr that rode a
        # serialized symbol)
        parsed = json.loads(name)
        name, kwargs = parsed[0], {**(parsed[1] or {}), **kwargs}
    return _REGISTRY[name.lower()](**kwargs)


class InitDesc(str):
    """Name + attrs describing the array being initialized."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base initializer with the reference's name-based dispatch
    (ref: Initializer.__call__ in python/mxnet/initializer.py)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("desc must be a string or InitDesc")
        if isinstance(desc, InitDesc) and desc.attrs.get("__init__"):
            create(desc.attrs["__init__"])._init_weight(desc, arr)
            return
        name = str(desc)
        if name.endswith("weight"):
            self._init_weight(name, arr)
        elif name.endswith("bias"):
            self._init_bias(name, arr)
        elif name.endswith("gamma"):
            self._init_gamma(name, arr)
        elif name.endswith("beta"):
            self._init_beta(name, arr)
        elif name.endswith("moving_mean") or name.endswith("running_mean"):
            self._init_zero(name, arr)
        elif name.endswith("moving_var") or name.endswith("running_var"):
            self._init_one(name, arr)
        elif name.endswith("min") or name.endswith("max"):
            self._init_zero(name, arr)
        elif name.endswith("parameters") and "rnn" in name:
            self._init_weight(name, arr)
        else:
            self._init_default(name, arr)

    # helpers -------------------------------------------------------------
    def _set(self, arr, value):
        arr._data = jnp.asarray(value, dtype=arr._data.dtype).reshape(arr.shape)

    def _init_zero(self, name, arr):
        self._set(arr, jnp.zeros(arr.shape))

    def _init_one(self, name, arr):
        self._set(arr, jnp.ones(arr.shape))

    def _init_bias(self, name, arr):
        self._init_zero(name, arr)

    def _init_gamma(self, name, arr):
        self._init_one(name, arr)

    def _init_beta(self, name, arr):
        self._init_zero(name, arr)

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _init_default(self, name, arr):
        self._init_weight(name, arr)


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        self._init_zero(name, arr)


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        self._init_one(name, arr)


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        self._set(arr, jnp.full(arr.shape, self.value))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        k = _global_random.next_key()
        self._set(arr, jax.random.uniform(k, arr.shape, minval=-self.scale, maxval=self.scale))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        k = _global_random.next_key()
        self._set(arr, self.sigma * jax.random.normal(k, arr.shape))


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        k = _global_random.next_key()
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = jax.random.uniform(k, (nout, nin), minval=-1.0, maxval=1.0)
        else:
            tmp = jax.random.normal(k, (nout, nin))
        u, _, v = jnp.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        self._set(arr, self.scale * q.reshape(arr.shape))


@register
class Xavier(Initializer):
    """(ref: initializer.py Xavier — default for most reference examples)"""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            self._init_zero(name, arr)
            return
        if len(shape) > 2:
            hw_scale = float(np.prod(shape[2:]))
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in, "out": fan_out}[self.factor_type]
        scale = math.sqrt(self.magnitude / factor)
        k = _global_random.next_key()
        if self.rnd_type == "uniform":
            self._set(arr, jax.random.uniform(k, shape, minval=-scale, maxval=scale))
        else:
            self._set(arr, scale * jax.random.normal(k, shape))


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        shape = arr.shape
        weight = np.zeros(int(np.prod(shape)), dtype="float32")
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._set(arr, weight.reshape(shape))


@register
class LSTMBias(Initializer):
    """Forget-gate bias = 1 (ref: initializer.py LSTMBias); our gate order is
    [i, f, g, o] so the second quarter is the forget gate."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = np.zeros(arr.shape, dtype="float32")
        n = arr.shape[0] // 4
        b[n : 2 * n] = self.forget_bias
        self._set(arr, b)

    _init_bias = _init_weight


@register
class FusedRNN(Initializer):
    """Initialize the fused RNN op's packed parameter vector (ref:
    initializer.py FusedRNN): the weight section gets `init` (default
    Xavier), the bias section zeros — except LSTM forget-gate i2h biases,
    which get `forget_bias`. Layout must match ops/nn.py
    _rnn_slice_params (weights per (layer, direction), then biases)."""

    def __init__(self, init=None, num_hidden=0, num_layers=1, mode="lstm",
                 bidirectional=False, forget_bias=1.0):
        super().__init__(num_hidden=num_hidden, num_layers=num_layers,
                         mode=mode, bidirectional=bidirectional,
                         forget_bias=forget_bias)
        if isinstance(init, str):
            init = create(init)
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, name, arr):
        from .ops.nn import _GATES

        H = self._num_hidden
        D = 2 if self._bidirectional else 1
        G = _GATES[self._mode]
        L = self._num_layers
        n_bias = L * D * 2 * G * H
        n_weight = arr.shape[0] - n_bias

        # the packed weight section is (G*H, inp)+(G*H, H) blocks per
        # (layer, direction); the inner init must see each 2-D matrix (a
        # flat vector would hit Xavier's 1-D zero-fill branch)
        inner = self._init or Xavier()
        inp0 = n_weight // (D * G * H) - (L - 1) * (H * D + H) - H
        blocks = []
        for layer in range(L):
            inp = inp0 if layer == 0 else H * D
            for _ in range(D):
                for shape in ((G * H, inp), (G * H, H)):
                    block = NDArray(jnp.zeros(shape, dtype=arr.dtype))
                    inner._init_weight(name, block)
                    blocks.append(block._data.reshape(-1))
        weights = NDArray(jnp.concatenate(blocks))
        assert weights.shape[0] == n_weight, \
            "FusedRNN init walked a different layout than the op"

        biases = np.zeros((n_bias,), dtype="float32")
        if self._mode == "lstm":
            # per (layer, direction): i2h biases [i f g o], then h2h
            for blk in range(L * D):
                start = blk * 2 * G * H + H  # forget gate of the i2h part
                biases[start:start + H] = self._forget_bias
        self._set(arr, jnp.concatenate(
            [weights._data, jnp.asarray(biases, dtype=arr.dtype)]))

    _init_bias = _init_weight


@register
class Mixed(Initializer):
    def __init__(self, patterns, initializers):
        super().__init__()
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, i in self.map:
            if prog.match(str(name)):
                i(name, arr)
                return
        raise ValueError(f"parameter {name} did not match any pattern")


@register
class Load:
    """Init from a dict of arrays, falling back to default_init."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = {k.replace("arg:", "").replace("aux:", ""): v for k, v in param.items()}
        self.default_init = default_init

    def __call__(self, name, arr):
        if name in self.param:
            arr._data = jnp.asarray(
                self.param[name]._data if isinstance(self.param[name], NDArray) else self.param[name],
                dtype=arr._data.dtype,
            )
        elif self.default_init is not None:
            self.default_init(name, arr)
        else:
            raise ValueError(f"no init for {name}")


class init:
    """Namespace alias so `mx.init.Xavier()` works like the reference."""

    Zero = Zero
    One = One
    Constant = Constant
    Uniform = Uniform
    Normal = Normal
    Orthogonal = Orthogonal
    Xavier = Xavier
    MSRAPrelu = MSRAPrelu
    Bilinear = Bilinear
    LSTMBias = LSTMBias
    FusedRNN = FusedRNN
    Mixed = Mixed
    Load = Load
    Initializer = Initializer
    InitDesc = InitDesc
