"""Placeholder."""
class init:
    pass
