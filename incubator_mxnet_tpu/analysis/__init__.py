"""Static analysis: graph validator + framework lint.

Two pillars, both emitting structured diagnostics with stable codes
(catalog: docs/STATIC_ANALYSIS.md):

- Graph validator (`validate` / `validate_json`, codes MXA0xx): a pass
  pipeline over Symbol graphs that front-loads the correctness checks the
  reference runs as nnvm passes — structural integrity, full shape/dtype
  inference with op-boundary provenance, and TPU perf hazards (host-sync
  ops, hostile dtypes, tiling-defeating layouts). Reachable as
  `Symbol.validate()`, the opt-in `MXNET_GRAPH_VALIDATE` hook at Executor
  bind time, and `tools/graph_check.py`.

- Framework lint (`mxlint`, codes MXL0xx): an AST checker over
  `incubator_mxnet_tpu/` itself enforcing the framework's own invariants
  (documented config knobs, registered telemetry names, no bare excepts,
  no host materialization in hot paths, documented ops, thread/lock
  hygiene). CLI: `tools/mxlint.py`; CI runs it with the committed
  zero-findings baseline `ci/mxlint_baseline.json`.

A third, dynamic pillar lives in `sanitizers` (codes MXS0xx): opt-in
runtime checkers for the threaded runtime — lock-order/deadlock
detection, KV-page refcount shadow state — enabled per-process via
`MXTPU_SANITIZERS=locks,pages,threads` and free when off. CLI:
`tools/sanitize.py`.
"""
from .diagnostics import (  # noqa: F401
    Diagnostic, Report, Severity, CODE_CATALOG, GraphValidationError,
)
from .passes import validate, validate_json, HOST_SYNC_OPS  # noqa: F401
from .mxlint import LINT_RULES, LintFinding, run_lint  # noqa: F401
from .sanitizers import (  # noqa: F401
    MXS_CATALOG, PageSanitizer, SanitizerError, attach_page_sanitizer,
    san_condition, san_lock, san_rlock,
)

__all__ = [
    "Diagnostic", "Report", "Severity", "CODE_CATALOG",
    "GraphValidationError", "validate", "validate_json", "HOST_SYNC_OPS",
    "LINT_RULES", "LintFinding", "run_lint",
    "MXS_CATALOG", "PageSanitizer", "SanitizerError",
    "attach_page_sanitizer", "san_condition", "san_lock", "san_rlock",
]
