"""Structured diagnostics for the static-analysis layer.

The reference front-loads graph correctness into nnvm passes that fail
with op/node context (ref: src/nnvm/infer_graph_attr_pass.cc error paths);
our XLA-tracing failures are deep and node-anonymous. Every check in this
package therefore reports through one shape: a `Diagnostic` with a stable
`MXA0xx` code, a severity, and per-node provenance (node name, op type,
input names/shapes), collected into a `Report` that renders for humans,
serializes for tooling, and feeds the `mxtpu_graph_validate_findings_total`
counter at Executor bind time.

Code space: `MXA0xx` = graph-validator findings (this module's consumers in
`passes.py`); `MXL0xx` = framework-lint findings (`mxlint.py`). The catalog
lives in docs/STATIC_ANALYSIS.md and is regenerated from `CODE_CATALOG`.
"""
from __future__ import annotations

import dataclasses
import enum
import json

__all__ = ["Severity", "Diagnostic", "Report", "CODE_CATALOG"]


class Severity(enum.IntEnum):
    """Ordered so max() over a report gives the report's overall level."""

    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self):
        return self.name.lower()


# code -> (default severity, one-line summary). docs/STATIC_ANALYSIS.md
# renders this table; tests assert every emitted code is cataloged.
CODE_CATALOG = {
    # structural
    "MXA001": (Severity.ERROR, "graph contains a cycle"),
    "MXA002": (Severity.ERROR, "dangling input: node input refers to a "
                               "missing node or out-of-range output"),
    "MXA003": (Severity.ERROR, "duplicate argument name: two distinct "
                               "variable nodes share a name"),
    "MXA004": (Severity.ERROR, "unknown operator (not in OP_REGISTRY)"),
    # shape / dtype inference
    "MXA010": (Severity.ERROR, "shape/dtype inference failed at an op "
                               "boundary"),
    "MXA011": (Severity.ERROR, "input shapes unavailable: inference could "
                               "not reach this node"),
    "MXA012": (Severity.WARNING, "dtype hazard on TPU (float64/int64 "
                                 "silently demoted or slow; float16 has no "
                                 "MXU support — use bfloat16)"),
    # liveness
    "MXA020": (Severity.WARNING, "dead node: unreachable from any graph "
                                 "head"),
    "MXA021": (Severity.WARNING, "given shape name matches no graph "
                                 "argument (typo?)"),
    "MXA022": (Severity.INFO, "unused node output (computed but never "
                              "consumed and not a head)"),
    # TPU perf hazards
    "MXA030": (Severity.WARNING, "op forces a host transfer / defeats jit "
                                 "(data-dependent output shape)"),
    "MXA031": (Severity.WARNING, "explicit cast to a TPU-hostile dtype"),
    "MXA032": (Severity.INFO, "layout defeats MXU/VPU tiling (lane dim "
                              "128, sublane 8 for f32)"),
}


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding, with per-node provenance.

    `node` / `op` / `inputs` carry the graph context the raw XLA trace
    error lacks; `detail` is a short stable discriminator (used for
    dedup and baseline keys), `message` the full human text.
    """

    code: str
    severity: Severity
    message: str
    node: str | None = None
    op: str | None = None
    inputs: tuple = ()  # ((input_name, shape_or_None, dtype_or_None), ...)
    detail: str = ""

    def __str__(self):
        loc = f" [node {self.node}" + (f" ({self.op})]" if self.op else "]") \
            if self.node else ""
        return f"{self.code} {self.severity}:{loc} {self.message}"

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["severity"] = str(self.severity)
        d["inputs"] = [list(i) for i in self.inputs]
        return d


class Report:
    """An ordered collection of diagnostics with severity filters."""

    def __init__(self, diagnostics=(), graph_name=None):
        self.diagnostics = list(diagnostics)
        self.graph_name = graph_name

    def append(self, diag):
        self.diagnostics.append(diag)

    def extend(self, diags):
        self.diagnostics.extend(diags)

    def __iter__(self):
        return iter(self.diagnostics)

    def __len__(self):
        return len(self.diagnostics)

    def __bool__(self):
        # truthiness = "has findings"; use .ok for the inverse reading
        return bool(self.diagnostics)

    @property
    def errors(self):
        return [d for d in self.diagnostics if d.severity >= Severity.ERROR]

    @property
    def warnings(self):
        return [d for d in self.diagnostics
                if d.severity == Severity.WARNING]

    @property
    def ok(self):
        """True when nothing error-severity was found."""
        return not self.errors

    def by_code(self, code):
        return [d for d in self.diagnostics if d.code == code]

    def raise_if_errors(self):
        if self.errors:
            raise GraphValidationError(self)
        return self

    def __str__(self):
        name = f" for {self.graph_name}" if self.graph_name else ""
        if not self.diagnostics:
            return f"graph validation{name}: clean"
        lines = [f"graph validation{name}: {len(self.errors)} error(s), "
                 f"{len(self.warnings)} warning(s)"]
        for d in sorted(self.diagnostics, key=lambda d: -int(d.severity)):
            lines.append(f"  {d}")
        return "\n".join(lines)

    def to_json(self, indent=2):
        return json.dumps(
            {"graph": self.graph_name,
             "findings": [d.to_dict() for d in self.diagnostics]},
            indent=indent)


class GraphValidationError(ValueError):
    """Raised by Report.raise_if_errors / MXNET_GRAPH_VALIDATE=raise."""

    def __init__(self, report):
        self.report = report
        super().__init__(str(report))
