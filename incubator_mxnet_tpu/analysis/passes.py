"""Graph-validator pass pipeline over Symbol graphs.

TPU-native analog of the reference's pre-execution nnvm passes (shape/type
inference, graph checks — ref: src/nnvm/infer_graph_attr_pass.cc,
src/executor/graph_executor.cc CheckAndInferShape): every check runs at
graph-construction time and reports per-node provenance instead of letting
XLA tracing throw a deep node-anonymous stack later.

Passes (each a function `(ctx) -> None` appending to `ctx.report`):
  structural  — cycle (MXA001), dangling input (MXA002), duplicate
                names (MXA003)
  given-names — shape kwargs that match no argument (MXA021)
  inference   — full shape/dtype inference with op-boundary mismatch
                reporting (MXA010/MXA011), reusing symbol/infer.py so the
                validator and the executor can never disagree
  dtype       — TPU dtype hazards (MXA012) and hostile casts (MXA031)
  host-sync   — ops with data-dependent output shapes that force host
                transfer / defeat jit (MXA030)
  layout      — shapes that defeat MXU/VPU tiling (MXA032; MXU is
                128x128, VPU lanes are 8x128 — see the TPU guide)
  liveness    — unused node outputs (MXA022)

`validate_json` additionally runs structural checks a live Symbol cannot
express (dead nodes unreachable from heads — MXA020, unknown ops —
MXA004) over the serialized nnvm-schema graph.
"""
from __future__ import annotations

import json

import numpy as np

from .diagnostics import Diagnostic, Report, Severity, CODE_CATALOG

__all__ = ["validate", "validate_json", "HOST_SYNC_OPS", "TPU_LANE",
           "TPU_SUBLANE"]

# ops whose output shape depends on input *values*: XLA cannot trace them
# into the fused program, so eager use synchronizes device->host and
# symbolic use forces per-batch retraces (ref: contrib.boolean_mask docs)
HOST_SYNC_OPS = frozenset({
    "boolean_mask",
    "_contrib_boolean_mask",
    "sample_unique_zipfian",
})

# dtypes that TPUs execute degraded: f64 is emulated (silently demoted
# under default XLA flags), int64 is pair-emulated on the VPU, f16 has no
# MXU path (bf16 is the native half type)
_HAZARD_DTYPES = {"float64", "int64", "float16"}

TPU_LANE = 128     # minor-most tile dim, all dtypes
TPU_SUBLANE = 8    # second-minor tile dim for f32


def _diag(code, message, node=None, op=None, inputs=(), detail="",
          severity=None):
    sev, _ = CODE_CATALOG[code]
    return Diagnostic(code=code, severity=severity or sev, message=message,
                      node=node, op=op, inputs=tuple(inputs), detail=detail)


class _Ctx:
    """Per-validation state shared by the passes."""

    def __init__(self, symbol, given, report):
        self.symbol = symbol
        self.given = dict(given or {})
        self.report = report
        self.nodes = symbol._topo_nodes()
        self.heads = list(symbol._outputs)
        # filled by the inference pass: (id(node), out_idx) -> (shape, dtype)
        self.entries = {}
        self.has_cycle = False


# -- structural --------------------------------------------------------------

def _pass_structural(ctx):
    # cycle: iterative three-color DFS from the heads. _topo_nodes uses a
    # visited set so it terminates on cyclic graphs, but its order is then
    # not topological — every later pass tolerates missing producer info.
    WHITE, GREY, BLACK = 0, 1, 2
    color = {}
    for head, _ in ctx.heads:
        if color.get(id(head), WHITE) != WHITE:
            continue
        stack = [(head, iter(head.inputs))]
        color[id(head)] = GREY
        while stack:
            node, it = stack[-1]
            advanced = False
            for src, _i in it:
                c = color.get(id(src), WHITE)
                if c == GREY:
                    ctx.has_cycle = True
                    ctx.report.append(_diag(
                        "MXA001",
                        f"cycle through node {src.name!r}: its inputs "
                        f"transitively depend on its own output",
                        node=src.name,
                        op=None if src.is_var else src.op.name,
                        detail=src.name))
                elif c == WHITE:
                    color[id(src)] = GREY
                    stack.append((src, iter(src.inputs)))
                    advanced = True
                    break
            if not advanced:
                color[id(node)] = BLACK
                stack.pop()

    names_seen = {}
    for n in ctx.nodes:
        # dangling input: entry referencing an output slot the producer
        # does not have (hand-built or corrupted graphs)
        for j, (src, i) in enumerate(n.inputs):
            if i >= src.num_outputs:
                ctx.report.append(_diag(
                    "MXA002",
                    f"input {j} of node {n.name!r} references output {i} "
                    f"of {src.name!r}, which has only {src.num_outputs} "
                    f"output(s)",
                    node=n.name, op=None if n.is_var else n.op.name,
                    detail=f"{n.name}:{j}"))
        prev = names_seen.get(n.name)
        if prev is not None and prev is not n:
            both_vars = n.is_var and prev.is_var
            ctx.report.append(_diag(
                "MXA003",
                f"two distinct {'variable' if both_vars else 'graph'} "
                f"nodes are both named {n.name!r}; name-keyed binding "
                f"(arg_dict, save/load) will silently collapse them",
                node=n.name,
                op=None if n.is_var else n.op.name,
                detail=n.name,
                severity=Severity.ERROR if both_vars else Severity.WARNING))
        else:
            names_seen[n.name] = n


def _pass_given_names(ctx):
    known = set(ctx.symbol.list_inputs())
    for name in ctx.given:
        if name not in known:
            ctx.report.append(_diag(
                "MXA021",
                f"shape given for {name!r}, which is not an input of this "
                f"graph (inputs: {sorted(known)})",
                detail=name))


# -- shape / dtype inference -------------------------------------------------

def _pass_inference(ctx):
    from ..symbol.infer import infer_shapes, ShapeInferenceError

    if ctx.has_cycle:
        # inference over a cyclic graph would report every consumer of the
        # cycle as "missing input shapes" — pure noise after MXA001
        return
    errors = []
    given = {k: v for k, v in ctx.given.items()
             if k in set(ctx.symbol.list_inputs())}
    try:
        infer_shapes(ctx.symbol, given, errors=errors, entry_out=ctx.entries)
    except Exception as e:  # defensive: the collecting mode should not raise
        ctx.report.append(_diag("MXA010", f"shape inference aborted: {e}"))
        return
    for err in errors:
        if isinstance(err, ShapeInferenceError):
            code = "MXA011" if err.missing_inputs else "MXA010"
            ctx.report.append(_diag(
                code, str(err), node=err.node_name, op=err.op_name,
                inputs=err.input_info, detail=err.node_name))
        else:
            ctx.report.append(_diag("MXA010", str(err)))


# -- TPU dtype hazards -------------------------------------------------------

def _pass_dtype(ctx):
    for n in ctx.nodes:
        if n.is_var:
            declared = n.misc_attrs.get("__dtype__")
            if declared and str(np.dtype(declared)) in _HAZARD_DTYPES:
                ctx.report.append(_diag(
                    "MXA012",
                    f"variable {n.name!r} declares dtype {declared}; on "
                    f"TPU float64/int64 are emulated (or silently demoted "
                    f"by XLA) and float16 has no MXU path — prefer "
                    f"float32/bfloat16/int32",
                    node=n.name, detail=f"{n.name}:{declared}"))
            continue
        if n.op.name in ("cast", "Cast", "amp_cast"):
            target = {**n.op.attrs, **n.attrs}.get("dtype")
            if target and str(target) in _HAZARD_DTYPES:
                ctx.report.append(_diag(
                    "MXA031",
                    f"node {n.name!r} casts to {target}; this dtype is "
                    f"TPU-hostile (emulated or silently demoted) and the "
                    f"widening propagates to every consumer",
                    node=n.name, op=n.op.name,
                    detail=f"{n.name}:{target}"))
        # silent upcast at an op boundary: inferred output wider than
        # every input (e.g. an f32 literal promoting a bf16 activation)
        out = ctx.entries.get((id(n), 0))
        if out is None:
            continue
        in_dts = [ctx.entries.get((id(src), i)) for src, i in n.inputs]
        in_dts = [d[1] for d in in_dts if d is not None]
        if not in_dts:
            continue
        out_dt = np.dtype(out[1])
        if (out_dt.kind == "f" and
                all(np.dtype(d).kind == "f" for d in in_dts) and
                all(np.dtype(d).itemsize < out_dt.itemsize for d in in_dts)):
            ctx.report.append(_diag(
                "MXA012",
                f"node {n.name!r} ({n.op.name}) silently upcasts: inputs "
                f"are {[str(np.dtype(d)) for d in in_dts]} but the output "
                f"is {out_dt} — a float32 constant or attr is promoting "
                f"the computation",
                node=n.name, op=n.op.name, detail=f"{n.name}:upcast"))


# -- host-sync / jit hazards -------------------------------------------------

def _pass_host_sync(ctx):
    for n in ctx.nodes:
        if not n.is_var and n.op.name in HOST_SYNC_OPS:
            ctx.report.append(_diag(
                "MXA030",
                f"node {n.name!r} uses op {n.op.name!r}, whose output "
                f"shape depends on input values: it cannot live inside "
                f"the fused XLA program and forces a host round-trip "
                f"(and a retrace per distinct result shape) every step",
                node=n.name, op=n.op.name, detail=n.name))


# -- layout / tiling ---------------------------------------------------------

def _pass_layout(ctx):
    for n in ctx.nodes:
        if n.is_var:
            continue
        attrs = {**n.op.attrs, **n.attrs}
        if n.op.name == "FullyConnected":
            nh = int(attrs.get("num_hidden") or 0)
            if nh and nh % TPU_LANE:
                ctx.report.append(_diag(
                    "MXA032",
                    f"node {n.name!r}: num_hidden={nh} is not a multiple "
                    f"of {TPU_LANE}; the MXU pads the output lane dim to "
                    f"{-(-nh // TPU_LANE) * TPU_LANE} "
                    f"({100 * (-(-nh // TPU_LANE) * TPU_LANE - nh) // max(nh, 1)}% wasted)",
                    node=n.name, op=n.op.name, detail=f"{n.name}:{nh}"))
        elif n.op.name in ("Convolution", "Deconvolution"):
            nf = int(attrs.get("num_filter") or 0)
            if nf and nf % TPU_SUBLANE:
                ctx.report.append(_diag(
                    "MXA032",
                    f"node {n.name!r}: num_filter={nf} is not a multiple "
                    f"of {TPU_SUBLANE}; channel tiling pads every "
                    f"activation tile",
                    node=n.name, op=n.op.name, detail=f"{n.name}:{nf}"))
        elif n.op.name == "Embedding":
            od = int(attrs.get("output_dim") or 0)
            if od and od % TPU_LANE:
                ctx.report.append(_diag(
                    "MXA032",
                    f"node {n.name!r}: output_dim={od} is not a multiple "
                    f"of {TPU_LANE}; embedding rows pad to the lane width",
                    node=n.name, op=n.op.name, detail=f"{n.name}:{od}"))


# -- liveness ----------------------------------------------------------------

def _pass_unused_outputs(ctx):
    used = set()
    for n in ctx.nodes:
        for src, i in n.inputs:
            used.add((id(src), i))
    for node, i in ctx.heads:
        used.add((id(node), i))
    for n in ctx.nodes:
        if n.is_var or n.num_outputs <= 1:
            continue
        unused = [i for i in range(n.num_outputs) if (id(n), i) not in used]
        if unused:
            ctx.report.append(_diag(
                "MXA022",
                f"node {n.name!r} ({n.op.name}) computes "
                f"{n.num_outputs} outputs but output(s) {unused} are "
                f"never consumed",
                node=n.name, op=n.op.name,
                detail=f"{n.name}:{unused}"))


_PASSES = (
    _pass_structural,
    _pass_given_names,
    _pass_inference,
    _pass_dtype,
    _pass_host_sync,
    _pass_layout,
    _pass_unused_outputs,
)


def validate(symbol, shapes=None, name=None):
    """Run the full pass pipeline over a Symbol.

    `shapes` maps input names to shapes (same kwargs as infer_shape);
    without them the inference pass still runs off `__shape__` attrs and
    parameter-shape rules, reporting what it can. Returns a Report.
    """
    report = Report(graph_name=name or getattr(symbol, "name", None))
    ctx = _Ctx(symbol, shapes, report)
    for p in _PASSES:
        p(ctx)
    return report


def validate_json(json_str, shapes=None, name=None):
    """Validate a serialized graph (`Symbol.tojson` / `*-symbol.json`).

    Runs the raw-dict structural checks first — dead nodes (MXA020) and
    unknown ops (MXA004) are only expressible in the serialized form,
    since a live Symbol is reachable-by-construction — then, when the
    graph is loadable, the full Symbol pipeline.
    """
    from ..ops.registry import OP_REGISTRY
    from ..symbol.symbol import load_json

    report = Report(graph_name=name)
    try:
        d = json.loads(json_str)
    except ValueError as e:
        report.append(_diag("MXA004", f"not a graph json: {e}"))
        return report

    nodes = d.get("nodes", [])
    heads = d.get("heads", [])
    loadable = True
    for idx, nd_ in enumerate(nodes):
        op = nd_.get("op", "null")
        if op != "null" and op not in OP_REGISTRY:
            loadable = False
            report.append(_diag(
                "MXA004",
                f"node {nd_.get('name', idx)!r} uses unknown op {op!r}",
                node=nd_.get("name"), op=op, detail=str(nd_.get("name"))))
        for j, ent in enumerate(nd_.get("inputs", [])):
            if ent[0] >= idx:
                # forward/self reference: the json schema is topo-ordered,
                # so this is either corruption or a cycle
                loadable = False
                report.append(_diag(
                    "MXA002",
                    f"node {nd_.get('name', idx)!r} input {j} references "
                    f"node index {ent[0]} at or after itself",
                    node=nd_.get("name"), detail=f"{nd_.get('name')}:{j}"))

    # dead nodes: anything not reachable from the heads
    reachable = set()
    stack = [h[0] for h in heads if h and h[0] < len(nodes)]
    while stack:
        i = stack.pop()
        if i in reachable:
            continue
        reachable.add(i)
        for ent in nodes[i].get("inputs", []):
            if 0 <= ent[0] < len(nodes):
                stack.append(ent[0])
    for idx, nd_ in enumerate(nodes):
        if idx not in reachable:
            report.append(_diag(
                "MXA020",
                f"node {nd_.get('name', idx)!r} "
                f"({nd_.get('op', 'null')}) is unreachable from the graph "
                f"heads: dead weight in the serialized graph",
                node=nd_.get("name"), op=nd_.get("op"),
                detail=str(nd_.get("name"))))

    if loadable:
        try:
            symbol = load_json(json_str)
        except Exception as e:
            report.append(_diag(
                "MXA004", f"graph json failed to load: {e}"))
            return report
        sub = validate(symbol, shapes=shapes, name=name)
        report.extend(sub.diagnostics)
        if report.graph_name is None:
            report.graph_name = sub.graph_name
    return report
