"""Runtime sanitizers for the threaded runtime: lockdep + page refcounts.

Static analysis (passes.py, mxlint.py) covers what an AST can see; this
module covers what only execution can: lock-order inversions between the
PS fleet's handler threads, blocking calls made while a lock is held,
and refcount bugs in the copy-on-write KV page pool. The design follows
kernel lockdep (a global lock-ORDER graph over lock classes, so a
potential ABBA deadlock is reported from a single-threaded run that
merely *establishes* both edges) and ThreadSanitizer's shadow-state idea
(an independent refcount/generation map validates every page
transition), scoped to what Python threads + the GIL actually need.

Everything is off unless `MXTPU_SANITIZERS` lists a sanitizer:

    MXTPU_SANITIZERS=locks,pages,threads

- ``locks``  — `san_lock(name)` / `san_rlock(name)` / `san_condition(name)`
  return instrumented primitives that maintain the lock-order graph,
  flag blocking ops under a held lock (`time.sleep`, `queue.Queue`
  waits, condition waits, and explicit `note_blocking()` sites), and
  flag long hold times (> `MXTPU_SANITIZER_HOLD_MS`).
- ``pages``  — `attach_page_sanitizer(allocator)` arms a shadow-state
  checker that validates every alloc/share/cow/free against its own
  refcount map and per-page generation counters; `assert_quiescent()`
  proves at engine drain that every live reference is owned.
- ``threads`` — no runtime hook; the token gates the MXL008–MXL010
  concurrency lint in CI scenarios (`tools/sanitize.py`).

When the knob is UNSET the factories return *plain* `threading`
primitives — the only cost of the disabled path is one module-load
branch at lock-creation time; there is no per-acquire indirection.

Findings carry stable `MXS0xx` codes (catalog below; rendered in
docs/STATIC_ANALYSIS.md) through the same `Diagnostic`/`Report`
machinery the graph validator uses, feed the
`mxtpu_sanitizer_findings_total{sanitizer,code}` counter, and log
`sanitizer_finding` flight-recorder events, so a CI scenario, a
dashboard, and a post-mortem dump all see the same shape.
"""
from __future__ import annotations

import atexit
import queue
import sys
import threading
import time
import traceback

from .. import config as _config
from .diagnostics import Diagnostic, Report, Severity

__all__ = [
    "MXS_CATALOG", "SanitizerError", "PageSanitizer",
    "enabled", "enabled_set", "refresh_from_env", "reset",
    "san_lock", "san_rlock", "san_condition",
    "note_blocking", "report", "findings",
    "attach_page_sanitizer",
]

FINDINGS_TOTAL = "mxtpu_sanitizer_findings_total"
_FINDINGS_HELP = ("Findings emitted by the runtime sanitizers "
                  "(MXTPU_SANITIZERS), by sanitizer and MXS code.")

# code -> (severity, one-line summary). docs/STATIC_ANALYSIS.md renders
# this table; tests assert every emitted code is cataloged.
MXS_CATALOG = {
    # LockSanitizer
    "MXS001": (Severity.ERROR, "lock-order inversion: the lock-order "
                               "graph contains a cycle (potential ABBA "
                               "deadlock)"),
    "MXS002": (Severity.WARNING, "blocking operation (sleep / queue wait "
                                 "/ condition wait / socket) invoked "
                                 "while holding a sanitized lock"),
    "MXS003": (Severity.WARNING, "lock held longer than "
                                 "MXTPU_SANITIZER_HOLD_MS"),
    # PageSanitizer
    "MXS010": (Severity.ERROR, "page double-free: free/release of a page "
                               "whose shadow refcount is already zero"),
    "MXS011": (Severity.ERROR, "page use-after-free: a mapping or write "
                               "refers to a page whose generation counter "
                               "moved on (freed and reallocated)"),
    "MXS012": (Severity.ERROR, "copy-on-write violation: write into a "
                               "page whose refcount is > 1 (shared "
                               "read-only)"),
    "MXS013": (Severity.ERROR, "refcount leak at drain: live references "
                               "not accounted for by any registered "
                               "owner mapping"),
    "MXS014": (Severity.ERROR, "shadow-state divergence: allocator "
                               "refcounts disagree with the sanitizer's "
                               "shadow map"),
}

_VALID = frozenset({"locks", "pages", "threads"})


class SanitizerError(AssertionError):
    """Raised by `assert_quiescent()` (and other hard checks) with the
    sanitizer report attached."""

    def __init__(self, rep):
        self.report = rep
        super().__init__(str(rep))


# -- knob resolution (module-load branch; refresh_from_env for tests) --------

def _parse(raw):
    toks = {t.strip().lower() for t in str(raw or "").split(",") if t.strip()}
    if toks - _VALID:
        raise ValueError(
            f"MXTPU_SANITIZERS: unknown sanitizer(s) {sorted(toks - _VALID)}"
            f"; valid: {sorted(_VALID)}")
    return frozenset(toks)


_enabled_set = _parse(_config.get("MXTPU_SANITIZERS"))


def enabled_set():
    """The active sanitizer set (frozenset of 'locks'/'pages'/'threads')."""
    return _enabled_set


def enabled(kind):
    """Whether one sanitizer ('locks', 'pages', 'threads') is active."""
    return kind in _enabled_set


def refresh_from_env():
    """Re-resolve MXTPU_SANITIZERS (tests that monkeypatch env) and
    clear all sanitizer state. Only PRIMITIVES CREATED AFTER the refresh
    pick up the new setting — locks are resolved plain-vs-instrumented
    at creation time (that is the zero-cost-when-off contract)."""
    global _enabled_set
    reset()
    _deactivate_blocking_patches()
    _enabled_set = _parse(_config.get("MXTPU_SANITIZERS"))
    if "locks" in _enabled_set:
        _activate_blocking_patches()
    return _enabled_set


# -- findings sink ------------------------------------------------------------

_report = Report(graph_name="sanitizers")
_seen = {}                     # (code, detail) -> Diagnostic
_findings_lock = threading.Lock()


def _emit(code, sanitizer, message, detail):
    """Record one deduped finding and fan it out to telemetry + the
    flight recorder. Dedup key is (code, detail) so a hot loop reports a
    site once, not once per iteration; a re-emission returns the
    already-recorded diagnostic (repeated drain checks stay truthful)."""
    with _findings_lock:
        prior = _seen.get((code, detail))
        if prior is not None:
            return prior
        diag = Diagnostic(code=code, severity=MXS_CATALOG[code][0],
                          message=message, detail=detail)
        _seen[(code, detail)] = diag
        _report.append(diag)
    try:
        from .. import telemetry
        telemetry.inc(FINDINGS_TOTAL, help=_FINDINGS_HELP,
                      sanitizer=sanitizer, code=code)
        telemetry.recorder.log_event("sanitizer_finding",
                                     sanitizer=sanitizer, code=code,
                                     detail=detail)
    except Exception:
        pass  # a finding must never take the runtime down with it
    return diag


def report():
    """Snapshot Report of every finding so far."""
    with _findings_lock:
        return Report(list(_report), graph_name="sanitizers")


def findings(code=None):
    """Finding list, optionally filtered by MXS code."""
    rep = report()
    return rep.by_code(code) if code else list(rep)


def reset():
    """Clear findings, the lock-order graph, and held-lock state (the
    enabled set is untouched — use refresh_from_env to re-resolve)."""
    global _report
    with _findings_lock:
        _report = Report(graph_name="sanitizers")
        _seen.clear()
    with _graph_lock:
        _adj.clear()
        _edge_info.clear()
    _tls.__dict__.clear()


_hold_ms = None


def _hold_threshold_ms():
    global _hold_ms
    if _hold_ms is None:
        _hold_ms = float(_config.get("MXTPU_SANITIZER_HOLD_MS"))
    return _hold_ms


# ============================================================================
# LockSanitizer: lock-order graph + blocking-op + hold-time checks
# ============================================================================

_tls = threading.local()          # per-thread: held = [(name, t0, site)]
_graph_lock = threading.Lock()
_adj: dict = {}                   # name -> set(successor names)
_edge_info: dict = {}             # (a, b) -> {"site", "stack"}


def _held():
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _call_site(depth=3):
    """file:line of the first frame outside this module — the
    acquisition site that keys the order graph's provenance."""
    f = sys._getframe(depth)
    while f is not None and f.f_code.co_filename.endswith("sanitizers.py"):
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{f.f_code.co_filename}:{f.f_lineno}"


def _stack():
    return "".join(traceback.format_stack(sys._getframe(3), limit=10))


def _find_path(src, dst):
    """DFS path src -> dst over the order graph (None when absent)."""
    stack, seen = [(src, [src])], {src}
    while stack:
        node, path = stack.pop()
        for nxt in _adj.get(node, ()):
            if nxt == dst:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _before_acquire(name):
    """Called BEFORE blocking on `name`: record order edges held->name
    and check for a cycle. Running before the blocking acquire means an
    actual deadlock still gets its report out."""
    held = _held()
    if not held:
        return
    site = _call_site()
    for held_name, _t0, held_site in held:
        if held_name == name:
            continue  # same lock class re-entry (RLock) — not an edge
        edge = (held_name, name)
        with _graph_lock:
            if edge in _edge_info:
                continue
            _edge_info[edge] = {"site": f"{held_site} -> {site}",
                                "stack": _stack()}
            _adj.setdefault(held_name, set()).add(name)
            back = _find_path(name, held_name)
        if back is not None:
            cycle = [held_name] + back  # held -> name -> ... -> held
            rev = _edge_info.get((back[0], back[1])) if len(back) > 1 \
                else None
            _emit(
                "MXS001", "locks",
                "potential deadlock: acquiring "
                f"{name!r} while holding {held_name!r} closes the "
                f"lock-order cycle {' -> '.join(cycle)}.\n"
                f"-- this acquisition ({held_site} -> {site}):\n"
                f"{_edge_info[edge]['stack']}"
                + (f"-- prior reverse edge "
                   f"({rev['site']}):\n{rev['stack']}" if rev else ""),
                detail=" -> ".join(_canonical_cycle(cycle)))


def _canonical_cycle(cycle):
    """Rotate a cycle (last element == first) so the lexicographically
    smallest name leads — one stable dedup key per distinct cycle."""
    ring = cycle[:-1]
    k = ring.index(min(ring))
    ring = ring[k:] + ring[:k]
    return ring + [ring[0]]


def _after_acquire(name):
    _held().append((name, time.monotonic(), _call_site()))


def _after_release(name):
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] == name:
            _n, t0, site = held.pop(i)
            dt_ms = (time.monotonic() - t0) * 1000.0
            if dt_ms > _hold_threshold_ms():
                _emit("MXS003", "locks",
                      f"lock {name!r} held {dt_ms:.1f} ms at {site} "
                      f"(threshold MXTPU_SANITIZER_HOLD_MS="
                      f"{_hold_threshold_ms():g})",
                      detail=f"{name}@{site}")
            return


def note_blocking(op, exclude=None):
    """Report MXS002 when the calling thread holds any sanitized lock
    (other than `exclude`). Instrumented blocking sites (jit compile,
    socket helpers) call this; `time.sleep` and `queue.Queue` waits are
    patched automatically while the locks sanitizer is active."""
    held = [h for h in _held() if h[0] != exclude]
    if not held:
        return
    names = [h[0] for h in held]
    site = _call_site()
    _emit("MXS002", "locks",
          f"blocking operation {op!r} at {site} while holding "
          f"lock(s) {names} — a peer waiting on {names[-1]!r} stalls "
          f"behind this wait",
          detail=f"{op}@{site}:{names[-1]}")


# -- blocking-op patches (installed only while the locks sanitizer is on) ----

_real_sleep = None
_real_qget = None
_real_qput = None


def _activate_blocking_patches():
    global _real_sleep, _real_qget, _real_qput
    if _real_sleep is not None:
        return
    _real_sleep = time.sleep
    _real_qget = queue.Queue.get
    _real_qput = queue.Queue.put

    def _sleep(secs):
        note_blocking(f"time.sleep({secs})")
        return _real_sleep(secs)

    def _get(self, block=True, timeout=None):
        if block:
            note_blocking("queue.Queue.get")
        return _real_qget(self, block, timeout)

    def _put(self, item, block=True, timeout=None):
        if block and self.maxsize > 0:
            note_blocking("queue.Queue.put")
        return _real_qput(self, item, block, timeout)

    time.sleep = _sleep
    queue.Queue.get = _get
    queue.Queue.put = _put


def _deactivate_blocking_patches():
    global _real_sleep, _real_qget, _real_qput
    if _real_sleep is None:
        return
    time.sleep = _real_sleep
    queue.Queue.get = _real_qget
    queue.Queue.put = _real_qput
    _real_sleep = _real_qget = _real_qput = None


if "locks" in _enabled_set:
    _activate_blocking_patches()


# -- instrumented primitives --------------------------------------------------

class _SanLock:
    """Instrumented threading.Lock: order-graph edges, blocking-op and
    hold-time checks. Same duck type as threading.Lock."""

    __slots__ = ("name", "_lock")

    def __init__(self, name, lock=None):
        self.name = name
        self._lock = lock if lock is not None else threading.Lock()

    def acquire(self, blocking=True, timeout=-1):
        if blocking:
            _before_acquire(self.name)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            _after_acquire(self.name)
        return ok

    def release(self):
        self._lock.release()
        _after_release(self.name)

    def locked(self):
        return self._lock.locked()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return f"<SanLock {self.name!r} {self._lock!r}>"


class _SanRLock:
    """Instrumented threading.RLock; re-entrant acquires of the same
    lock add no order edges (lockdep's same-class rule)."""

    __slots__ = ("name", "_lock", "_depth")

    def __init__(self, name):
        self.name = name
        self._lock = threading.RLock()
        self._depth = 0  # guarded by _lock itself

    def acquire(self, blocking=True, timeout=-1):
        first = not self._lock._is_owned() \
            if hasattr(self._lock, "_is_owned") else True
        if blocking and first:
            _before_acquire(self.name)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._depth += 1
            if self._depth == 1:
                _after_acquire(self.name)
        return ok

    def release(self):
        self._depth -= 1
        last = self._depth == 0
        self._lock.release()
        if last:
            _after_release(self.name)

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()


class _SanCondition:
    """Instrumented threading.Condition. `wait` is itself a blocking op:
    waiting while holding any OTHER sanitized lock reports MXS002 (the
    classic lost-wakeup/deadlock shape)."""

    __slots__ = ("name", "_cond")

    def __init__(self, name):
        self.name = name
        self._cond = threading.Condition()

    def acquire(self, blocking=True, timeout=-1):
        if blocking:
            _before_acquire(self.name)
        ok = self._cond.acquire(blocking, timeout) if timeout != -1 \
            else self._cond.acquire(blocking)
        if ok:
            _after_acquire(self.name)
        return ok

    def release(self):
        self._cond.release()
        _after_release(self.name)

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def wait(self, timeout=None):
        note_blocking(f"condition.wait({self.name})", exclude=self.name)
        _after_release(self.name)  # wait() drops the lock for its nap
        try:
            return self._cond.wait(timeout)
        finally:
            _after_acquire(self.name)

    def wait_for(self, predicate, timeout=None):
        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = time.monotonic() + timeout
                waittime = endtime - time.monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait()
            result = predicate()
        return result

    def notify(self, n=1):
        self._cond.notify(n)

    def notify_all(self):
        self._cond.notify_all()


def san_lock(name):
    """Named lock factory for the runtime packages. Plain
    `threading.Lock()` when the locks sanitizer is off (resolved once,
    at creation — no per-acquire indirection); an instrumented lock
    participating in the global order graph when it is on. `name` is the
    lock CLASS (lockdep sense): every PS per-key lock shares one class."""
    if "locks" not in _enabled_set:
        return threading.Lock()
    return _SanLock(name)


def san_rlock(name):
    if "locks" not in _enabled_set:
        return threading.RLock()
    return _SanRLock(name)


def san_condition(name):
    if "locks" not in _enabled_set:
        return threading.Condition()
    return _SanCondition(name)


# ============================================================================
# PageSanitizer: shadow refcounts + generation counters for the KV pool
# ============================================================================

class PageSanitizer:
    """Shadow-state checker for a `serving.pages.PageAllocator`.

    Maintains an INDEPENDENT refcount map and a per-page generation
    counter (bumped on every allocation), plus an owner->page->generation
    mapping registry fed by the `owner=` provenance the allocator call
    sites pass (request ids, "prefix_cache"). Every transition the
    allocator performs is validated against the shadow state:

    - free/release at shadow refcount 0         -> MXS010 (double free)
    - share/cow/write of a page whose recorded
      generation moved on                       -> MXS011 (use-after-free)
    - write into a page with refcount > 1       -> MXS012 (COW violation)
    - drain-time references owned by nobody     -> MXS013 (leak)
    - shadow map != allocator refcounts         -> MXS014 (divergence)
    """

    def __init__(self, allocator=None):
        self.allocator = allocator
        self._refs: dict[int, int] = {}
        self._gen: dict[int, int] = {}
        self._next_gen = 0
        self._maps: dict = {}   # owner -> {page: gen-at-map-time}

    # -- transition hooks (called by PageAllocator) -----------------------

    def on_alloc(self, pages, owner=None):
        for p in pages:
            if self._refs.get(p, 0) != 0:
                self._emit_page(
                    "MXS014",
                    f"alloc handed out page {p} which the shadow map "
                    f"still holds at refcount {self._refs[p]}",
                    f"alloc:{p}")
            self._next_gen += 1
            self._refs[p] = 1
            self._gen[p] = self._next_gen
            self._map(owner, p)

    def on_share(self, pages, owner=None):
        for p in pages:
            if self._refs.get(p, 0) == 0:
                self._emit_page(
                    "MXS011",
                    f"share of page {p} at shadow refcount 0 — the new "
                    f"table would read recycled garbage "
                    f"(generation {self._gen.get(p, 0)})",
                    f"share:{p}:g{self._gen.get(p, 0)}")
                continue
            self._refs[p] += 1
            self._map(owner, p)

    def on_cow(self, page, new_page, owner=None):
        """cow() moved one reference off shared `page` onto exclusive
        `new_page` (whose alloc hook already ran). `new_page is None`
        means the pool had no page for the copy (no transition)."""
        if self._refs.get(page, 0) == 0:
            self._emit_page(
                "MXS011",
                f"cow of page {page} at shadow refcount 0",
                f"cow:{page}:g{self._gen.get(page, 0)}")
            return
        if new_page is None or new_page == page:
            return  # exhausted, or caller already exclusive
        self._refs[page] -= 1
        self._unmap(owner, page)
        self._map(owner, new_page)

    def on_free(self, pages, owner=None):
        for p in pages:
            refs = self._refs.get(p, 0)
            if refs == 0:
                self._emit_page(
                    "MXS010",
                    f"double free of page {p} (shadow refcount already "
                    f"0; generation {self._gen.get(p, 0)})",
                    f"free:{p}:g{self._gen.get(p, 0)}")
                continue
            self._refs[p] = refs - 1
            if self._refs[p] == 0:
                del self._refs[p]
            self._unmap(owner, p)

    # -- owner mapping registry -------------------------------------------

    def _map(self, owner, page):
        if owner is None:
            return
        self._maps.setdefault(owner, {})[page] = self._gen.get(page, 0)

    def _unmap(self, owner, page):
        if owner is None:
            return
        m = self._maps.get(owner)
        if m is not None:
            m.pop(page, None)
            if not m:
                self._maps.pop(owner, None)

    # -- write-side checks (engine decode/prefill paths) -------------------

    def note_write(self, owner, pages):
        """The engine is about to write K/V into `pages` on behalf of
        `owner`: a shared page here means the COW discipline failed."""
        for p in pages:
            refs = self._refs.get(p, 0)
            if refs == 0:
                self._emit_page(
                    "MXS011",
                    f"write into page {p} by {owner!r} at shadow "
                    f"refcount 0 (freed page still mapped in a table "
                    f"row)",
                    f"write-uaf:{p}:g{self._gen.get(p, 0)}")
            elif refs > 1:
                self._emit_page(
                    "MXS012",
                    f"write into SHARED page {p} (refcount {refs}) by "
                    f"{owner!r} — other tables map it read-only; it "
                    f"must copy-on-write first",
                    f"write-shared:{p}:{owner}")
            m = self._maps.get(owner)
            if m is not None and p in m and m[p] != self._gen.get(p, 0):
                self._emit_page(
                    "MXS011",
                    f"stale mapping: {owner!r} mapped page {p} at "
                    f"generation {m[p]} but the page is now generation "
                    f"{self._gen.get(p, 0)} (freed and reallocated "
                    f"under a live table row)",
                    f"stale:{p}:{owner}")

    # -- drain-time accounting ---------------------------------------------

    def check(self):
        """Run the quiescence accounting WITHOUT raising; returns the
        list of new findings. At drain every live shadow reference must
        be owned by a registered mapping at the current generation, and
        the shadow map must agree with the allocator."""
        out = []

        def keep(d):
            if d is not None:
                out.append(d)

        # stale mappings (generation moved under a registered owner)
        for owner, m in sorted(self._maps.items(), key=lambda kv: str(kv[0])):
            for p, g in sorted(m.items()):
                if self._gen.get(p, 0) != g:
                    keep(self._emit_page(
                        "MXS011",
                        f"{owner!r} still maps page {p} at generation "
                        f"{g}; page is now generation "
                        f"{self._gen.get(p, 0)}",
                        f"drain-stale:{p}:{owner}"))
        # per-page reference accounting: refs == number of owner mappings
        owned: dict[int, int] = {}
        for m in self._maps.values():
            for p in m:
                owned[p] = owned.get(p, 0) + 1
        for p, refs in sorted(self._refs.items()):
            n_owned = owned.get(p, 0)
            if refs != n_owned:
                owners = sorted(str(o) for o, m in self._maps.items()
                                if p in m)
                keep(self._emit_page(
                    "MXS013",
                    f"page {p} holds {refs} reference(s) at drain but "
                    f"only {n_owned} owner mapping(s) account for them "
                    f"(owners: {owners or 'none'}) — "
                    f"{refs - n_owned:+d} leaked reference(s)",
                    f"leak:{p}"))
        # shadow vs allocator divergence (an allocator bug, not a user one)
        if self.allocator is not None:
            actual = dict(getattr(self.allocator, "_refs", {}))
            if actual != self._refs:
                delta = {p: (self._refs.get(p, 0), actual.get(p, 0))
                         for p in set(actual) | set(self._refs)
                         if actual.get(p, 0) != self._refs.get(p, 0)}
                keep(self._emit_page(
                    "MXS014",
                    f"shadow refcounts diverged from the allocator: "
                    f"{{page: (shadow, actual)}} = {delta}",
                    "divergence"))
        return out

    def assert_quiescent(self):
        """Raise SanitizerError when drain-time accounting finds a leak,
        a stale mapping, or shadow divergence. Serving tests and the
        bench call this at end of run."""
        bad = self.check()
        if bad:
            raise SanitizerError(Report(bad, graph_name="page-sanitizer"))
        return True

    # -- plumbing ----------------------------------------------------------

    @staticmethod
    def _emit_page(code, message, detail):
        return _emit(code, "pages", message, detail)


def attach_page_sanitizer(allocator, force=False):
    """Arm a PageSanitizer on `allocator` when the pages sanitizer is
    enabled (or `force=True`, for tests): the allocator's transition
    hooks start feeding it. Returns the sanitizer, or None when off."""
    if not force and "pages" not in _enabled_set:
        return None
    san = PageSanitizer(allocator)
    allocator.sanitizer = san
    return san


# -- end-of-process visibility ------------------------------------------------

@atexit.register
def _report_at_exit():
    """Print the findings summary at interpreter exit so subprocess
    scenarios (tools/sanitize.py running chaos_train) surface findings
    without a side channel. Stable grep token: '[sanitizers]'."""
    if not _enabled_set:
        return
    rep = report()
    if rep:
        print(f"[sanitizers] {len(rep)} finding(s):", file=sys.stderr)
        for d in rep:
            print(f"[sanitizers] {d.code} {d.severity}: "
                  f"{d.message.splitlines()[0]}", file=sys.stderr)
