"""Bucketed sentence iteration for language models
(ref: python/mxnet/rnn/io.py — encode_sentences:31, BucketSentenceIter:84).

TPU-native note: bucketing is the static-shape answer to variable-length
sequences — one jitted program per bucket length (BucketingModule caches
executors per bucket key), no dynamic shapes inside XLA.
"""
from __future__ import annotations

import bisect
import random

import numpy as np

from ..io import DataBatch, DataDesc, DataIter

__all__ = ["encode_sentences", "BucketSentenceIter"]


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0, unknown_token=None):
    """Map token-string sentences to int ids, growing `vocab` for unseen
    tokens (or mapping them to `unknown_token` when a fixed vocab is
    given). Returns (encoded_sentences, vocab)."""
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        growable = True
    else:
        growable = False
    next_id = start_label
    encoded = []
    for sent in sentences:
        ids = []
        for word in sent:
            if word not in vocab:
                if not growable and not unknown_token:
                    raise ValueError(f"unknown token {word!r} with a fixed "
                                     "vocabulary and no unknown_token")
                if next_id == invalid_label:
                    next_id += 1
                if unknown_token:
                    word = unknown_token
                if word not in vocab:
                    vocab[word] = next_id
                    next_id += 1
            ids.append(vocab[word])
        encoded.append(ids)
    return encoded, vocab


class BucketSentenceIter(DataIter):
    """Pad each sentence up to its bucket length; label is the sequence
    shifted left by one (next-token prediction). Batches come from one
    bucket at a time so every batch has a static shape."""

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label",
                 dtype="float32", layout="NT"):
        super().__init__(batch_size)
        if not buckets:
            counts = np.bincount([len(s) for s in sentences])
            buckets = [length for length, n in enumerate(counts)
                       if n >= batch_size]
        buckets = sorted(buckets)
        self.buckets = buckets
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.layout = layout
        self.major_axis = layout.find("N")

        padded = [[] for _ in buckets]
        ndiscard = 0
        for sent in sentences:
            bucket = bisect.bisect_left(buckets, len(sent))
            if bucket == len(buckets):
                ndiscard += 1
                continue
            row = np.full((buckets[bucket],), invalid_label, dtype=dtype)
            row[:len(sent)] = sent
            padded[bucket].append(row)
        if ndiscard:
            import logging

            logging.warning("discarded %d sentences longer than the largest "
                            "bucket %d", ndiscard, buckets[-1])
        self.data = [
            np.asarray(rows, dtype=dtype) if rows
            else np.empty((0, buckets[b]), dtype=dtype)
            for b, rows in enumerate(padded)]

        self.default_bucket_key = max(buckets)
        shape = ((batch_size, self.default_bucket_key)
                 if self.major_axis == 0
                 else (self.default_bucket_key, batch_size))
        self.provide_data = [DataDesc(data_name, shape, layout=layout)]
        self.provide_label = [DataDesc(label_name, shape, layout=layout)]
        self.idx = []
        for b, rows in enumerate(self.data):
            self.idx.extend((b, start) for start
                            in range(0, len(rows) - batch_size + 1,
                                     batch_size))
        self.curr_idx = 0
        self.reset()

    def reset(self):
        self.curr_idx = 0
        random.shuffle(self.idx)
        for rows in self.data:
            np.random.shuffle(rows)
        self.nddata, self.ndlabel = [], []
        for rows in self.data:
            label = np.empty_like(rows)
            label[:, :-1] = rows[:, 1:]
            label[:, -1] = self.invalid_label
            self.nddata.append(rows)
            self.ndlabel.append(label)

    def next(self):
        from .. import nd

        if self.curr_idx == len(self.idx):
            raise StopIteration
        b, start = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.nddata[b][start:start + self.batch_size]
        label = self.ndlabel[b][start:start + self.batch_size]
        if self.major_axis == 1:
            data, label = data.T, label.T
        shape = data.shape
        return DataBatch(
            data=[nd.array(data)], label=[nd.array(label)], pad=0,
            bucket_key=self.buckets[b],
            provide_data=[DataDesc(self.data_name, shape,
                                   layout=self.layout)],
            provide_label=[DataDesc(self.label_name, shape,
                                    layout=self.layout)])
