"""Symbolic RNN API (ref: python/mxnet/rnn/)."""
from .rnn_cell import (  # noqa: F401
    BaseRNNCell, BidirectionalCell, DropoutCell, FusedRNNCell, GRUCell,
    LSTMCell, ModifierCell, RNNCell, RNNParams, ResidualCell,
    SequentialRNNCell, ZoneoutCell,
)
from .rnn import (  # noqa: F401
    do_rnn_checkpoint, load_rnn_checkpoint, save_rnn_checkpoint,
)
from .io import BucketSentenceIter, encode_sentences  # noqa: F401
