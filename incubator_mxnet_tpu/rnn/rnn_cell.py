"""Symbolic RNN cell API (ref: python/mxnet/rnn/rnn_cell.py — BaseRNNCell
:108, RNNCell:362, LSTMCell:408, GRUCell:469, FusedRNNCell:536,
SequentialRNNCell:748, DropoutCell:827, ModifierCell:867, ZoneoutCell:909,
ResidualCell:957, BidirectionalCell:998).

TPU-native shape: a cell is a Symbol-graph builder; `unroll` emits a static
length-T graph that XLA fuses into one program (static shapes — bucketing
handles variable length, `symbol/` jit caches per bucket). `FusedRNNCell`
targets the fused `sym.RNN` op, whose implementation is a `lax.scan` over
the packed cuDNN-layout parameter vector (ops/nn.py:696) — the same
one-program-per-sequence property the reference only gets on GPU via cuDNN.

One documented deviation: initial states default to shape (1, H) zeros and
broadcast against the (N, ...) batch inside the graph, instead of the
reference's 0-as-unknown batch placeholder (our shape inference is
jax.eval_shape, which has no unknown dims). Feed `begin_state(
func=sym.Variable)` states explicitly to override.
"""
from __future__ import annotations

from .. import initializer as init
from .. import ndarray as nd
from .. import symbol
from ..ops.nn import _GATES, rnn_param_size

__all__ = [
    "RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
    "FusedRNNCell", "SequentialRNNCell", "DropoutCell", "ModifierCell",
    "ZoneoutCell", "ResidualCell", "BidirectionalCell",
]


class RNNParams:
    """Variable container enabling weight sharing between cells
    (ref: rnn_cell.py:77)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.Variable(name, **kwargs)
        return self._params[name]


def _as_steps(inputs, length, layout):
    """Normalize `inputs` into a per-step Symbol list.

    Accepts one (N,T,...)/(T,N,...) Symbol (split along the T axis of
    `layout`) or an existing list; returns (steps, t_axis)."""
    t_axis = layout.find("T")
    assert t_axis >= 0, f"invalid layout {layout}"
    if isinstance(inputs, symbol.Symbol):
        if len(inputs.list_outputs()) != 1:
            raise ValueError("unroll does not accept grouped symbols; pass a "
                             "list of per-step symbols instead")
        steps = list(symbol.split(inputs, axis=t_axis, num_outputs=length,
                                  squeeze_axis=1))
    else:
        steps = list(inputs)
        assert length is None or len(steps) == length
    return steps, t_axis


def _merge_steps(outputs, layout, merge):
    """Per-step Symbol list -> one stacked Symbol (merge=True) or the list
    (merge=False/None)."""
    if not merge:
        return outputs
    t_axis = layout.find("T")
    return symbol.stack(*outputs, axis=t_axis)


class BaseRNNCell:
    """Graph-building recurrent cell: __call__ emits one step, unroll
    emits T steps (ref: rnn_cell.py:108)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        """Reset step counters before building another graph."""
        self._init_counter = -1
        self._counter = -1
        for cell in getattr(self, "_cells", []):
            cell.reset()

    def __call__(self, inputs, states):
        raise NotImplementedError()

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError()

    @property
    def state_shape(self):
        return [info["shape"] for info in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=symbol.zeros, **kwargs):
        """Initial-state symbols, one per state_info entry. Default: (1, H)
        zeros that broadcast over the batch (see module docstring)."""
        assert not self._modified, (
            "After applying modifier cells the base cell cannot be called "
            "directly. Call the modifier cell instead.")
        states = []
        for info in self.state_info:
            self._init_counter += 1
            call_kwargs = dict(kwargs)
            if info is not None:
                call_kwargs.update(info)
            call_kwargs.pop("__layout__", None)
            states.append(func(
                name=f"{self._prefix}begin_state_{self._init_counter}",
                **call_kwargs))
        return states

    def unpack_weights(self, args):
        """Split this cell's packed i2h/h2h arrays into per-gate entries
        (ref: rnn_cell.py unpack_weights)."""
        args = dict(args)
        gates = self._gate_names
        if not gates:
            return args
        h = self._num_hidden
        for group in ("i2h", "h2h"):
            w = args.pop(f"{self._prefix}{group}_weight")
            b = args.pop(f"{self._prefix}{group}_bias")
            for j, gate in enumerate(gates):
                args[f"{self._prefix}{group}{gate}_weight"] = w[j*h:(j+1)*h].copy()
                args[f"{self._prefix}{group}{gate}_bias"] = b[j*h:(j+1)*h].copy()
        return args

    def pack_weights(self, args):
        """Inverse of unpack_weights."""
        args = dict(args)
        gates = self._gate_names
        if not gates:
            return args
        for group in ("i2h", "h2h"):
            ws = [args.pop(f"{self._prefix}{group}{g}_weight") for g in gates]
            bs = [args.pop(f"{self._prefix}{group}{g}_bias") for g in gates]
            args[f"{self._prefix}{group}_weight"] = nd.concatenate(ws)
            args[f"{self._prefix}{group}_bias"] = nd.concatenate(bs)
        return args

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Emit a length-T static graph; returns (outputs, final_states).
        outputs is a stacked Symbol when merge_outputs=True, else a list."""
        self.reset()
        steps, _ = _as_steps(inputs, length, layout)
        states = begin_state if begin_state is not None else self.begin_state()
        outputs = []
        for x in steps:
            out, states = self(x, states)
            outputs.append(out)
        return _merge_steps(outputs, layout, merge_outputs), states

    def _activate(self, x, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(x, act_type=activation, **kwargs)
        return activation(x, **kwargs)

    def _gate_fc(self, inputs, state_h, n_units, name):
        """The shared i2h/h2h affine pair every gate cell starts from."""
        i2h = symbol.FullyConnected(
            data=inputs, weight=self._iW, bias=self._iB,
            num_hidden=n_units, name=f"{name}i2h")
        h2h = symbol.FullyConnected(
            data=state_h, weight=self._hW, bias=self._hB,
            num_hidden=n_units, name=f"{name}h2h")
        return i2h, h2h

    def _fetch_params(self, bias_init=None):
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        self._iB = self.params.get(
            "i2h_bias", **({"init": bias_init} if bias_init else {}))
        self._hB = self.params.get("h2h_bias")


class RNNCell(BaseRNNCell):
    """Elman cell: h' = act(W_x x + W_h h + b) (ref: rnn_cell.py:362)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._fetch_params()

    @property
    def state_info(self):
        return [{"shape": (1, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h, h2h = self._gate_fc(inputs, states[0], self._num_hidden, name)
        out = self._activate(i2h + h2h, self._activation, name=f"{name}out")
        return out, [out]


class LSTMCell(BaseRNNCell):
    """LSTM cell, gate order [i, f, g, o] matching the fused op
    (ref: rnn_cell.py:408; ops/nn.py _lstm_step)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._fetch_params(bias_init=init.LSTMBias(forget_bias=forget_bias))

    @property
    def state_info(self):
        return [{"shape": (1, self._num_hidden), "__layout__": "NC"},
                {"shape": (1, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h, h2h = self._gate_fc(inputs, states[0], 4 * self._num_hidden, name)
        i, f, g, o = symbol.SliceChannel(i2h + h2h, num_outputs=4,
                                         name=f"{name}slice")
        i = symbol.Activation(i, act_type="sigmoid", name=f"{name}i")
        f = symbol.Activation(f, act_type="sigmoid", name=f"{name}f")
        g = symbol.Activation(g, act_type="tanh", name=f"{name}c")
        o = symbol.Activation(o, act_type="sigmoid", name=f"{name}o")
        next_c = f * states[1] + i * g
        next_h = o * symbol.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """cuDNN-variant GRU (reset gate applied to the h2h product incl. its
    bias), matching the fused op (ref: rnn_cell.py:469; ops/nn.py
    _gru_step)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._fetch_params()

    @property
    def state_info(self):
        return [{"shape": (1, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        prev_h = states[0]
        i2h, h2h = self._gate_fc(inputs, prev_h, 3 * self._num_hidden, name)
        ir, iz, inew = symbol.SliceChannel(i2h, num_outputs=3,
                                           name=f"{name}i2h_slice")
        hr, hz, hnew = symbol.SliceChannel(h2h, num_outputs=3,
                                           name=f"{name}h2h_slice")
        r = symbol.Activation(ir + hr, act_type="sigmoid", name=f"{name}r")
        z = symbol.Activation(iz + hz, act_type="sigmoid", name=f"{name}z")
        cand = symbol.Activation(inew + r * hnew, act_type="tanh",
                                 name=f"{name}h")
        next_h = (1.0 - z) * cand + z * prev_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Whole-sequence cell over the fused `sym.RNN` op: one lax.scan
    program instead of T unrolled steps (ref: rnn_cell.py:536)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = f"{mode}_"
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        self._directions = ["l", "r"] if bidirectional else ["l"]
        self._parameter = self.params.get(
            "parameters", init=init.FusedRNN(
                None, num_hidden, num_layers, mode, bidirectional,
                forget_bias))

    @property
    def state_info(self):
        b = (2 if self._bidirectional else 1) * self._num_layers
        n = 2 if self._mode == "lstm" else 1
        return [{"shape": (b, 1, self._num_hidden), "__layout__": "LNC"}
                for _ in range(n)]

    @property
    def _gate_names(self):
        return {"rnn_relu": ("",), "rnn_tanh": ("",),
                "lstm": ("_i", "_f", "_c", "_o"),
                "gru": ("_r", "_z", "_o")}[self._mode]

    def _per_matrix_names(self, num_input):
        """Packed-layout walk: yields (name, shape) in the exact order
        ops/nn.py _rnn_slice_params consumes the vector (weights for every
        (layer, direction), then all biases)."""
        H, D = self._num_hidden, len(self._directions)
        gates = self._gate_names
        for layer in range(self._num_layers):
            inp = num_input if layer == 0 else H * D
            for direction in self._directions:
                for gate in gates:
                    yield (f"{self._prefix}{direction}{layer}_i2h{gate}_weight",
                           (H, inp))
                for gate in gates:
                    yield (f"{self._prefix}{direction}{layer}_h2h{gate}_weight",
                           (H, H))
        for layer in range(self._num_layers):
            for direction in self._directions:
                for gate in gates:
                    yield (f"{self._prefix}{direction}{layer}_i2h{gate}_bias",
                           (H,))
                for gate in gates:
                    yield (f"{self._prefix}{direction}{layer}_h2h{gate}_bias",
                           (H,))

    def _infer_num_input(self, total):
        """Invert rnn_param_size for the layer-0 input width."""
        H, D = self._num_hidden, len(self._directions)
        G = _GATES[self._mode]
        rest = rnn_param_size(self._num_layers, 0, H,
                              self._bidirectional, self._mode)
        return (total - rest) // (D * G * H)

    def unpack_weights(self, args):
        args = dict(args)
        arr = args.pop(self._parameter.name)
        flat = arr.asnumpy().reshape(-1)
        num_input = self._infer_num_input(flat.size)
        offset = 0
        for name, shape in self._per_matrix_names(num_input):
            n = 1
            for s in shape:
                n *= s
            args[name] = nd.array(flat[offset:offset + n].reshape(shape))
            offset += n
        assert offset == flat.size, "invalid parameter size for FusedRNNCell"
        return args

    def pack_weights(self, args):
        import numpy as np

        args = dict(args)
        first = f"{self._prefix}l0_i2h{self._gate_names[0]}_weight"
        num_input = args[first].shape[1]
        chunks = []
        for name, shape in self._per_matrix_names(num_input):
            chunks.append(args.pop(name).asnumpy().reshape(-1))
        args[self._parameter.name] = nd.array(np.concatenate(chunks))
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "FusedRNNCell cannot be stepped; use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        # the fused op wants one (T, N, C) tensor
        t_axis = layout.find("T")
        if not isinstance(inputs, symbol.Symbol):
            steps = [symbol.expand_dims(x, axis=0) for x in inputs]
            data = symbol.Concat(*steps, dim=0)
        elif t_axis == 1:
            data = symbol.swapaxes(inputs, dim1=0, dim2=1)
        else:
            data = inputs
        if begin_state is None:
            begin_state = self.begin_state()
        state_kw = {"state": begin_state[0]}
        if self._mode == "lstm":
            state_kw["state_cell"] = begin_state[1]
        out = symbol.RNN(
            data=data, parameters=self._parameter,
            state_size=self._num_hidden, num_layers=self._num_layers,
            bidirectional=self._bidirectional, p=self._dropout,
            state_outputs=self._get_next_state, mode=self._mode,
            name=self._prefix + "rnn", **state_kw)
        if not self._get_next_state:
            outputs, states = out, []
        elif self._mode == "lstm":
            outputs, states = out[0], [out[1], out[2]]
        else:
            outputs, states = out[0], [out[1]]
        if t_axis == 1:
            outputs = symbol.swapaxes(outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            outputs = list(symbol.split(outputs, axis=t_axis,
                                        num_outputs=length, squeeze_axis=1))
        return outputs, states

    def unfuse(self):
        """Equivalent SequentialRNNCell of step-able cells (ref:
        rnn_cell.py unfuse); weight names line up with unpack_weights."""
        make = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden, activation="relu",
                                          prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden, activation="tanh",
                                          prefix=p),
            "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p,
                                       forget_bias=self._forget_bias),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }[self._mode]
        stack = SequentialRNNCell()
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    make(f"{self._prefix}l{i}_"),
                    make(f"{self._prefix}r{i}_"),
                    output_prefix=f"{self._prefix}bi_l{i}_"))
            else:
                stack.add(make(f"{self._prefix}l{i}_"))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix=f"{self._prefix}_dropout{i}_"))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Vertical stack: each cell's output feeds the next (ref:
    rnn_cell.py:748)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            assert cell._own_params, (
                "Either specify params for SequentialRNNCell or child "
                "cells, not both.")
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return sum((c.state_info for c in self._cells), [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum((c.begin_state(**kwargs) for c in self._cells), [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states, p = [], 0
        for cell in self._cells:
            assert not isinstance(cell, BidirectionalCell), \
                "BidirectionalCell cannot be stepped inside a stack"
            n = len(cell.state_info)
            inputs, st = cell(inputs, states[p:p + n])
            p += n
            next_states.extend(st)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        if begin_state is None:
            begin_state = self.begin_state()
        next_states, p = [], 0
        last = len(self._cells) - 1
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            inputs, st = cell.unroll(
                length, inputs=inputs, begin_state=begin_state[p:p + n],
                layout=layout,
                merge_outputs=merge_outputs if i == last else None)
            p += n
            next_states.extend(st)
        return inputs, next_states


class DropoutCell(BaseRNNCell):
    """Stateless dropout stage for stacks (ref: rnn_cell.py:827)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix, params)
        self.dropout = float(dropout)

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        if isinstance(inputs, symbol.Symbol) and merge_outputs is not False:
            # whole-sequence tensor: one dropout over all steps
            return self(inputs, [])
        return super().unroll(length, inputs, begin_state=begin_state,
                              layout=layout, merge_outputs=merge_outputs)


class ModifierCell(BaseRNNCell):
    """Wraps a base cell and alters its behavior; parameters stay with the
    base cell (ref: rnn_cell.py:867)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=symbol.zeros, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func, **kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)

    def __call__(self, inputs, states):
        raise NotImplementedError()


class ZoneoutCell(ModifierCell):
    """Zoneout: randomly keep previous outputs/states (ref:
    rnn_cell.py:909)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, FusedRNNCell), (
            "FusedRNNCell does not support zoneout; unfuse() first")
        assert not isinstance(base_cell, BidirectionalCell), (
            "Apply ZoneoutCell to the cells underneath the "
            "BidirectionalCell instead")
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell = self.base_cell
        p_out, p_st = self.zoneout_outputs, self.zoneout_states
        next_out, next_states = cell(inputs, states)

        def keep_mask(p, like):
            return symbol.Dropout(symbol.ones_like(like), p=p)

        prev = self.prev_output
        if prev is None:
            prev = symbol.zeros_like(next_out)
        out = (symbol.where(keep_mask(p_out, next_out), next_out, prev)
               if p_out != 0.0 else next_out)
        new_states = (
            [symbol.where(keep_mask(p_st, ns), ns, os)
             for ns, os in zip(next_states, states)]
            if p_st != 0.0 else next_states)
        self.prev_output = out
        return out, new_states


class ResidualCell(ModifierCell):
    """output = base(output) + input (ref: rnn_cell.py:957)."""

    def __call__(self, inputs, states):
        out, states = self.base_cell(inputs, states)
        return out + inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs)
        self.base_cell._modified = True
        if isinstance(outputs, symbol.Symbol):
            if not isinstance(inputs, symbol.Symbol):
                inputs = _merge_steps(list(inputs), layout, True)
            return outputs + inputs, states
        steps, _ = _as_steps(inputs, length, layout)
        return [o + x for o, x in zip(outputs, steps)], states


class BidirectionalCell(BaseRNNCell):
    """Runs one cell forward and one on the reversed sequence; outputs are
    concatenated per step (ref: rnn_cell.py:998)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params=params)
        self._output_prefix = output_prefix
        self._override_cell_params = params is not None
        if self._override_cell_params:
            assert l_cell._own_params and r_cell._own_params, (
                "Either specify params for BidirectionalCell or child "
                "cells, not both.")
            l_cell.params._params.update(self.params._params)
            r_cell.params._params.update(self.params._params)
        self.params._params.update(l_cell.params._params)
        self.params._params.update(r_cell.params._params)
        self._cells = [l_cell, r_cell]

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "BidirectionalCell cannot be stepped; use unroll")

    @property
    def state_info(self):
        return sum((c.state_info for c in self._cells), [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum((c.begin_state(**kwargs) for c in self._cells), [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        steps, _ = _as_steps(inputs, length, layout)
        if begin_state is None:
            begin_state = self.begin_state()
        l_cell, r_cell = self._cells
        n_l = len(l_cell.state_info)
        l_out, l_states = l_cell.unroll(
            length, inputs=steps, begin_state=begin_state[:n_l],
            layout=layout, merge_outputs=False)
        r_out, r_states = r_cell.unroll(
            length, inputs=list(reversed(steps)),
            begin_state=begin_state[n_l:], layout=layout,
            merge_outputs=False)
        outputs = [
            symbol.Concat(lo, ro, dim=1,
                          name=f"{self._output_prefix}t{i}")
            for i, (lo, ro) in enumerate(zip(l_out, reversed(r_out)))]
        return (_merge_steps(outputs, layout, merge_outputs),
                l_states + r_states)
