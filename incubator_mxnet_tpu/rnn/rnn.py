"""RNN checkpoint helpers (ref: python/mxnet/rnn/rnn.py — unpack fused
weights before saving so checkpoints are portable across fused/unfused
cells, pack after loading)."""
from __future__ import annotations

from ..model import load_checkpoint, save_checkpoint
from .rnn_cell import BaseRNNCell

__all__ = ["save_rnn_checkpoint", "load_rnn_checkpoint", "do_rnn_checkpoint"]


def _as_cells(cells):
    return [cells] if isinstance(cells, BaseRNNCell) else list(cells)


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """save_checkpoint with each cell's packed weights unpacked to
    per-gate arrays first."""
    for cell in _as_cells(cells):
        arg_params = cell.unpack_weights(arg_params)
    save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """load_checkpoint + re-pack per-gate arrays for the given cells."""
    sym, arg, aux = load_checkpoint(prefix, epoch)
    for cell in _as_cells(cells):
        arg = cell.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback checkpointing with unpacked weights."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)

    return _callback
