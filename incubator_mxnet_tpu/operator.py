"""Custom operators defined in Python.

TPU-native equivalent of the reference's custom-op bridge (ref:
src/operator/custom/custom-inl.h:95, python/mxnet/operator.py CustomOp/
CustomOpProp). The reference runs Python ops on a dedicated thread pool so
the engine never blocks on the GIL; here the host callback mechanism is
`jax.pure_callback` — XLA suspends the device computation, runs the Python
body on the host, and resumes, which composes with jit/grad via
jax.custom_vjp.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from . import autograd
from .ndarray.ndarray import NDArray
from .ops.registry import register as _register_op

__all__ = ["CustomOp", "CustomOpProp", "register", "get_custom_op"]

_CUSTOM_REGISTRY = {}


class CustomOp:
    """Base class for custom operator bodies (ref: operator.py CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        if req in ("write", "inplace", None):
            dst._data = src._data if isinstance(src, NDArray) else jnp.asarray(src)
        elif req == "add":
            dst._data = dst._data + (src._data if isinstance(src, NDArray) else jnp.asarray(src))


class CustomOpProp:
    """Operator metadata (ref: operator.py CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError


def register(reg_name):
    """Register a CustomOpProp subclass under a name
    (ref: mx.operator.register -> MXCustomOpRegister)."""

    def deco(prop_cls):
        _CUSTOM_REGISTRY[reg_name] = prop_cls
        _register_custom_as_op(reg_name, prop_cls)
        return prop_cls

    return deco


def get_custom_op(name):
    return _CUSTOM_REGISTRY[name]


def _register_custom_as_op(reg_name, prop_cls):
    """Surface the custom op as nd.Custom-style callable: runs the Python
    forward/backward through pure_callback with a custom_vjp."""

    def call(*inputs, **kwargs):
        prop = prop_cls(**kwargs)
        arg_names = prop.list_arguments()
        n_out = len(prop.list_outputs())
        in_arrays = [a if isinstance(a, NDArray) else NDArray(jnp.asarray(a)) for a in inputs]
        in_shapes = [a.shape for a in in_arrays]
        _, out_shapes, _ = prop.infer_shape(list(in_shapes))
        in_dtypes = [a.dtype for a in in_arrays]
        op = prop.create_operator(None, in_shapes, in_dtypes)
        out_avals = [
            jax.ShapeDtypeStruct(tuple(s), np.float32) for s in out_shapes
        ]

        def host_forward(*datas):
            ins = [NDArray(np.asarray(d)) for d in datas]
            outs = [NDArray(np.zeros(s, np.float32)) for s in out_shapes]
            op.forward(True, ["write"] * n_out, ins, outs, [])
            return tuple(np.asarray(o.asnumpy()) for o in outs)

        def host_backward(*datas):
            k = len(in_arrays)
            ins = [NDArray(np.asarray(d)) for d in datas[:k]]
            outs = [NDArray(np.asarray(d)) for d in datas[k : k + n_out]]
            ograds = [NDArray(np.asarray(d)) for d in datas[k + n_out :]]
            igrads = [NDArray(np.zeros(s.shape, np.float32)) for s in ins]
            op.backward(["write"] * k, ograds, ins, outs, igrads, [])
            return tuple(np.asarray(g.asnumpy()) for g in igrads)

        @jax.custom_vjp
        def fwd(*datas):
            return jax.pure_callback(host_forward, tuple(out_avals), *datas)

        def fwd_fwd(*datas):
            outs = jax.pure_callback(host_forward, tuple(out_avals), *datas)
            return outs, (datas, outs)

        def fwd_bwd(res, gs):
            datas, outs = res
            in_avals = tuple(jax.ShapeDtypeStruct(d.shape, d.dtype) for d in datas)
            grads = jax.pure_callback(host_backward, in_avals, *(datas + outs + tuple(gs)))
            return grads

        fwd.defvjp(fwd_fwd, fwd_bwd)

        results = autograd.invoke_recorded(
            lambda *ds: fwd(*ds), in_arrays, name=f"custom:{reg_name}"
        )
        return results if len(results) > 1 else results[0]

    from . import ndarray as nd_mod

    setattr(nd_mod, f"Custom_{reg_name}", call)
    _CUSTOM_CALLS[reg_name] = call
    return call


_CUSTOM_CALLS: dict = {}


def Custom(*inputs, op_type=None, **kwargs):
    """MXNet-parity dispatcher: nd.Custom(data, ..., op_type='my_op')
    (ref: the Custom op in src/operator/custom/custom.cc — scripts select
    the registered prop by the op_type attr)."""
    if op_type is None:
        raise TypeError("nd.Custom requires op_type=<registered name>")
    if op_type not in _CUSTOM_CALLS:
        raise KeyError(
            f"no custom op '{op_type}' registered "
            f"(have: {sorted(_CUSTOM_CALLS)})")
    return _CUSTOM_CALLS[op_type](*inputs, **kwargs)
