"""Profiler (ref: src/profiler/profiler.h, python/mxnet/profiler.py).

Keeps the reference's UX — set_config / set_state('run'|'stop') / dump — on
top of jax.profiler, which emits XPlane/Perfetto traces viewable in
TensorBoard or chrome://tracing (matching the reference's chrome-trace dump
ref: profiler.h:87-90). Op-level annotations use TraceAnnotation, the analog
of the engine's named-opr profiling spans.
"""
from __future__ import annotations

import os

import jax
import jax.profiler

__all__ = [
    "set_config", "set_state", "dump", "pause", "resume", "Task", "Frame",
    "Event", "Counter", "Marker", "scope",
]

_CONFIG = {"filename": "profile.json", "profile_all": False}
_STATE = {"running": False, "dir": None}


def set_config(**kwargs):
    """(ref: profiler.py set_config) — accepts the reference's kwargs;
    `filename` determines the trace directory."""
    _CONFIG.update(kwargs)


def set_state(state="stop", profile_process="worker"):
    if state == "run" and not _STATE["running"]:
        trace_dir = os.path.splitext(_CONFIG.get("filename", "profile.json"))[0] + "_trace"
        os.makedirs(trace_dir, exist_ok=True)
        jax.profiler.start_trace(trace_dir)
        _STATE.update(running=True, dir=trace_dir)
    elif state == "stop" and _STATE["running"]:
        jax.profiler.stop_trace()
        _STATE["running"] = False


def dump(finished=True, profile_process="worker"):
    if _STATE["running"]:
        set_state("stop")
    return _STATE["dir"]


def pause(profile_process="worker"):
    pass


def resume(profile_process="worker"):
    pass


def scope(name):
    """Annotation context (ref: ProfileTask) — shows up in the trace."""
    return jax.profiler.TraceAnnotation(name)


class _Annotated:
    def __init__(self, name, *a, **kw):
        self.name = name
        self._ctx = None

    def start(self):
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()

    def stop(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


class Task(_Annotated):
    """(ref: profiler.h ProfileTask:761)"""


class Frame(_Annotated):
    """(ref: profiler.h ProfileFrame:911)"""


class Event(_Annotated):
    """(ref: profiler.h ProfileEvent:837)"""


class Counter:
    """(ref: profiler.h ProfileCounter:556) — host-side counter recorded into
    logs (XPlane has no free counters)."""

    def __init__(self, domain, name, value=0):
        self.name = name
        self.value = value

    def set_value(self, value):
        self.value = value

    def increment(self, delta=1):
        self.value += delta

    def decrement(self, delta=1):
        self.value -= delta


class Marker:
    def __init__(self, domain, name):
        self.name = name

    def mark(self, scope="process"):
        pass
