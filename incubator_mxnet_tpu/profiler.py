"""Profiler (ref: src/profiler/profiler.h, python/mxnet/profiler.py).

Keeps the reference's UX — set_config / set_state('run'|'stop') / dump — on
top of jax.profiler, which emits XPlane/Perfetto traces viewable in
TensorBoard or chrome://tracing (matching the reference's chrome-trace dump
ref: profiler.h:87-90). Op-level annotations use TraceAnnotation, the analog
of the engine's named-opr profiling spans.
"""
from __future__ import annotations

import os

import jax
import jax.profiler

__all__ = [
    "set_config", "set_state", "dump", "dumps", "pause", "resume", "Task",
    "Frame", "Event", "Counter", "Marker", "Domain", "scope",
    "aggregate_enabled",
    "timed_invoke", "record_duration", "reset_stats", "memory_analysis",
    "record_memory", "dumps_memory",
]

_CONFIG = {"filename": "profile.json", "profile_all": False,
           "aggregate_stats": False}
_STATE = {"running": False, "dir": None}


def set_config(**kwargs):
    """(ref: profiler.py set_config) — accepts the reference's kwargs;
    `filename` determines the trace directory. `aggregate_stats=True`
    additionally records a per-op aggregate table (`dumps()`); it
    synchronizes after every eager op to attribute real device time, the
    same observability/throughput trade the reference's profiler makes when
    instrumenting each engine opr."""
    _CONFIG.update(kwargs)


# ---------------------------------------------------------------------------
# per-op aggregate statistics (ref: src/profiler/aggregate_stats.cc —
# the MXAggregateProfileStatsPrint table, the part users actually read)
# ---------------------------------------------------------------------------


class _OpStat:
    __slots__ = ("count", "total", "min", "max")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0

    def add(self, dur):
        self.count += 1
        self.total += dur
        self.min = min(self.min, dur)
        self.max = max(self.max, dur)


_AGG_STATS: dict[str, _OpStat] = {}


def aggregate_enabled():
    return _STATE["running"] and _CONFIG.get("aggregate_stats", False)


def timed_invoke(op_name, call, *args, **kwargs):
    """Run `call`, blocking on its outputs, and charge the wall time to
    `op_name` in the aggregate table."""
    import time as _time

    t0 = _time.perf_counter()
    results = call(*args, **kwargs)
    try:
        sync = results if isinstance(results, (list, tuple)) else [results]
        for r in sync:
            data = getattr(r, "_data", r)
            if hasattr(data, "block_until_ready"):
                data.block_until_ready()
    except Exception:
        pass  # timing must never break the op itself
    record_duration(op_name, _time.perf_counter() - t0)
    return results


def record_duration(op_name, dur):
    """Charge `dur` seconds to `op_name` in the aggregate table. Also the
    sink telemetry spans feed when aggregate stats are on — one table, not
    two (see telemetry/spans.py)."""
    _AGG_STATS.setdefault(op_name, _OpStat()).add(dur)


def reset_stats():
    _AGG_STATS.clear()
    _MEM_STATS.clear()


def dumps(reset=False, sort_by="total", ascending=False):
    """Formatted per-op aggregate table
    (ref: profiler.py dumps -> MXAggregateProfileStatsPrint).

    Columns: Name, Total Count, Time total/min/max/avg in ms.
    """
    key = {
        "total": lambda kv: kv[1].total,
        "count": lambda kv: kv[1].count,
        "min": lambda kv: kv[1].min,
        "max": lambda kv: kv[1].max,
        "avg": lambda kv: kv[1].total / max(kv[1].count, 1),
    }.get(sort_by)
    if key is None:
        raise ValueError(f"sort_by must be total/count/min/max/avg, got {sort_by}")
    rows = sorted(_AGG_STATS.items(), key=key, reverse=not ascending)
    lines = [
        "Profile Statistics:",
        f"{'Name':<40s} {'Count':>8s} {'Total(ms)':>12s} {'Min(ms)':>10s} "
        f"{'Max(ms)':>10s} {'Avg(ms)':>10s}",
        "-" * 94,
    ]
    if not rows:
        lines.append("(no ops recorded)")
    for name, s in rows:
        avg = s.total / max(s.count, 1)
        mn = 0.0 if s.count == 0 else s.min  # never render the inf sentinel
        lines.append(
            f"{name[:40]:<40s} {s.count:>8d} {s.total * 1e3:>12.3f} "
            f"{mn * 1e3:>10.3f} {s.max * 1e3:>10.3f} {avg * 1e3:>10.3f}")
    if reset:
        reset_stats()
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# compiled-program memory statistics (ref: src/profiler/storage_profiler.h —
# the reference tracked per-device allocations through its pooled allocator;
# under XLA the ground truth is the compiler's own memory analysis of each
# executable: argument/output/temp/alias bytes, known exactly at compile
# time rather than sampled at runtime)
# ---------------------------------------------------------------------------

_MEM_STATS: dict[str, dict] = {}


def record_memory(name, compiled):
    """Record a compiled executable's memory breakdown under `name`.
    `compiled` is a jax.stages.Compiled (jit(f).lower(...).compile())."""
    try:
        m = compiled.memory_analysis()
    except Exception:
        return None  # backend without memory analysis: not recordable
    if m is None:
        return None
    stats = {
        "argument_bytes": int(getattr(m, "argument_size_in_bytes", 0)),
        "output_bytes": int(getattr(m, "output_size_in_bytes", 0)),
        "temp_bytes": int(getattr(m, "temp_size_in_bytes", 0)),
        "alias_bytes": int(getattr(m, "alias_size_in_bytes", 0)),
        "code_bytes": int(getattr(m, "generated_code_size_in_bytes", 0)),
    }
    # peak device footprint while the program runs: live args + outputs +
    # XLA temp arena (aliased/donated bytes are counted once, in args)
    stats["peak_bytes"] = (stats["argument_bytes"] + stats["output_bytes"]
                           + stats["temp_bytes"] - stats["alias_bytes"])
    _MEM_STATS[name] = stats
    return stats


def memory_analysis(fn, *args, name=None, static_argnums=None):
    """Compile `fn` for `args` (cached by jax) and record/return its device
    memory breakdown — the per-program HBM answer to the reference's
    storage profiler."""
    jitted = fn if hasattr(fn, "lower") else jax.jit(
        fn, static_argnums=static_argnums or ())
    compiled = jitted.lower(*args).compile()
    return record_memory(name or getattr(fn, "__name__", "program"),
                         compiled)


def dumps_memory():
    """Formatted per-program memory table (storage_profiler.h analog)."""
    lines = [
        "Memory Statistics (per compiled program):",
        f"{'Name':<32s} {'Peak(MiB)':>10s} {'Args(MiB)':>10s} "
        f"{'Out(MiB)':>9s} {'Temp(MiB)':>10s} {'Alias(MiB)':>10s}",
        "-" * 85,
    ]
    mib = 1024.0 * 1024.0
    for name, s in sorted(_MEM_STATS.items(),
                          key=lambda kv: -kv[1]["peak_bytes"]):
        lines.append(
            f"{name[:32]:<32s} {s['peak_bytes'] / mib:>10.2f} "
            f"{s['argument_bytes'] / mib:>10.2f} "
            f"{s['output_bytes'] / mib:>9.2f} "
            f"{s['temp_bytes'] / mib:>10.2f} "
            f"{s['alias_bytes'] / mib:>10.2f}")
    return "\n".join(lines)


def set_state(state="stop", profile_process="worker"):
    if state == "run" and not _STATE["running"]:
        trace_dir = os.path.splitext(_CONFIG.get("filename", "profile.json"))[0] + "_trace"
        os.makedirs(trace_dir, exist_ok=True)
        jax.profiler.start_trace(trace_dir)
        _STATE.update(running=True, dir=trace_dir)
    elif state == "stop" and _STATE["running"]:
        jax.profiler.stop_trace()
        _STATE["running"] = False


def dump(finished=True, profile_process="worker"):
    if _STATE["running"]:
        set_state("stop")
    return _STATE["dir"]


def pause(profile_process="worker"):
    pass


def resume(profile_process="worker"):
    pass


def scope(name):
    """Annotation context (ref: ProfileTask) — shows up in the trace."""
    return jax.profiler.TraceAnnotation(name)


class _Annotated:
    def __init__(self, name, *a, **kw):
        # reference signature is Task/Frame(domain, name) but Event(name);
        # accept both orders (ref: python/mxnet/profiler.py Task.__init__)
        self.domain = None
        if isinstance(name, Domain):
            self.domain = name
            if a:
                name = a[0]
            elif "name" in kw:
                name = kw["name"]
            else:
                raise TypeError(
                    f"{type(self).__name__}(domain, name): name is required")
        self.name = name
        self._ctx = None

    def start(self):
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()

    def stop(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


class Task(_Annotated):
    """(ref: profiler.h ProfileTask:761)"""


class Frame(_Annotated):
    """(ref: profiler.h ProfileFrame:911)"""


class Event(_Annotated):
    """(ref: profiler.h ProfileEvent:837)"""


class Domain:
    """Instrumentation namespace grouping Tasks/Counters/Markers
    (ref: python/mxnet/profiler.py Domain -> MXProfileCreateDomain)."""

    def __init__(self, name):
        self.name = name

    def new_counter(self, name, value=None):
        return Counter(self, name, value or 0)

    def new_task(self, name):
        return Task(self, name)

    def new_marker(self, name):
        return Marker(self, name)

    def __repr__(self):
        return f"Domain(name={self.name})"


class Counter:
    """(ref: profiler.h ProfileCounter:556) — host-side counter recorded into
    logs (XPlane has no free counters)."""

    def __init__(self, domain, name, value=0):
        self.domain = domain
        self.name = name
        self.value = value

    def set_value(self, value):
        self.value = value

    def increment(self, delta=1):
        self.value += delta

    def decrement(self, delta=1):
        self.value -= delta

    def __iadd__(self, delta):
        self.increment(delta)
        return self

    def __isub__(self, delta):
        self.decrement(delta)
        return self


class Marker:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope="process"):
        pass
