# R frontend over the imperative C ABI (reference role: R-package/R/ —
# mx.nd.* array ops and autograd for R users).
#
# Ops run through the embedded-interpreter runtime on real XLA devices.
# Example:
#   mx.init()
#   x <- mx.nd.array(matrix(rnorm(12), 3, 4))
#   y <- mx.op.invoke("relu", list(x))[[1]]
#   mx.nd.to.array(y)

mx.init <- function() {
  invisible(.Call(mxr_init))
}

#' Create a float32 NDArray from an R array/matrix/vector (column-major R
#' data is transposed to the row-major layout the runtime uses).
mx.nd.array <- function(data) {
  d <- dim(data)
  if (is.null(d)) d <- length(data)
  # R is column-major; aperm to serve C-order
  if (length(d) > 1) data <- aperm(data, rev(seq_along(d)))
  .Call(mxr_nd_create, as.double(data), as.integer(d))
}

mx.nd.shape <- function(nd) {
  .Call(mxr_nd_shape, nd)
}

#' Copy an NDArray back into an R array (restoring column-major layout).
mx.nd.to.array <- function(nd) {
  shape <- .Call(mxr_nd_shape, nd)
  v <- .Call(mxr_nd_to_vec, nd)
  if (length(shape) <= 1) return(v)
  a <- array(v, dim = rev(shape))
  aperm(a, rev(seq_along(shape)))
}

#' Invoke any registered op: mx.op.invoke("FullyConnected", list(x, w, b),
#' attrs = '{"num_hidden": 128}'). Returns a list of NDArrays.
mx.op.invoke <- function(name, inputs, attrs = NULL) {
  .Call(mxr_invoke, name, inputs, attrs)
}

mx.autograd.record <- function(train_mode = TRUE) {
  invisible(.Call(mxr_record_begin, as.integer(train_mode)))
}

mx.autograd.end <- function() {
  invisible(.Call(mxr_record_end))
}

mx.attach.grad <- function(nd) {
  invisible(.Call(mxr_attach_grad, nd))
}

mx.backward <- function(loss) {
  invisible(.Call(mxr_backward, loss))
}

mx.grad <- function(nd) {
  .Call(mxr_grad, nd)
}

#' Serialize a named list of op attributes to the JSON object the runtime
#' expects (capi_imperative.py invoke(): nulls dropped, arrays -> tuples).
#' Whole numbers are emitted without a decimal point so integer-typed op
#' attrs (num_hidden, axis, ...) arrive as ints after json decoding.
#' `arrays` names tuple-typed attrs (registry default is a tuple): those
#' are ALWAYS encoded as JSON arrays, because R cannot distinguish the
#' scalar 1 from the length-1 vector c(1) and ops like slice do
#' len(begin)/begin[i] on them.
mx.attrs.json <- function(attrs, arrays = character(0)) {
  keep <- attrs[!vapply(attrs, is.null, logical(1))]
  if (length(keep) == 0L) return(NULL)
  enc1 <- function(v) {
    if (is.logical(v)) return(if (v) "true" else "false")
    if (is.character(v)) {
      v <- gsub("\\\\", "\\\\\\\\", v)
      return(paste0('"', gsub('"', '\\\\"', v), '"'))
    }
    if (is.numeric(v)) {
      if (!is.finite(v)) return(if (v > 0) "1e308" else "-1e308")
      if (v == floor(v) && abs(v) < 9e15) return(sprintf("%.0f", v))
      return(format(v, digits = 17, scientific = FALSE))
    }
    stop("unsupported attr type: ", class(v))
  }
  enc <- function(v, force_array = FALSE) {
    if (force_array || length(v) > 1L)
      return(paste0("[", paste(vapply(v, enc1, character(1)),
                               collapse = ","), "]"))
    enc1(v)
  }
  parts <- vapply(names(keep), function(k) {
    enc(keep[[k]], force_array = k %in% arrays)
  }, character(1))
  paste0("{", paste(sprintf('"%s":%s', names(keep), parts),
                    collapse = ","), "}")
}

# --- graph-level executor (reference role: R-package's mx.simple.bind /
# executor path; the whole symbol JSON binds to ONE jitted XLA program
# per forward — the same natives as the C++/JVM/Perl executors) ----------

#' Bind a serialized symbol (the Python frontend's Symbol.tojson schema)
#' over a NAMED list of NDArrays; grad_names selects the arguments that
#' accumulate gradients during mx.exec.backward.
mx.symbol.bind.compiled <- function(symbol_json, args,
                                    grad_names = character(0)) {
  stopifnot(!is.null(names(args)), all(nzchar(names(args))))
  .Call(mxr_sym_bind, symbol_json, names(args), unname(args),
        as.character(grad_names))
}

#' Feed new data into a bound argument (dtype-preserving).
mx.exec.set.arg <- function(exec, name, nd) {
  invisible(.Call(mxr_exec_set_arg, exec, name, nd))
}

#' Run the compiled graph; returns a list of output NDArrays.
mx.exec.forward <- function(exec, is.train = FALSE) {
  .Call(mxr_exec_forward, exec, as.integer(is.train))
}

#' Ones-seeded backward into the executor's gradient arrays.
mx.exec.backward <- function(exec) {
  invisible(.Call(mxr_exec_backward, exec))
}

#' Gradient of a grad_names argument from the last backward.
mx.exec.grad <- function(exec, name) {
  .Call(mxr_exec_grad, exec, name)
}
