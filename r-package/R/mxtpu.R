# R frontend over the imperative C ABI (reference role: R-package/R/ —
# mx.nd.* array ops and autograd for R users).
#
# Ops run through the embedded-interpreter runtime on real XLA devices.
# Example:
#   mx.init()
#   x <- mx.nd.array(matrix(rnorm(12), 3, 4))
#   y <- mx.op.invoke("relu", list(x))[[1]]
#   mx.nd.to.array(y)

mx.init <- function() {
  invisible(.Call(mxr_init))
}

#' Create a float32 NDArray from an R array/matrix/vector (column-major R
#' data is transposed to the row-major layout the runtime uses).
mx.nd.array <- function(data) {
  d <- dim(data)
  if (is.null(d)) d <- length(data)
  # R is column-major; aperm to serve C-order
  if (length(d) > 1) data <- aperm(data, rev(seq_along(d)))
  .Call(mxr_nd_create, as.double(data), as.integer(d))
}

mx.nd.shape <- function(nd) {
  .Call(mxr_nd_shape, nd)
}

#' Copy an NDArray back into an R array (restoring column-major layout).
mx.nd.to.array <- function(nd) {
  shape <- .Call(mxr_nd_shape, nd)
  v <- .Call(mxr_nd_to_vec, nd)
  if (length(shape) <= 1) return(v)
  a <- array(v, dim = rev(shape))
  aperm(a, rev(seq_along(shape)))
}

#' Invoke any registered op: mx.op.invoke("FullyConnected", list(x, w, b),
#' attrs = '{"num_hidden": 128}'). Returns a list of NDArrays.
mx.op.invoke <- function(name, inputs, attrs = NULL) {
  .Call(mxr_invoke, name, inputs, attrs)
}

mx.autograd.record <- function(train_mode = TRUE) {
  invisible(.Call(mxr_record_begin, as.integer(train_mode)))
}

mx.autograd.end <- function() {
  invisible(.Call(mxr_record_end))
}

mx.attach.grad <- function(nd) {
  invisible(.Call(mxr_attach_grad, nd))
}

mx.backward <- function(loss) {
  invisible(.Call(mxr_backward, loss))
}

mx.grad <- function(nd) {
  .Call(mxr_grad, nd)
}
