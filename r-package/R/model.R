# R training frontend (reference role: R-package/R/model.R
# mx.model.FeedForward.create / predict, and R-package/R/symbol.R).
#
# Design: a "symbol" is a lightweight chain description (R lists tagged
# with class "mx.symbol") built by mx.symbol.* constructors. Training is
# imperative underneath — each batch runs forward through the generated
# mx.nd.* ops under autograd, backward through the embedded runtime, and
# updates via the framework's own fused optimizer ops (sgd_update /
# sgd_mom_update), so the R loop stays thin while all math runs on XLA
# devices. The reference instead binds a symbolic executor per batch
# shape; the imperative form is the TPU-native equivalent of the same
# user contract: symbol in, trained model out.

mx.symbol.Variable <- function(name = "data") {
  structure(list(op = "var", name = name), class = "mx.symbol")
}

mx.symbol.FullyConnected <- function(data, num_hidden, name = NULL,
                                     no_bias = FALSE) {
  structure(list(op = "fc", input = data, num_hidden = num_hidden,
                 name = name, no_bias = no_bias), class = "mx.symbol")
}

mx.symbol.Activation <- function(data, act_type = "relu", name = NULL) {
  structure(list(op = "act", input = data, act_type = act_type, name = name),
            class = "mx.symbol")
}

#' 2-D convolution layer. Data flows NCHW; `kernel`/`stride`/`pad` are
#' length-2 vectors (the reference mx.symbol.Convolution contract).
mx.symbol.Convolution <- function(data, kernel, num_filter,
                                  stride = c(1, 1), pad = c(0, 0),
                                  name = NULL) {
  structure(list(op = "conv", input = data, kernel = kernel,
                 num_filter = num_filter, stride = stride, pad = pad,
                 name = name), class = "mx.symbol")
}

mx.symbol.Pooling <- function(data, kernel, pool_type = "max",
                              stride = kernel, pad = c(0, 0), name = NULL) {
  structure(list(op = "pool", input = data, kernel = kernel,
                 pool_type = pool_type, stride = stride, pad = pad,
                 name = name), class = "mx.symbol")
}

mx.symbol.Flatten <- function(data, name = NULL) {
  structure(list(op = "flatten", input = data, name = name),
            class = "mx.symbol")
}

#' Output head: trains with softmax cross-entropy, predicts probabilities
#' (the reference SoftmaxOutput contract).
mx.symbol.SoftmaxOutput <- function(data, name = "softmax") {
  structure(list(op = "softmax_output", input = data, name = name),
            class = "mx.symbol")
}

#' Linear regression head: trains with squared error (reference
#' LinearRegressionOutput contract), predicts the raw output.
mx.symbol.LinearRegressionOutput <- function(data, name = "linreg") {
  structure(list(op = "linreg_output", input = data, name = name),
            class = "mx.symbol")
}

is.mx.symbol <- function(x) inherits(x, "mx.symbol")

#' Walk the chain root -> input, assigning default layer names (fc1, fc2,
#' ... counted from the input side, matching user expectations).
mx.symbol.chain <- function(symbol) {
  chain <- list()
  node <- symbol
  while (!is.null(node)) {
    chain[[length(chain) + 1L]] <- node
    node <- node$input
  }
  chain <- rev(chain)  # input -> output order
  counts <- list()
  for (i in seq_along(chain)) {
    node <- chain[[i]]
    if (is.null(node$name) || !nzchar(node$name)) {
      k <- node$op
      counts[[k]] <- (if (is.null(counts[[k]])) 0L else counts[[k]]) + 1L
      chain[[i]]$name <- paste0(k, counts[[k]])
    }
  }
  chain
}

#' Parameter names the symbol requires (reference arguments(symbol) role),
#' in chain order.
mx.symbol.arguments <- function(symbol) {
  args <- character(0)
  for (node in mx.symbol.chain(symbol)) {
    if (node$op %in% c("fc", "conv")) {
      args <- c(args, paste0(node$name, "_weight"))
      if (!isTRUE(node$no_bias)) args <- c(args, paste0(node$name, "_bias"))
    }
  }
  args
}

#' Spatial output size of a conv/pool window along one axis.
.mx.out.dim <- function(n, k, s, p) (n + 2L * p - k) %/% s + 1L

#' Initialize parameters for a symbol given the per-sample input shape:
#' a scalar feature count for MLP chains, or c(C, H, W) for chains that
#' start with Convolution/Pooling (required there — conv weights need the
#' channel count). initializer: a function(shape) -> R array, or a
#' numeric scale for uniform(-scale, scale) (reference mx.init.uniform).
mx.model.init.params <- function(symbol, in_features, initializer = 0.07) {
  init_fn <- if (is.function(initializer)) {
    initializer
  } else {
    scale <- as.numeric(initializer)
    function(shape) array(stats::runif(prod(shape), -scale, scale),
                          dim = shape)
  }
  params <- list()
  # `shape` tracks per-sample dims: a scalar feature count after fc/
  # flatten, c(C, H, W) through conv/pool stages
  shape <- in_features
  for (node in mx.symbol.chain(symbol)) {
    if (node$op == "fc") {
      w <- init_fn(c(node$num_hidden, prod(shape)))
      params[[paste0(node$name, "_weight")]] <- mx.nd.array(w)
      if (!isTRUE(node$no_bias)) {
        params[[paste0(node$name, "_bias")]] <-
          mx.nd.array(array(0, dim = node$num_hidden))
      }
      shape <- node$num_hidden
    } else if (node$op == "conv") {
      stopifnot(length(shape) == 3L)
      w <- init_fn(c(node$num_filter, shape[1], node$kernel))
      params[[paste0(node$name, "_weight")]] <- mx.nd.array(w)
      params[[paste0(node$name, "_bias")]] <-
        mx.nd.array(array(0, dim = node$num_filter))
      shape <- c(node$num_filter,
                 .mx.out.dim(shape[2], node$kernel[1], node$stride[1],
                             node$pad[1]),
                 .mx.out.dim(shape[3], node$kernel[2], node$stride[2],
                             node$pad[2]))
    } else if (node$op == "pool") {
      stopifnot(length(shape) == 3L)
      shape <- c(shape[1],
                 .mx.out.dim(shape[2], node$kernel[1], node$stride[1],
                             node$pad[1]),
                 .mx.out.dim(shape[3], node$kernel[2], node$stride[2],
                             node$pad[2]))
    } else if (node$op == "flatten") {
      shape <- prod(shape)
    }
  }
  params
}

#' Forward pass: data NDArray -> head-input NDArray (logits for a softmax
#' head). params is the named list from mx.model.init.params.
mx.symbol.forward <- function(symbol, params, data) {
  h <- data
  for (node in mx.symbol.chain(symbol)) {
    h <- switch(node$op,
      var = h,
      fc = mx.nd.FullyConnected(
        h, params[[paste0(node$name, "_weight")]],
        if (isTRUE(node$no_bias)) NULL
        else params[[paste0(node$name, "_bias")]],
        num_hidden = node$num_hidden, no_bias = isTRUE(node$no_bias)),
      act = mx.nd.Activation(h, act_type = node$act_type),
      conv = mx.nd.Convolution(
        h, params[[paste0(node$name, "_weight")]],
        params[[paste0(node$name, "_bias")]],
        kernel = node$kernel, num_filter = node$num_filter,
        stride = node$stride, pad = node$pad),
      pool = mx.nd.Pooling(h, kernel = node$kernel,
                           pool_type = node$pool_type,
                           stride = node$stride, pad = node$pad),
      flatten = mx.nd.Flatten(h),
      softmax_output = h,   # loss/softmax applied by the trainer/predictor
      linreg_output = h,
      stop("unsupported symbol op: ", node$op))
  }
  h
}

mx.model.head <- function(symbol) {
  chain <- mx.symbol.chain(symbol)
  chain[[length(chain)]]$op
}

#' Row-subset a sample-major array of any rank (rows = samples).
.mx.take.rows <- function(X, idx) {
  d <- dim(X)
  if (is.null(d) || length(d) <= 2L) return(X[idx, , drop = FALSE])
  args <- c(list(X, idx), rep(list(quote(expr = )), length(d) - 1L),
            list(drop = FALSE))
  do.call(`[`, args)
}

#' Train a feed-forward model (reference mx.model.FeedForward.create,
#' R-package/R/model.R:470 — same user contract, imperative engine).
#'
#' X: samples along dim 1 — an n x d matrix for MLPs, or an
#' n x C x H x W array for conv nets (NCHW). y: numeric vector of 0-based
#' class ids (softmax head) or regression targets (linreg head).
#' eval.data: optional list(data = matrix/array, label = vector).
#' Returns class "MXFeedForwardModel" usable with predict().
mx.model.FeedForward.create <- function(symbol, X, y,
                                        num.round = 10,
                                        array.batch.size = 128,
                                        learning.rate = 0.01,
                                        momentum = 0,
                                        wd = 0,
                                        initializer = 0.07,
                                        eval.data = NULL,
                                        verbose = TRUE,
                                        epoch.end.callback = NULL) {
  stopifnot(is.mx.symbol(symbol), is.matrix(X) || is.array(X))
  n <- dim(X)[1]
  stopifnot(length(y) == n)
  head <- mx.model.head(symbol)
  in_shape <- dim(X)[-1]  # per-sample dims: d, or c(C, H, W)
  params <- mx.model.init.params(symbol, in_shape, initializer)
  momentum_state <- NULL
  if (momentum > 0) {
    momentum_state <- lapply(params, function(p) {
      mx.nd.zeros_like(p)
    })
  }
  for (round in seq_len(num.round)) {
    idx <- sample.int(n)
    total_loss <- 0
    nb <- 0L
    for (start in seq(1L, n, by = array.batch.size)) {
      take <- idx[start:min(start + array.batch.size - 1L, n)]
      xb <- mx.nd.array(.mx.take.rows(X, take))
      yb <- mx.nd.array(as.numeric(y[take]))
      for (p in names(params)) mx.attach.grad(params[[p]])
      mx.autograd.record()
      out <- mx.symbol.forward(symbol, params, xb)
      loss <- if (head == "softmax_output") {
        mx.nd.softmax_cross_entropy(out, yb)
      } else {
        sq <- mx.nd.square(mx.nd.broadcast_sub(
          mx.nd.reshape_like(out, yb), yb))
        mx.nd.sum(sq)
      }
      mx.autograd.end()
      mx.backward(loss)
      scale <- 1 / length(take)
      for (p in names(params)) {
        g <- mx.grad(params[[p]])
        if (momentum > 0) {
          upd <- mx.nd.sgd_mom_update(params[[p]], g, momentum_state[[p]],
                                      lr = learning.rate,
                                      momentum = momentum, wd = wd,
                                      rescale_grad = scale)
          params[[p]] <- upd[[1L]]
          momentum_state[[p]] <- upd[[2L]]
        } else {
          params[[p]] <- mx.nd.sgd_update(params[[p]], g,
                                          lr = learning.rate, wd = wd,
                                          rescale_grad = scale)
        }
      }
      total_loss <- total_loss + sum(mx.nd.to.array(loss)) / length(take)
      nb <- nb + 1L
    }
    if (verbose) {
      msg <- sprintf("Round [%d] Train-loss=%f", round, total_loss / nb)
      if (!is.null(eval.data)) {
        model_now <- structure(list(symbol = symbol, params = params),
                               class = "MXFeedForwardModel")
        acc <- mx.model.accuracy(model_now, eval.data$data, eval.data$label)
        msg <- sprintf("%s Validation-accuracy=%f", msg, acc)
      }
      cat(msg, "\n")
    }
    if (!is.null(epoch.end.callback)) epoch.end.callback(round)
  }
  structure(list(symbol = symbol, params = params),
            class = "MXFeedForwardModel")
}

#' Predict: returns the n x k probability matrix for a softmax head
#' (reference predict.MXFeedForwardModel layout, one sample per row) or
#' the raw outputs for a regression head.
predict.MXFeedForwardModel <- function(object, X, ...) {
  xb <- mx.nd.array(X)
  out <- mx.symbol.forward(object$symbol, object$params, xb)
  if (mx.model.head(object$symbol) == "softmax_output") {
    out <- mx.nd.softmax(out)
  }
  mx.nd.to.array(out)
}

mx.model.accuracy <- function(model, X, y) {
  prob <- predict(model, X)
  pred <- max.col(prob) - 1L  # 0-based class ids
  mean(pred == as.integer(y))
}

#' Save/load a trained model as a plain RDS of host arrays (the reference
#' saves .params/.json files; one artifact is the R idiom).
mx.model.save <- function(model, file) {
  host <- lapply(model$params, mx.nd.to.array)
  saveRDS(list(symbol = model$symbol, params = host), file)
}

mx.model.load <- function(file) {
  blob <- readRDS(file)
  params <- lapply(blob$params, function(a) {
    if (is.null(dim(a))) a <- array(a, dim = length(a))
    mx.nd.array(a)
  })
  structure(list(symbol = blob$symbol, params = params),
            class = "MXFeedForwardModel")
}
