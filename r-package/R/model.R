# R training frontend (reference role: R-package/R/model.R
# mx.model.FeedForward.create / predict, and R-package/R/symbol.R).
#
# Design: a "symbol" is a lightweight chain description (R lists tagged
# with class "mx.symbol") built by mx.symbol.* constructors. Training is
# imperative underneath — each batch runs forward through the generated
# mx.nd.* ops under autograd, backward through the embedded runtime, and
# updates via the framework's own fused optimizer ops (sgd_update /
# sgd_mom_update), so the R loop stays thin while all math runs on XLA
# devices. The reference instead binds a symbolic executor per batch
# shape; the imperative form is the TPU-native equivalent of the same
# user contract: symbol in, trained model out.

mx.symbol.Variable <- function(name = "data") {
  structure(list(op = "var", name = name), class = "mx.symbol")
}

mx.symbol.FullyConnected <- function(data, num_hidden, name = NULL,
                                     no_bias = FALSE) {
  structure(list(op = "fc", input = data, num_hidden = num_hidden,
                 name = name, no_bias = no_bias), class = "mx.symbol")
}

mx.symbol.Activation <- function(data, act_type = "relu", name = NULL) {
  structure(list(op = "act", input = data, act_type = act_type, name = name),
            class = "mx.symbol")
}

#' Output head: trains with softmax cross-entropy, predicts probabilities
#' (the reference SoftmaxOutput contract).
mx.symbol.SoftmaxOutput <- function(data, name = "softmax") {
  structure(list(op = "softmax_output", input = data, name = name),
            class = "mx.symbol")
}

#' Linear regression head: trains with squared error (reference
#' LinearRegressionOutput contract), predicts the raw output.
mx.symbol.LinearRegressionOutput <- function(data, name = "linreg") {
  structure(list(op = "linreg_output", input = data, name = name),
            class = "mx.symbol")
}

is.mx.symbol <- function(x) inherits(x, "mx.symbol")

#' Walk the chain root -> input, assigning default layer names (fc1, fc2,
#' ... counted from the input side, matching user expectations).
mx.symbol.chain <- function(symbol) {
  chain <- list()
  node <- symbol
  while (!is.null(node)) {
    chain[[length(chain) + 1L]] <- node
    node <- node$input
  }
  chain <- rev(chain)  # input -> output order
  counts <- list()
  for (i in seq_along(chain)) {
    node <- chain[[i]]
    if (is.null(node$name) || !nzchar(node$name)) {
      k <- node$op
      counts[[k]] <- (if (is.null(counts[[k]])) 0L else counts[[k]]) + 1L
      chain[[i]]$name <- paste0(k, counts[[k]])
    }
  }
  chain
}

#' Parameter names the symbol requires (reference arguments(symbol) role),
#' in chain order.
mx.symbol.arguments <- function(symbol) {
  args <- character(0)
  for (node in mx.symbol.chain(symbol)) {
    if (node$op == "fc") {
      args <- c(args, paste0(node$name, "_weight"))
      if (!isTRUE(node$no_bias)) args <- c(args, paste0(node$name, "_bias"))
    }
  }
  args
}

#' Initialize parameters for a symbol given the input feature count.
#' initializer: a function(shape) -> R array, or a numeric scale for
#' uniform(-scale, scale) (reference mx.init.uniform).
mx.model.init.params <- function(symbol, in_features, initializer = 0.07) {
  init_fn <- if (is.function(initializer)) {
    initializer
  } else {
    scale <- as.numeric(initializer)
    function(shape) array(stats::runif(prod(shape), -scale, scale),
                          dim = shape)
  }
  params <- list()
  features <- in_features
  for (node in mx.symbol.chain(symbol)) {
    if (node$op == "fc") {
      w <- init_fn(c(node$num_hidden, features))
      params[[paste0(node$name, "_weight")]] <- mx.nd.array(w)
      if (!isTRUE(node$no_bias)) {
        params[[paste0(node$name, "_bias")]] <-
          mx.nd.array(array(0, dim = node$num_hidden))
      }
      features <- node$num_hidden
    }
  }
  params
}

#' Forward pass: data NDArray -> head-input NDArray (logits for a softmax
#' head). params is the named list from mx.model.init.params.
mx.symbol.forward <- function(symbol, params, data) {
  h <- data
  for (node in mx.symbol.chain(symbol)) {
    h <- switch(node$op,
      var = h,
      fc = mx.nd.FullyConnected(
        h, params[[paste0(node$name, "_weight")]],
        if (isTRUE(node$no_bias)) NULL
        else params[[paste0(node$name, "_bias")]],
        num_hidden = node$num_hidden, no_bias = isTRUE(node$no_bias)),
      act = mx.nd.Activation(h, act_type = node$act_type),
      softmax_output = h,   # loss/softmax applied by the trainer/predictor
      linreg_output = h,
      stop("unsupported symbol op: ", node$op))
  }
  h
}

mx.model.head <- function(symbol) {
  chain <- mx.symbol.chain(symbol)
  chain[[length(chain)]]$op
}

#' Train a feed-forward model (reference mx.model.FeedForward.create,
#' R-package/R/model.R:470 — same user contract, imperative engine).
#'
#' X: numeric matrix, one sample per ROW (n x d). y: numeric vector of
#' 0-based class ids (softmax head) or regression targets (linreg head).
#' eval.data: optional list(data = matrix, label = vector).
#' Returns class "MXFeedForwardModel" usable with predict().
mx.model.FeedForward.create <- function(symbol, X, y,
                                        num.round = 10,
                                        array.batch.size = 128,
                                        learning.rate = 0.01,
                                        momentum = 0,
                                        wd = 0,
                                        initializer = 0.07,
                                        eval.data = NULL,
                                        verbose = TRUE,
                                        epoch.end.callback = NULL) {
  stopifnot(is.mx.symbol(symbol), is.matrix(X) || is.array(X))
  n <- nrow(X)
  stopifnot(length(y) == n)
  head <- mx.model.head(symbol)
  params <- mx.model.init.params(symbol, ncol(X), initializer)
  momentum_state <- NULL
  if (momentum > 0) {
    momentum_state <- lapply(params, function(p) {
      mx.nd.zeros_like(p)
    })
  }
  for (round in seq_len(num.round)) {
    idx <- sample.int(n)
    total_loss <- 0
    nb <- 0L
    for (start in seq(1L, n, by = array.batch.size)) {
      take <- idx[start:min(start + array.batch.size - 1L, n)]
      xb <- mx.nd.array(X[take, , drop = FALSE])
      yb <- mx.nd.array(as.numeric(y[take]))
      for (p in names(params)) mx.attach.grad(params[[p]])
      mx.autograd.record()
      out <- mx.symbol.forward(symbol, params, xb)
      loss <- if (head == "softmax_output") {
        mx.nd.softmax_cross_entropy(out, yb)
      } else {
        sq <- mx.nd.square(mx.nd.broadcast_sub(
          mx.nd.reshape_like(out, yb), yb))
        mx.nd.sum(sq)
      }
      mx.autograd.end()
      mx.backward(loss)
      scale <- 1 / length(take)
      for (p in names(params)) {
        g <- mx.grad(params[[p]])
        if (momentum > 0) {
          upd <- mx.nd.sgd_mom_update(params[[p]], g, momentum_state[[p]],
                                      lr = learning.rate,
                                      momentum = momentum, wd = wd,
                                      rescale_grad = scale)
          params[[p]] <- upd[[1L]]
          momentum_state[[p]] <- upd[[2L]]
        } else {
          params[[p]] <- mx.nd.sgd_update(params[[p]], g,
                                          lr = learning.rate, wd = wd,
                                          rescale_grad = scale)
        }
      }
      total_loss <- total_loss + sum(mx.nd.to.array(loss)) / length(take)
      nb <- nb + 1L
    }
    if (verbose) {
      msg <- sprintf("Round [%d] Train-loss=%f", round, total_loss / nb)
      if (!is.null(eval.data)) {
        model_now <- structure(list(symbol = symbol, params = params),
                               class = "MXFeedForwardModel")
        acc <- mx.model.accuracy(model_now, eval.data$data, eval.data$label)
        msg <- sprintf("%s Validation-accuracy=%f", msg, acc)
      }
      cat(msg, "\n")
    }
    if (!is.null(epoch.end.callback)) epoch.end.callback(round)
  }
  structure(list(symbol = symbol, params = params),
            class = "MXFeedForwardModel")
}

#' Predict: returns the n x k probability matrix for a softmax head
#' (reference predict.MXFeedForwardModel layout, one sample per row) or
#' the raw outputs for a regression head.
predict.MXFeedForwardModel <- function(object, X, ...) {
  xb <- mx.nd.array(X)
  out <- mx.symbol.forward(object$symbol, object$params, xb)
  if (mx.model.head(object$symbol) == "softmax_output") {
    out <- mx.nd.softmax(out)
  }
  mx.nd.to.array(out)
}

mx.model.accuracy <- function(model, X, y) {
  prob <- predict(model, X)
  pred <- max.col(prob) - 1L  # 0-based class ids
  mean(pred == as.integer(y))
}

#' Save/load a trained model as a plain RDS of host arrays (the reference
#' saves .params/.json files; one artifact is the R idiom).
mx.model.save <- function(model, file) {
  host <- lapply(model$params, mx.nd.to.array)
  saveRDS(list(symbol = model$symbol, params = host), file)
}

mx.model.load <- function(file) {
  blob <- readRDS(file)
  params <- lapply(blob$params, function(a) {
    if (is.null(dim(a))) a <- array(a, dim = length(a))
    mx.nd.array(a)
  })
  structure(list(symbol = blob$symbol, params = params),
            class = "MXFeedForwardModel")
}
